"""Cluster self-healing (ISSUE 14): the shared resumable membership
task engine + the metad PartSupervisor.

Two consumers drive part membership changes through ONE engine:

  * BALANCE DATA (cluster/balance.py) — operator-triggered, runs on the
    submitting graphd through MetaClient/StorageClient (`ClientPartOps`).
  * auto-repair — the metad leader's PartSupervisor scans host liveness
    against the part map and, when a host stays dead past
    `repair_grace_secs`, drives a raft-persisted RepairPlan through
    `LocalPartOps` (direct proposes + raw storage RPCs).

The engine's phase protocol (each phase idempotent, each adds XOR
removes — consecutive raft configurations always share a quorum):

    add      the target joins as a LEARNER (non-voting: receives
             appends/snapshot install, never counts toward quorum —
             repair can never wedge a live group).  When the part has
             already LOST its voter quorum (a dead voter of a 2-group),
             the target joins as a voter instead: a learner could never
             catch up from a leaderless group, and the single-server
             voter add is what restores electability.
    catchup  poll the target's applied index up to the leader's commit
             index (`balance_catchup_timeout_secs`, live-updatable).
    promote  learner → voter (one meta propose; the voter set grows by
             a member that already holds the log).
    remove   drop the dead/migrated replica from the part map (leader
             handed off first when it is the one leaving).

Crashing between (or inside) any two phases and re-driving from the
recorded phase converges to the same replica set: every phase checks
the current map before mutating.  Failpoint sites `repair:add_learner`,
`repair:catchup`, `repair:promote`, `repair:remove` bracket the phases;
`meta:repair_step` fires before every supervisor-driven phase.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..utils import trace as _trace
from ..utils.config import define_flag, get_config
from ..utils.failpoints import FailpointError, fail
from ..utils.stats import stats

define_flag("repair_enabled", True,
            "metad leader scans host liveness against the part map and "
            "automatically restores full replication when a storaged "
            "stays dead past repair_grace_secs (UPDATE CONFIGS "
            "repair_enabled=false is the operator kill switch; manual "
            "BALANCE DATA keeps working either way)")
define_flag("repair_grace_secs", 60.0,
            "how long a host must stay CONTINUOUSLY dead (no heartbeat "
            "past the liveness horizon) before auto-repair re-replicates "
            "its parts — the hysteresis that keeps a flapping host from "
            "thrashing data moves")
define_flag("repair_max_concurrent", 2,
            "upper bound on concurrently-driven repair plans (each plan "
            "snapshot-installs a whole part onto its target; the limit "
            "caps the catch-up bandwidth repair may take from serving)")
define_flag("repair_scan_interval_secs", 0.5,
            "PartSupervisor scan period on the metad leader")
define_flag("balance_catchup_timeout_secs", 30.0,
            "how long a membership change waits for the new replica's "
            "applied index to reach the leader's commit index before "
            "failing the task — honored by BALANCE DATA and auto-repair "
            "alike, live-updatable via UPDATE CONFIGS")

#: time_to_full_redundancy_s buckets (seconds — snapshot install +
#: catch-up of a whole part, not RPC scale)
REDUNDANCY_BUCKETS_S = (0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                        300.0, 600.0, 1800.0)

PHASES = ("add_learner", "catchup", "promote", "remove")


class MembershipError(Exception):
    pass


class _Interrupted(Exception):
    """The driving supervisor lost its mandate (deposed / stopping):
    the plan stays RUNNING so the next leader resumes it."""


def catchup_timeout_s() -> float:
    try:
        return max(float(get_config().get("balance_catchup_timeout_secs")),
                   0.1)
    except Exception:  # noqa: BLE001 — config not initialized
        return 30.0


# -- the ops surface the engine drives ---------------------------------------


class PartOps:
    """Meta mutations + storage probes for one consumer of the engine.
    Implementations: ClientPartOps (graphd/balance), LocalPartOps
    (metad supervisor)."""

    def parts_of(self, space: str) -> List[List[str]]:
        raise NotImplementedError

    def learners_of(self, space: str) -> List[List[str]]:
        raise NotImplementedError

    def set_part_replicas(self, space: str, pid: int, replicas):
        raise NotImplementedError

    def set_part_learners(self, space: str, pid: int, learners):
        raise NotImplementedError

    def promote_learner(self, space: str, pid: int, host: str):
        raise NotImplementedError

    def transfer_leader_meta(self, space: str, pid: int, to: str):
        raise NotImplementedError

    def call_host(self, addr: str, method: str, **kw) -> Any:
        raise NotImplementedError

    def reconcile(self, hosts: Iterable[str]):
        """Best-effort storage.reconcile fan-out — hosts may be dead."""
        for h in hosts:
            try:
                self.call_host(h, "storage.reconcile")
            except Exception:  # noqa: BLE001 — host may be mid-death
                pass


class ClientPartOps(PartOps):
    """BALANCE DATA's adapter: MetaClient + StorageClient."""

    def __init__(self, meta, sc):
        self.meta = meta
        self.sc = sc

    def parts_of(self, space):
        return self.meta.parts_of(space)

    def learners_of(self, space):
        return self.meta.learners_of(space)

    def set_part_replicas(self, space, pid, replicas):
        self.meta.set_part_replicas(space, pid, replicas)

    def set_part_learners(self, space, pid, learners):
        self.meta.set_part_learners(space, pid, learners)

    def promote_learner(self, space, pid, host):
        self.meta.promote_learner(space, pid, host)

    def transfer_leader_meta(self, space, pid, to):
        self.meta.transfer_leader(space, pid, to)

    def call_host(self, addr, method, **kw):
        return self.sc._client(addr).call(method, **kw)


class LocalPartOps(PartOps):
    """The metad leader's adapter: meta mutations go straight through
    the local raft group (`_propose`); storage probes use raw per-host
    RPC clients (metad holds no MetaClient of its own)."""

    def __init__(self, svc):
        self.svc = svc
        self._clients: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def parts_of(self, space):
        with self.svc.state_lock:
            pm = self.svc.state.part_map.get(space)
            if pm is None:
                raise MembershipError(f"space `{space}' not found")
            return [list(r) for r in pm]

    def learners_of(self, space):
        with self.svc.state_lock:
            if space not in self.svc.state.part_map:
                raise MembershipError(f"space `{space}' not found")
            return [list(ls) for ls in self.svc.state.learners_of(space)]

    def set_part_replicas(self, space, pid, replicas):
        self.svc._propose({"op": "set_part_replicas", "space": space,
                           "part": pid, "replicas": list(replicas)})

    def set_part_learners(self, space, pid, learners):
        self.svc._propose({"op": "set_part_learners", "space": space,
                           "part": pid, "learners": list(learners)})

    def promote_learner(self, space, pid, host):
        self.svc._propose({"op": "promote_learner", "space": space,
                           "part": pid, "host": host})

    def transfer_leader_meta(self, space, pid, to):
        self.svc._propose({"op": "transfer_leader", "space": space,
                           "part": pid, "to": to})

    def call_host(self, addr, method, **kw):
        from .rpc import RpcClient
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient.from_addr(
                    addr, timeout=10.0, retries=0)
        return c.call(method, **kw)

    def close(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass


# -- storage probes ----------------------------------------------------------


def raft_info(ops: PartOps, host: str, space: str, pid: int
              ) -> Optional[Dict]:
    try:
        return ops.call_host(host, "storage.part_raft_info",
                             space=space, part=pid)
    except Exception:  # noqa: BLE001 — host may be mid-death
        return None


def find_leader(ops: PartOps, hosts: Iterable[str], space: str,
                pid: int) -> Optional[str]:
    for h in hosts:
        info = raft_info(ops, h, space, pid)
        if info and info.get("is_leader"):
            return h
    return None


def wait_caught_up(ops: PartOps, host: str, space: str, pid: int,
                   cands: List[str],
                   timeout: Optional[float] = None):
    """Poll the new replica until its applied index reaches the
    leader's commit index as of entry.  The leader's index MUST be
    known — a transient RPC failure must not degrade the target to 0,
    or an empty replica reads as caught up and the shrink phase drops
    the only full copy.  The leader may DIE mid-catchup: re-discover
    its successor among `cands` and resume — a freshly elected
    leader's commit index covers everything the dead one committed."""
    timeout = catchup_timeout_s() if timeout is None else timeout
    dl = time.monotonic() + timeout
    # the catch-up target itself stays a candidate: raft log-
    # completeness can make the NEW replica win the post-crash
    # election, and anchoring on its own commit index is equally safe
    cur: Optional[str] = None
    target = None
    cands = list(dict.fromkeys(list(cands) + [host]))
    while target is None and time.monotonic() < dl:
        li = raft_info(ops, cur, space, pid) if cur else None
        if li is not None and li.get("is_leader", True):
            target = li["commit_index"]
            break
        # named leader dead/deposed: walk the replica set for its
        # successor (an election in flight keeps returning None — poll)
        cur = find_leader(ops, cands, space, pid)
        if cur is None:
            time.sleep(0.05)
    if target is None:
        raise MembershipError(
            f"no reachable leader for {space}/{pid}; cannot establish "
            f"a catch-up target")
    while time.monotonic() < dl:
        info = raft_info(ops, host, space, pid)
        if info and info["last_applied"] >= target:
            return
        time.sleep(0.05)
    raise MembershipError(
        f"replica {host} of {space}/{pid} did not catch up to {target} "
        f"within {timeout:g}s")


def transfer_leader_away(ops: PartOps, space: str, pid: int,
                         hosts: List[str], to: str,
                         timeout: float = 10.0) -> bool:
    """Move raft leadership of the part onto `to` (and reorder the meta
    map leader-first); False when nobody could hand it off."""
    cur = find_leader(ops, hosts, space, pid)
    if cur == to:
        ops.transfer_leader_meta(space, pid, to)
        return True
    if cur is None:
        return False
    try:
        r = ops.call_host(cur, "storage.transfer_part_leader",
                          space=space, part=pid, to=to)
    except Exception:  # noqa: BLE001
        return False
    if not (isinstance(r, dict) and r.get("ok")):
        return False        # definitive refusal — don't poll the timeout
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        info = raft_info(ops, to, space, pid)
        if info and info["is_leader"]:
            ops.transfer_leader_meta(space, pid, to)
            return True
        time.sleep(0.05)
    return False


# -- the resumable membership task engine ------------------------------------


def run_membership_change(ops: PartOps, space: str, pid: int,
                          add: Optional[str] = None,
                          remove: Optional[str] = None,
                          alive: Optional[Iterable[str]] = None,
                          start_phase: str = "add_learner",
                          on_phase: Optional[Callable[[str], None]]
                          = None):
    """Drive one part's membership change through the phase protocol.
    `alive`: hosts currently believed live (quorum-path decision +
    reconcile targets).  `start_phase` resumes a half-driven task;
    `on_phase(phase)` is called BEFORE each phase executes (the
    supervisor persists it so a crash re-drives from that phase)."""
    alive_set = set(alive) if alive is not None else None

    def is_alive(h: str) -> bool:
        return alive_set is None or h in alive_set

    try:
        phases = PHASES[PHASES.index(start_phase):]
    except ValueError:
        raise MembershipError(f"unknown phase {start_phase!r}") from None

    for phase in phases:
        if on_phase is not None:
            on_phase(phase)
        if phase == "add_learner" and add is not None:
            fail.hit("repair:add_learner", key=f"{space}/{pid}")
            voters = ops.parts_of(space)[pid]
            if add not in voters:
                live = [v for v in voters if is_alive(v)]
                learners = ops.learners_of(space)[pid]
                use_learner = 2 * len(live) > len(voters)
                if not use_learner:
                    # the liveness view may be pessimistic (post-
                    # election grace, partition): ask the group itself
                    # before resorting to the quorum-restore voter add
                    use_learner = find_leader(ops, voters, space,
                                              pid) is not None
                if use_learner:
                    # a live voter majority exists → a leader does (or
                    # will): the target joins as a LEARNER and can never
                    # wedge the group while it catches up
                    if add not in learners:
                        ops.set_part_learners(space, pid,
                                              learners + [add])
                else:
                    # quorum already lost (e.g. one dead voter of a
                    # 2-group): a learner could never catch up from a
                    # leaderless group — the single-server VOTER add is
                    # what restores electability, and it is quorum-safe
                    # (any old-config majority intersects any new one)
                    ops.set_part_replicas(space, pid,
                                          list(voters) + [add])
                ops.reconcile(sorted(set(
                    [h for h in voters if is_alive(h)] + [add])))
        elif phase == "catchup" and add is not None:
            fail.hit("repair:catchup", key=f"{space}/{pid}")
            # every voter stays a leader candidate (a dead one costs a
            # fast refused connect; a pessimistic liveness view must
            # not hide the real leader from the walk)
            cands = list(ops.parts_of(space)[pid]) + [add]
            wait_caught_up(ops, add, space, pid, cands)
        elif phase == "promote" and add is not None:
            fail.hit("repair:promote", key=f"{space}/{pid}")
            if add in ops.learners_of(space)[pid]:
                with _trace.span("raft:promote_learner", space=space,
                                 part=pid, host=add):
                    ops.promote_learner(space, pid, add)
                ops.reconcile(sorted(set(
                    [h for h in ops.parts_of(space)[pid]
                     if is_alive(h)] + [add])))
        elif phase == "remove" and remove is not None:
            fail.hit("repair:remove", key=f"{space}/{pid}")
            voters = ops.parts_of(space)[pid]
            learners = ops.learners_of(space)[pid]
            if remove in learners:
                ops.set_part_learners(
                    space, pid, [l for l in learners if l != remove])
            if remove in voters:
                keep = [h for h in voters if h != remove]
                if not keep:
                    raise MembershipError(
                        f"refusing to drop the only replica of "
                        f"{space}/{pid}")
                live_keep = [h for h in keep if is_alive(h)] or keep
                leader = find_leader(ops, live_keep, space, pid)
                if leader is None and is_alive(remove):
                    # the leaving replica may still lead: hand off
                    # before the map drops it
                    if not transfer_leader_away(ops, space, pid, voters,
                                                live_keep[0]):
                        raise MembershipError(
                            f"cannot move leadership of {space}/{pid} "
                            f"into the surviving set {keep}")
                    leader = live_keep[0]
                ordered = ([leader] if leader else []) + \
                    [h for h in keep if h != leader]
                ops.set_part_replicas(space, pid, ordered)
                # reconcile the survivors AND the removed host (so it
                # stops its raft member and releases the part state)
                ops.reconcile(sorted(set(live_keep + [remove])))
    return True


# -- the metad-leader supervisor ---------------------------------------------


class PartSupervisor:
    """Scans host liveness × part map on the metad LEADER; when a host
    stays dead past `repair_grace_secs`, creates a raft-persisted
    RepairPlan per under-replicated part and drives it through the
    membership engine.  Plans resume across metad restarts and leader
    failovers: the phase lives in replicated state, every phase is
    idempotent, and a fresh leader's supervisor picks up any RUNNING
    plan it is not already driving."""

    def __init__(self, svc):
        self.svc = svc
        self.ops = LocalPartOps(svc)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._driving: Dict[int, threading.Thread] = {}
        self._mu = threading.Lock()
        # (space, pid) → monotonic not-before for a NEW plan after a
        # failed one (leader-local; a failed plan must not hot-loop)
        self._retry_at: Dict[Tuple[str, int], float] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"part-supervisor-{self.svc.my_addr}")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        with self._mu:
            drivers = list(self._driving.values())
        for t in drivers:
            t.join(timeout=2)
        self.ops.close()

    def _interval_s(self) -> float:
        try:
            return max(float(get_config().get(
                "repair_scan_interval_secs")), 0.05)
        except Exception:  # noqa: BLE001
            return 0.5

    def _loop(self):
        while not self._stop.wait(self._interval_s()):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep the supervisor alive
                pass

    # -- one scan ---------------------------------------------------------

    def _tick(self):
        svc = self.svc
        if not svc.raft.is_leader():
            # refresh the leadership streak bookkeeping (a later
            # re-election starts a fresh liveness grace) and drop
            # leader-local retry state
            svc._liveness_anchor()
            self._retry_at.clear()
            return
        anchor = svc._liveness_anchor()
        if anchor is None or time.monotonic() < anchor:
            # post-election liveness grace: this leader's view of who
            # is alive is not authoritative yet — neither NEW repairs
            # nor resumed plans may act on it (a resumed plan driven
            # against an all-UNKNOWN view would mis-pick the quorum-
            # restore path)
            return
        liveness = svc.host_liveness()
        with svc.state_lock:
            spaces = {sp: [list(r) for r in pm]
                      for sp, pm in svc.state.part_map.items()}
            learner_maps = {sp: [list(ls) for ls in
                                 svc.state.learners_of(sp)]
                            for sp in spaces}
            repairs = {k: dict(v) for k, v in svc.state.repairs.items()}
            rfs = {sp: svc.state.catalog.spaces[sp].replica_factor
                   for sp in spaces if sp in svc.state.catalog.spaces}
        try:
            grace = max(float(get_config().get("repair_grace_secs")), 0.0)
        except Exception:  # noqa: BLE001
            grace = 60.0
        try:
            enabled = bool(get_config().get("repair_enabled"))
        except Exception:  # noqa: BLE001
            enabled = True
        try:
            max_conc = max(int(get_config().get("repair_max_concurrent")),
                           1)
        except Exception:  # noqa: BLE001
            max_conc = 2

        def status_of(h: str) -> str:
            return liveness.get(h, {}).get("status", "OFFLINE") \
                if h in liveness else "OFFLINE"

        active_keys = {(r["space"], r["part"])
                       for r in repairs.values()
                       if r["status"] == "RUNNING"}
        under = 0
        ripe: List[Tuple[str, int, str]] = []
        now = time.monotonic()
        for sp, pm in spaces.items():
            for pid, reps in enumerate(pm):
                # janitor: a learner on a host dead past the grace is
                # useless (its catch-up can never finish) and would
                # block DROP HOSTS — clear it when no plan owns the part
                stale_l = [l for l in learner_maps[sp][pid]
                           if status_of(l) == "OFFLINE"
                           and liveness.get(l, {}).get("dead_for",
                                                       0.0) >= grace]
                if stale_l and (sp, pid) not in active_keys:
                    try:
                        self.ops.set_part_learners(
                            sp, pid, [l for l in learner_maps[sp][pid]
                                      if l not in stale_l])
                    except Exception:  # noqa: BLE001 — deposed mid-tick
                        return
                dead = [r for r in reps if status_of(r) == "OFFLINE"]
                if not dead:
                    continue
                under += 1
                nb = self._retry_at.get((sp, pid), 0.0)
                if (sp, pid) in active_keys or now < nb:
                    continue
                # hysteresis: the host must have been CONTINUOUSLY dead
                # for the whole grace (a heartbeat resets dead_for)
                past_grace = [r for r in dead
                              if liveness.get(r, {}).get("dead_for",
                                                         0.0) >= grace]
                if past_grace:
                    ripe.append((sp, pid, past_grace[0]))
        stats().gauge("under_replicated_parts", under)

        with self._mu:
            self._driving = {rid: t for rid, t in self._driving.items()
                             if t.is_alive()}
            running = len(self._driving)
            # resume persisted RUNNING plans this leader is not driving
            # (metad restart / leader failover mid-plan) — unless the
            # kill switch is off: a disabled repair plane must not move
            # data, resumed plans included; they stay RUNNING and pick
            # up from their recorded phase when re-enabled
            if enabled:
                for rid, r in sorted(repairs.items()):
                    if running >= max_conc:
                        break
                    if r["status"] != "RUNNING" or rid in self._driving:
                        continue
                    self._spawn(rid, r)
                    running += 1
        if not enabled:
            stats().gauge("repair_tasks_running", running)
            return
        for sp, pid, dead in ripe:
            with self._mu:
                if len(self._driving) >= max_conc:
                    break
            # a part whose LIVE members already satisfy rf (e.g. a
            # crashed task added the target as voter but died before
            # dropping the dead one) needs only the remove leg
            live_members = [r for r in spaces[sp][pid]
                            if liveness.get(r, {}).get("status")
                            == "ONLINE"]
            if len(live_members) >= rfs.get(sp, len(spaces[sp][pid])):
                target = None
            else:
                target = self._pick_target(sp, pid, spaces,
                                           learner_maps, liveness)
                if target is None:
                    continue    # no spare healthy host: stay degraded
            try:
                rid = self.svc._propose({
                    "op": "add_repair", "space": sp, "part": pid,
                    "dead": dead, "target": target, "ts": time.time()})
            except Exception:  # noqa: BLE001 — lost leadership mid-propose
                return
            plan = {"space": sp, "part": pid, "dead": dead,
                    "target": target, "phase": "add_learner",
                    "status": "RUNNING", "created": time.time()}
            with self._mu:
                self._spawn(rid, plan)
        with self._mu:
            stats().gauge("repair_tasks_running",
                          sum(1 for t in self._driving.values()
                              if t.is_alive()))

    def _pick_target(self, space: str, pid: int, spaces, learner_maps,
                     liveness) -> Optional[str]:
        """Best healthy host for the part's new replica: not already a
        member, in a zone the part does not cover when possible, then
        fewest hosted parts (count across spaces, learners included)."""
        reps = spaces[space][pid]
        learners = learner_maps[space][pid]
        # retry affinity: a LIVE learner left behind by a failed or
        # crashed task already holds (part of) the data — finishing its
        # promotion beats starting a fresh copy elsewhere, and keeps
        # retries from stranding learners
        for l in learners:
            if liveness.get(l, {}).get("status") == "ONLINE" \
                    and l not in reps:
                return l
        cands = [h for h, info in liveness.items()
                 if info.get("role") == "storage"
                 and info.get("status") == "ONLINE"
                 and h not in reps and h not in learners]
        if not cands:
            return None
        with self.svc.state_lock:
            zones = {z: list(hs)
                     for z, hs in self.svc.state.zones.items()}
        host_zone: Dict[str, str] = {}
        for z, hs in zones.items():
            for h in hs:
                host_zone[h] = z
        for h in list(liveness):
            host_zone.setdefault(h, f"__host_{h}")
        covered = {host_zone.get(h) for h in reps
                   if liveness.get(h, {}).get("status") == "ONLINE"}
        uncovered = [h for h in cands if host_zone.get(h) not in covered]
        if uncovered:
            cands = uncovered
        load: Dict[str, int] = {h: 0 for h in cands}
        for sp, pm in spaces.items():
            for reps2 in pm:
                for r in reps2:
                    if r in load:
                        load[r] += 1
            for ls in learner_maps[sp]:
                for l in ls:
                    if l in load:
                        load[l] += 1
        return min(sorted(cands), key=lambda h: load[h])

    # -- plan driving -----------------------------------------------------

    def _spawn(self, rid: int, plan: Dict[str, Any]):
        t = threading.Thread(target=self._drive, args=(rid, dict(plan)),
                             daemon=True, name=f"repair-{rid}")
        self._driving[rid] = t
        t.start()

    def _update(self, rid: int, **fields):
        fields.setdefault("updated", time.time())
        self.svc._propose({"op": "update_repair", "rid": rid,
                           "fields": fields})

    def _drive(self, rid: int, plan: Dict[str, Any]):
        svc = self.svc
        sp, pid = plan["space"], plan["part"]

        def on_phase(phase: str):
            if self._stop.is_set() or not svc.raft.is_leader():
                raise _Interrupted
            try:
                if not bool(get_config().get("repair_enabled")):
                    # kill switch flipped mid-plan: stop at the next
                    # phase boundary, leave the plan RUNNING — it
                    # resumes from this phase when re-enabled
                    raise _Interrupted
            except _Interrupted:
                raise
            except Exception:  # noqa: BLE001 — config not initialized
                pass
            fail.hit("meta:repair_step", key=f"{sp}/{pid}|{phase}")
            with _trace.span("meta:repair_step", rid=rid, space=sp,
                             part=pid, phase=phase):
                if plan.get("phase") != phase:
                    self._update(rid, phase=phase)
                    plan["phase"] = phase

        try:
            alive = [h for h, info in svc.host_liveness().items()
                     if info.get("status") == "ONLINE"]
            run_membership_change(
                self.ops, sp, pid, add=plan["target"],
                remove=plan["dead"], alive=alive,
                start_phase=plan.get("phase", "add_learner"),
                on_phase=on_phase)
            self._update(rid, status="DONE", phase="done")
            stats().inc("repair_tasks_done")
            created = float(plan.get("created") or 0.0)
            if created:
                stats().observe("time_to_full_redundancy_s",
                                max(time.time() - created, 0.0),
                                buckets=REDUNDANCY_BUCKETS_S)
        except (FailpointError, _Interrupted):
            # an armed repair:* / meta:repair_step fault, or this
            # supervisor losing its mandate mid-plan: treat like a
            # crash — the plan stays RUNNING and the (possibly new)
            # leader's supervisor re-drives it from the recorded phase
            pass
        except Exception as ex:  # noqa: BLE001 — plan outcome recorded
            self._retry_at[(sp, pid)] = time.monotonic() + \
                max(2.0, 2.0 * self._interval_s())
            if svc.raft.is_leader():
                try:
                    self._update(rid, status="FAILED", error=str(ex))
                    stats().inc("repair_tasks_failed")
                except Exception:  # noqa: BLE001 — deposed mid-update
                    pass
            # deposed: leave RUNNING — the new leader resumes it
        finally:
            with self._mu:
                self._driving.pop(rid, None)
