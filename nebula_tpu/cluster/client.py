"""GraphClient — what drivers/console use to talk to a graphd.

The nebula-python analog: authenticate once, then execute statements,
receiving ResultSet-shaped replies (wire-decoded DataSet).

Bulk results arrive columnar (ISSUE 2): numeric result columns ride
the RPC frame as typed blobs and decode into a lazy ColumnarDataSet —
`rs.data.column_array(name)` is the numpy column straight off the
wire buffer; per-row Python lists are built only if `.rows` is
touched.  Int columns may arrive TRANSPORT-NARROWED (int8/16/32 when
the value range fits — value-exact, `.rows`/`column()` still yield
plain Python ints); cast with `np.asarray(col, np.int64)` before
doing overflow-prone numpy arithmetic on the raw column.
"""
from __future__ import annotations

import random
import time
from typing import Optional

from ..core.wire import from_wire
from ..exec.context import ResultSet
from ..utils.config import get_config
from .rpc import RpcClient, RpcConnError, RpcError

#: how much longer the client waits than the server's statement budget:
#: graphd's own deadline (query_timeout_secs, ISSUE 5) should expire
#: FIRST and return a proper E_QUERY_TIMEOUT reply — the client-side
#: cutoff only catches a graphd that stopped answering entirely
CLIENT_TIMEOUT_GRACE_S = 10.0


def _statement_timeout() -> float:
    """The configured statement timeout (0/unset → legacy 300s)."""
    try:
        t = float(get_config().get("query_timeout_secs"))
    except Exception:  # noqa: BLE001 — config not initialized
        t = 0.0
    return t if t > 0 else 300.0


class GraphClient:
    def __init__(self, host: str, port: int,
                 timeout: Optional[float] = None):
        # retries=0: a statement may be non-idempotent; re-sending after a
        # dropped reply could execute it twice (at-least-once hazard)
        self.timeout = (timeout if timeout is not None
                        else _statement_timeout() + CLIENT_TIMEOUT_GRACE_S)
        self.rpc = RpcClient(host, port, timeout=self.timeout, retries=0)
        self.session_id: Optional[int] = None

    def authenticate(self, user: str = "root", password: str = "nebula"):
        r = self.rpc.call("graph.authenticate", user=user, password=password)
        self.session_id = r["session_id"]
        return self.session_id

    def execute(self, stmt: str) -> ResultSet:
        """Execute one statement.  An E_OVERLOAD shed (graphd admission
        queue full, or the daemon's RPC inbox bounded out) is retried
        honoring its retry-after hint, but only within the statement's
        remaining deadline budget (ISSUE 10 satellite): the client
        never turns bounded shedding into an unbounded retry storm.
        When the budget is spent the overload comes back STRUCTURED —
        `rs.error` keeps the full E_OVERLOAD text and
        `rs.retry_after_ms` carries the parsed hint."""
        if self.session_id is None:
            raise RpcError("not authenticated")
        from ..utils.admission import is_overload, parse_retry_after
        deadline = time.monotonic() + _statement_timeout()
        while True:
            err: Optional[str] = None
            try:
                r = self.rpc.call("graph.execute",
                                  session_id=self.session_id, stmt=stmt)
            except RpcError as ex:
                # the daemon's bounded RPC inbox shed the request (the
                # handler provably never ran) — same structured surface
                # as an admission-level shed, not a raw transport error
                if not is_overload(str(ex)):
                    raise
                err = str(ex)
            except RpcConnError as ex:
                if "rpc timeout" in str(ex):
                    # the statement outlived even the grace window
                    # (graphd wedged / unreachable mid-statement): a
                    # clean timeout result, not a raw transport
                    # traceback (ISSUE 5 satellite).  NOTE the
                    # statement may still be running — same contract
                    # as any client-side cancel.
                    return ResultSet(
                        error=f"E_QUERY_TIMEOUT: no reply within "
                              f"{self.timeout:g}s (statement budget "
                              f"{_statement_timeout():g}s + grace)")
                raise
            if err is None:
                if not is_overload(r["error"]):
                    data = from_wire(r["data"]) \
                        if r["data"] is not None else None
                    return ResultSet(data=data, space=r["space"],
                                     latency_us=r["latency_us"],
                                     plan_desc=r["plan_desc"],
                                     error=r["error"])
                err = r["error"]
            hint = parse_retry_after(err)
            # jittered hint: clients shed in the same burst get the
            # same retry_after_ms — sleeping it verbatim re-arrives
            # the herd in one pulse and re-sheds most of it
            hint_s = (hint if hint is not None else 0.25) \
                * random.uniform(0.5, 1.5)
            if time.monotonic() + hint_s >= deadline:
                # budget exhausted: hand the structured overload back
                rs = ResultSet(error=err)
                if hint is not None:
                    rs.retry_after_ms = int(hint * 1000)
                return rs
            time.sleep(hint_s)

    def signout(self):
        if self.session_id is not None:
            self.rpc.call("graph.signout", session_id=self.session_id)
            self.session_id = None

    def close(self):
        try:
            self.signout()
        finally:
            self.rpc.close()
