"""GraphClient — what drivers/console use to talk to a graphd.

The nebula-python analog: authenticate once, then execute statements,
receiving ResultSet-shaped replies (wire-decoded DataSet).

Bulk results arrive columnar (ISSUE 2): numeric result columns ride
the RPC frame as typed blobs and decode into a lazy ColumnarDataSet —
`rs.data.column_array(name)` is the numpy column straight off the
wire buffer; per-row Python lists are built only if `.rows` is
touched.  Int columns may arrive TRANSPORT-NARROWED (int8/16/32 when
the value range fits — value-exact, `.rows`/`column()` still yield
plain Python ints); cast with `np.asarray(col, np.int64)` before
doing overflow-prone numpy arithmetic on the raw column.
"""
from __future__ import annotations

from typing import Optional

from ..core.wire import from_wire
from ..exec.context import ResultSet
from .rpc import RpcClient, RpcError


class GraphClient:
    def __init__(self, host: str, port: int):
        # retries=0: a statement may be non-idempotent; re-sending after a
        # dropped reply could execute it twice (at-least-once hazard)
        self.rpc = RpcClient(host, port, timeout=300.0, retries=0)
        self.session_id: Optional[int] = None

    def authenticate(self, user: str = "root", password: str = "nebula"):
        r = self.rpc.call("graph.authenticate", user=user, password=password)
        self.session_id = r["session_id"]
        return self.session_id

    def execute(self, stmt: str) -> ResultSet:
        if self.session_id is None:
            raise RpcError("not authenticated")
        r = self.rpc.call("graph.execute", session_id=self.session_id,
                          stmt=stmt)
        data = from_wire(r["data"]) if r["data"] is not None else None
        return ResultSet(data=data, space=r["space"],
                         latency_us=r["latency_us"],
                         plan_desc=r["plan_desc"], error=r["error"])

    def signout(self):
        if self.session_id is not None:
            self.rpc.call("graph.signout", session_id=self.session_id)
            self.session_id = None

    def close(self):
        try:
            self.signout()
        finally:
            self.rpc.close()
