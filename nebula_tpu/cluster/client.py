"""GraphClient — what drivers/console use to talk to a graphd fleet.

The nebula-python analog: authenticate once, then execute statements,
receiving ResultSet-shaped replies (wire-decoded DataSet).

Fleet mode (ISSUE 20): construct with N graphd endpoints —
`GraphClient(["h:p", "h:p", ...])` — and the client ranks them with
the same per-peer score machinery the storage client uses for replica
routing (latency EWMA + retry-after penalty + breaker state), then
fails over transparently when a coordinator dies or drains:

  - `E_SESSION_MOVED` (graceful drain): the statement was refused
    BEFORE execution, so ANY statement — including writes — retries
    safely on the sibling named in the hint.
  - connection death mid-statement: the outcome is unknown.  Only
    read-shaped statements are retried on a sibling; a write comes
    back as a structured `E_COORDINATOR_LOST` result — the client
    NEVER silently re-sends a statement that may have executed.
  - every retry is clamped to the statement's deadline budget
    (ISSUE 5): failover never turns into an unbounded retry storm.

The session itself survives the owner: its row is metad-replicated,
and `graph.adopt_session` re-homes it (credentials re-checked; $var
state was owner-local and is lost — docs/ROBUSTNESS.md §10).

Bulk results arrive columnar (ISSUE 2): numeric result columns ride
the RPC frame as typed blobs and decode into a lazy ColumnarDataSet —
`rs.data.column_array(name)` is the numpy column straight off the
wire buffer; per-row Python lists are built only if `.rows` is
touched.  Int columns may arrive TRANSPORT-NARROWED (int8/16/32 when
the value range fits — value-exact, `.rows`/`column()` still yield
plain Python ints); cast with `np.asarray(col, np.int64)` before
doing overflow-prone numpy arithmetic on the raw column.
"""
from __future__ import annotations

import random
import re
import time
from typing import Dict, List, Optional, Sequence, Union

from ..core.wire import from_wire
from ..exec.context import ResultSet
from ..utils.config import get_config
from .rpc import RpcClient, RpcConnError, RpcError, RpcNeverSentError

#: how much longer the client waits than the server's statement budget:
#: graphd's own deadline (query_timeout_secs, ISSUE 5) should expire
#: FIRST and return a proper E_QUERY_TIMEOUT reply — the client-side
#: cutoff only catches a graphd that stopped answering entirely
CLIENT_TIMEOUT_GRACE_S = 10.0

SESSION_MOVED = "E_SESSION_MOVED"
_SIBLING_RE = re.compile(r"sibling=([^\s;,]+)")

#: leading keywords whose statements are safe to re-send when the
#: outcome of the first send is UNKNOWN (connection died mid-call):
#: pure reads / metadata — re-execution cannot double-apply anything.
#: Deliberately conservative: EXPLAIN/PROFILE run their statement.
_RETRYABLE_LEAD = frozenset({
    "GO", "MATCH", "FETCH", "LOOKUP", "FIND", "SHOW",
    "DESCRIBE", "DESC", "USE", "YIELD",
})


def _statement_timeout() -> float:
    """The configured statement timeout (0/unset → legacy 300s)."""
    try:
        t = float(get_config().get("query_timeout_secs"))
    except Exception:  # noqa: BLE001 — config not initialized
        t = 0.0
    return t if t > 0 else 300.0


def _stmt_retryable(stmt: str) -> bool:
    m = re.match(r"[\s(]*([A-Za-z]+)", stmt)
    return bool(m) and m.group(1).upper() in _RETRYABLE_LEAD


class GraphClient:
    def __init__(self, host: Union[str, Sequence[str]],
                 port: Optional[int] = None,
                 timeout: Optional[float] = None):
        # retries=0: a statement may be non-idempotent; re-sending after a
        # dropped reply could execute it twice (at-least-once hazard)
        if isinstance(host, (list, tuple)):
            endpoints = [str(h) for h in host]
        elif port is not None:
            endpoints = [f"{host}:{port}"]
        else:
            endpoints = [h.strip() for h in str(host).split(",") if h.strip()]
        if not endpoints:
            raise ValueError("no graphd endpoints")
        self.endpoints: List[str] = endpoints
        self.timeout = (timeout if timeout is not None
                        else _statement_timeout() + CLIENT_TIMEOUT_GRACE_S)
        self._rpcs: Dict[str, RpcClient] = {}
        self.addr = endpoints[0]
        self.session_id: Optional[int] = None
        self._user = "root"
        self._password = "nebula"
        # endpoints that have already adopted the CURRENT session — an
        # overload walk between them needs no adopt round-trip (the
        # session object survives on every coordinator that held it)
        self._adopted: set = set()

    # -- endpoint plumbing ------------------------------------------------

    def _rpc_for(self, addr: str) -> RpcClient:
        c = self._rpcs.get(addr)
        if c is None:
            host, port = addr.rsplit(":", 1)
            c = self._rpcs[addr] = RpcClient(host, int(port),
                                             timeout=self.timeout, retries=0)
        return c

    @property
    def rpc(self) -> RpcClient:
        """The current coordinator's RPC client (legacy single-endpoint
        attribute — code that pokes `client.rpc` keeps working)."""
        return self._rpc_for(self.addr)

    def _ranked(self, exclude=()) -> List[str]:
        """Sibling endpoints best-first by the shared per-peer score
        (latency EWMA + overload penalty + breaker state — the PR 9
        replica-routing machinery, reused verbatim)."""
        from .storage_client import peer_score
        cands = [e for e in self.endpoints
                 if e != self.addr and e not in exclude]
        cands.sort(key=peer_score)
        return cands

    def _failover(self, hint: Optional[str] = None, exclude=(),
                  count: bool = True) -> bool:
        """Re-home on a sibling: adopt the session there (credentials
        re-checked server-side), then make it the current coordinator.
        The drain hint goes first — the dying graphd knows who is
        alive; score order covers the hint-less crash case.
        `count=False` for capacity walks (an overload shed is not a
        coordinator failure — `coordinator_failovers` must keep meaning
        crashes and drains)."""
        order = self._ranked(exclude=exclude)
        if hint and hint != "-" and hint != self.addr:
            if hint in order:
                order.remove(hint)
            order.insert(0, hint)
        for ep in order:
            try:
                if self.session_id is not None \
                        and ep not in self._adopted:
                    self._rpc_for(ep).call(
                        "graph.adopt_session", session_id=self.session_id,
                        user=self._user, password=self._password)
                    self._adopted.add(ep)
                self.addr = ep
                if count:
                    from ..utils.stats import stats
                    stats().inc("coordinator_failovers")
                return True
            except (RpcError, RpcConnError):
                continue
        return False

    # -- session ----------------------------------------------------------

    def authenticate(self, user: str = "root", password: str = "nebula"):
        self._user, self._password = user, password
        last: Optional[Exception] = None
        for ep in [self.addr] + self._ranked():
            try:
                r = self._rpc_for(ep).call("graph.authenticate",
                                           user=user, password=password)
                self.addr = ep
                self.session_id = r["session_id"]
                self._adopted = {ep}
                return self.session_id
            except RpcConnError as ex:
                last = ex
            except RpcError as ex:
                # a draining graphd refuses new sessions — walk on;
                # anything else (bad password) is terminal
                if SESSION_MOVED not in str(ex):
                    raise
                last = ex
        raise last if last is not None else RpcError("no graphd reachable")

    # -- execute ----------------------------------------------------------

    def execute(self, stmt: str) -> ResultSet:
        """Execute one statement.  An E_OVERLOAD shed (graphd admission
        queue full, or the daemon's RPC inbox bounded out) is retried
        honoring its retry-after hint, but only within the statement's
        remaining deadline budget (ISSUE 10 satellite): the client
        never turns bounded shedding into an unbounded retry storm.
        When the budget is spent the overload comes back STRUCTURED —
        `rs.error` keeps the full E_OVERLOAD text and
        `rs.retry_after_ms` carries the parsed hint.

        Coordinator loss is handled per the fleet contract (module
        docstring): drain refusals retry anywhere, unknown-outcome
        losses retry only read-shaped statements, all inside the same
        deadline budget."""
        if self.session_id is None:
            raise RpcError("not authenticated")
        from ..utils.admission import is_overload, parse_retry_after
        from ..utils.stats import stats
        deadline = time.monotonic() + _statement_timeout()
        lost: set = set()
        while True:
            err: Optional[str] = None
            t0 = time.perf_counter()
            try:
                r = self._rpc_for(self.addr).call(
                    "graph.execute", session_id=self.session_id, stmt=stmt)
            except RpcError as ex:
                if SESSION_MOVED in str(ex):
                    # refused BEFORE execution (graceful drain): any
                    # statement retries safely on the named sibling
                    stats().inc("session_moves")
                    m = _SIBLING_RE.search(str(ex))
                    if time.monotonic() < deadline and self._failover(
                            hint=m.group(1) if m else None, exclude=lost):
                        continue
                    return ResultSet(error=str(ex))
                # the daemon's bounded RPC inbox shed the request (the
                # handler provably never ran) — same structured surface
                # as an admission-level shed, not a raw transport error
                if not is_overload(str(ex)):
                    raise
                err = str(ex)
            except RpcConnError as ex:
                if "rpc timeout" in str(ex):
                    # the statement outlived even the grace window
                    # (graphd wedged / unreachable mid-statement): a
                    # clean timeout result, not a raw transport
                    # traceback (ISSUE 5 satellite).  NOTE the
                    # statement may still be running — same contract
                    # as any client-side cancel.
                    return ResultSet(
                        error=f"E_QUERY_TIMEOUT: no reply within "
                              f"{self.timeout:g}s (statement budget "
                              f"{_statement_timeout():g}s + grace)")
                if len(self.endpoints) <= 1:
                    raise
                dead = self.addr
                lost.add(dead)
                # never-sent failures are provably side-effect free —
                # any statement may retry; otherwise only read-shaped
                # statements are safe to re-send
                safe = isinstance(ex, RpcNeverSentError) \
                    or _stmt_retryable(stmt)
                moved = time.monotonic() < deadline \
                    and self._failover(exclude=lost)
                if moved and safe:
                    continue
                if safe:
                    raise
                return ResultSet(
                    error=f"E_COORDINATOR_LOST: connection to {dead} "
                          f"died mid-statement; outcome unknown — not "
                          f"retried (non-idempotent statement)"
                          + ("" if moved else "; no sibling reachable"))
            if err is None:
                from .storage_client import note_peer_latency
                note_peer_latency(self.addr, time.perf_counter() - t0)
                if not is_overload(r["error"]):
                    data = from_wire(r["data"]) \
                        if r["data"] is not None else None
                    return ResultSet(data=data, space=r["space"],
                                     latency_us=r["latency_us"],
                                     plan_desc=r["plan_desc"],
                                     error=r["error"])
                err = r["error"]
            hint = parse_retry_after(err)
            from .storage_client import note_peer_overload
            note_peer_overload(self.addr, hint)
            # jittered hint: clients shed in the same burst get the
            # same retry_after_ms — sleeping it verbatim re-arrives
            # the herd in one pulse and re-sheds most of it
            hint_s = (hint if hint is not None else 0.25) \
                * random.uniform(0.5, 1.5)
            if len(self.endpoints) > 1 and time.monotonic() < deadline \
                    and self._failover(exclude=lost, count=False):
                # fleet capacity walk: the shed priced THIS
                # coordinator's bucket — a sibling may have spare
                # tokens RIGHT NOW (the coordinator analog of the
                # follower-read capacity walk; note_peer_overload
                # above already penalized the shedder's score).  The
                # short pause bounds the spin when EVERY coordinator
                # is saturated.  Single-endpoint behavior unchanged.
                time.sleep(min(hint_s, 0.02))
                continue
            if time.monotonic() + hint_s >= deadline:
                # budget exhausted: hand the structured overload back
                rs = ResultSet(error=err)
                if hint is not None:
                    rs.retry_after_ms = int(hint * 1000)
                return rs
            time.sleep(hint_s)

    def signout(self):
        if self.session_id is not None:
            self.rpc.call("graph.signout", session_id=self.session_id)
            self.session_id = None

    def close(self):
        try:
            self.signout()
        except (RpcError, RpcConnError):
            pass  # the coordinator may be gone — closing is best-effort
        finally:
            for c in self._rpcs.values():
                c.close()
