"""Raft consensus — the replication layer of the host (control) plane.

Analog of the reference's raftex (RaftPart / Host / leader election /
log replication / snapshot transfer; reference: src/kvstore/raftex
[UNVERIFIED — empty mount, SURVEY §0]).  Correctness-grade Python per
SURVEY §2c: replication is not on the TPU hot path — metad catalog and
the storage write path ride it, reads are served from leader state.

One RaftPart per (space, partition) — or one for the whole meta store.
Pluggable transport: LoopbackTransport for in-proc multi-node tests
(with fault-injection hooks: drop/partition/delay, SURVEY §5), RPC
transport for real deployments.
"""
from __future__ import annotations

import base64
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import trace as _trace
from ..utils.config import define_flag, get_config
from ..utils.failpoints import FailpointError, fail
from .wal import Wal

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

define_flag("raft_max_batch", 64,
            "max entries per append_entries round (also the per-round "
            "unit of transfer_leadership catch-up); the group-commit "
            "replication batch ceiling")

define_flag("raft_lease_margin_ms", 25.0,
            "clock-skew safety margin subtracted from the minimum "
            "election timeout when judging the leader lease: a lease "
            "read is only served while a majority acked within "
            "(min_election_timeout - margin).  A margin >= the "
            "election timeout disables the lease fast path entirely "
            "(every read-index falls back to a quorum round)")

# raft_commit_latency_ms buckets (milliseconds — consensus rounds, not
# the µs RPC scale of LATENCY_BUCKETS_US)
COMMIT_LATENCY_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                             100.0, 250.0, 500.0, 1_000.0, 5_000.0)
# raft_replication_batch_size buckets (entries per append_entries round)
REPL_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                      256.0, 512.0, 1_024.0)


class RaftTransport:
    """send() returns the peer's reply dict, or None on failure."""

    def send(self, peer: str, group: str, method: str,
             payload: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


class LoopbackTransport(RaftTransport):
    """In-process transport — multi-'node' raft in one process, with
    fault injection (the reference tests raft the same way: multiple
    RaftPart instances over local thrift)."""

    def __init__(self):
        self.parts: Dict[Tuple[str, str], "RaftPart"] = {}
        self.dropped: set = set()        # (src, dst) pairs that drop
        self.delay_s = 0.0
        self.lock = threading.Lock()

    def register(self, part: "RaftPart"):
        with self.lock:
            self.parts[(part.node_id, part.group)] = part

    def partition(self, a: str, b: str):
        """Cut both directions between nodes a and b."""
        self.dropped.add((a, b))
        self.dropped.add((b, a))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None):
        if a is None:
            self.dropped.clear()
        else:
            self.dropped.discard((a, b))
            self.dropped.discard((b, a))

    def send(self, peer, group, method, payload):
        src = payload.get("_from", "")
        if (src, peer) in self.dropped:
            return None
        if self.delay_s:
            time.sleep(self.delay_s)
        with self.lock:
            part = self.parts.get((peer, group))
        if part is None or not part.alive:
            return None
        return part.handle(method, payload)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class RaftPart:
    """One consensus group member.

    apply_cb(index, data: bytes) is invoked in commit order exactly once
    per entry per process lifetime (replays from WAL on restart unless a
    snapshot covers them).
    snapshot_cb() -> bytes / restore_cb(bytes) enable log compaction and
    laggard catch-up.
    """

    def __init__(self, group: str, node_id: str, peers: List[str],
                 transport: RaftTransport, wal_dir: str,
                 apply_cb: Callable[[int, bytes], None],
                 snapshot_cb: Optional[Callable[[], bytes]] = None,
                 restore_cb: Optional[Callable[[bytes], None]] = None,
                 election_timeout: Tuple[float, float] = (0.15, 0.30),
                 heartbeat_interval: float = 0.05,
                 snapshot_threshold: int = 10_000,
                 wal_sync: bool = True,
                 learners: Optional[List[str]] = None):
        self.group = group
        self.node_id = node_id
        # voting members ONLY — quorum math (elections, commit advance,
        # lease) runs over `peers`; learners ride replication but never
        # count (ISSUE 14: repair can never wedge a live group)
        self.peers = [p for p in peers if p != node_id]
        # learner (non-voting) replicas: receive append_entries and
        # snapshot install like followers, but are invisible to every
        # quorum computation and never campaign or grant votes until
        # promoted (update_peers moves them into the voter set)
        self.learners = [l for l in (learners or []) if l not in self.peers]
        self.transport = transport
        self.apply_cb = apply_cb
        self.snapshot_cb = snapshot_cb
        self.restore_cb = restore_cb
        self.eto = election_timeout
        self.hb = heartbeat_interval
        self.snapshot_threshold = snapshot_threshold

        os.makedirs(wal_dir, exist_ok=True)
        # sync=True: an acked append must survive power loss — commit
        # durability depends on a majority of fsynced logs
        self.wal = Wal(os.path.join(wal_dir, f"{group}.wal"),
                       sync=wal_sync)
        self._meta_path = os.path.join(wal_dir, f"{group}.meta")
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.snap_index = 0
        self.snap_term = 0
        self._load_meta()
        if self.snap_index and self.wal.last_index() < self.snap_index:
            # snapshot compaction emptied the WAL before this restart —
            # re-anchor it past the snapshot or a new leadership here
            # would append at index 1 and never commit
            self.wal.reset(self.snap_index + 1)

        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = self.snap_index
        self.last_applied = self.snap_index
        # when this replica last heard from a live leader (append_entries
        # / snapshot install) — the staleness clock bounded_stale reads
        # are judged against; 0.0 = never
        self._leader_contact = 0.0
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}

        self.lock = threading.RLock()
        self.commit_cv = threading.Condition(self.lock)
        self._repl_cv = threading.Condition(self.lock)
        self._repl_threads: Dict[str, threading.Thread] = {}
        self._last_ack: Dict[str, float] = {}   # peer → send time of the
        #   last request that got a reply (lease freshness is measured
        #   from SEND: the follower's no-vote promise starts no later)
        # serializes apply_cb across the three callers (run loop, propose,
        # append_entries handler) so entries apply in commit order and a
        # propose's result is recorded before propose returns
        self._apply_mu = threading.Lock()
        self.alive = False
        self._deadline = 0.0
        self._last_hb = 0.0
        self._thread: Optional[threading.Thread] = None

        if isinstance(transport, LoopbackTransport):
            transport.register(self)

    # -- persistence of (term, vote, snapshot meta) -----------------------

    def _load_meta(self):
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                parts = f.read().split("\n")
            self.current_term = int(parts[0])
            self.voted_for = parts[1] or None
            if len(parts) > 3:
                self.snap_index, self.snap_term = int(parts[2]), int(parts[3])
        snap_file = self._meta_path + ".snap"
        if self.snap_index and self.restore_cb and os.path.exists(snap_file):
            with open(snap_file, "rb") as f:
                self.restore_cb(f.read())

    def _save_meta(self):
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{self.current_term}\n{self.voted_for or ''}\n"
                    f"{self.snap_index}\n{self.snap_term}")
        os.replace(tmp, self._meta_path)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        with self.lock:
            if self.alive:
                return
            self.alive = True
            self._reset_election_deadline()
            # replay unapplied committed entries is not needed: commit
            # index is volatile; entries re-commit via the leader
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"raft-{self.group}-{self.node_id}")
            self._thread.start()

    def stop(self):
        with self.lock:
            self.alive = False
            self.leader_id = None       # don't hint callers at ourselves
        if self._thread:
            self._thread.join(timeout=2)
        self.wal.close()

    def _reset_election_deadline(self):
        self._deadline = time.monotonic() + random.uniform(*self.eto)

    # -- main loop --------------------------------------------------------

    def _run(self):
        while True:
            with self.lock:
                if not self.alive:
                    return
                state = self.state
                now = time.monotonic()
                want_election = state != LEADER and now >= self._deadline
                want_hb = state == LEADER and now - self._last_hb >= self.hb
            if want_election:
                self._start_election()
            elif want_hb:
                self._replicate_all()
            self._apply_committed()
            time.sleep(0.01)

    # -- election ---------------------------------------------------------

    def _start_election(self):
        with self.lock:
            if self.node_id in self.learners:
                # a learner NEVER campaigns: it holds no vote, and a
                # catching-up replica's (complete-looking) log must not
                # be able to take leadership from the live voters
                self._reset_election_deadline()
                return
            if len(self.peers) == 0:
                # single-node group: become leader immediately
                self.current_term += 1
                self.voted_for = self.node_id
                self._save_meta()
                self._become_leader()
                return
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.node_id
            self._save_meta()
            term = self.current_term
            lli, llt = self._last_log()
            self._reset_election_deadline()
        # ask all peers concurrently: one unreachable peer (transport
        # timeout ≫ election timeout) must not stall the votes of the
        # healthy majority; leadership is taken as soon as a quorum grants
        votes = [1]
        votes_mu = threading.Lock()

        def ask(p):
            r = self.transport.send(p, self.group, "request_vote", {
                "_from": self.node_id, "term": term,
                "candidate": self.node_id,
                "last_log_index": lli, "last_log_term": llt})
            if r is None:
                return
            with self.lock:
                if r["term"] > self.current_term:
                    self._step_down(r["term"])
                    return
                if self.state != CANDIDATE or self.current_term != term:
                    return
                if r.get("granted"):
                    with votes_mu:
                        votes[0] += 1
                        n = votes[0]
                    if n * 2 > len(self.peers) + 1:
                        self._become_leader()

        # fire-and-forget: the ask threads tally votes and take
        # leadership themselves on quorum; joining here would stall the
        # run loop (and the new leader's first heartbeats) behind the
        # slowest/deadest peer's transport timeout
        for p in self.peers:
            threading.Thread(target=ask, args=(p,), daemon=True,
                             name=f"raft-vote-{self.node_id}").start()

    def _become_leader(self):
        self.state = LEADER
        self.leader_id = self.node_id
        # no-op entry in the new term: replicating it is what lets
        # _advance_commit (current-term-only, §5.4.2) re-commit the
        # previous terms' entries after a full-group restart
        self.wal.append(self.wal.last_index() + 1, self.current_term, b"")
        nxt = self.wal.last_index() + 1
        self.next_index = {p: nxt - 1 for p in self._repl_targets()}
        self.match_index = {p: 0 for p in self._repl_targets()}
        self._last_hb = 0.0
        if not self.peers:
            self.commit_index = self.wal.last_index()
            self.commit_cv.notify_all()

    def _step_down(self, term: int):
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._save_meta()
        self.state = FOLLOWER
        self._reset_election_deadline()

    def _last_log(self) -> Tuple[int, int]:
        lli = self.wal.last_index()
        if lli <= self.snap_index:
            return self.snap_index, self.snap_term
        return lli, self.wal.last_term()

    # -- replication ------------------------------------------------------

    def _repl_targets(self) -> List[str]:
        """Everything the leader ships entries to: voting peers plus
        learner replicas (which receive appends/snapshot install but
        never count toward the quorum _advance_commit computes)."""
        return self.peers + [l for l in self.learners
                             if l != self.node_id and l not in self.peers]

    def _replicate_all(self):
        """Kick the per-peer replicator threads.

        A slow or dead peer (transport timeout ≫ heartbeat interval) must
        never delay heartbeats to healthy followers — each peer has its
        own persistent replicator thread (no per-tick thread churn), and
        requests to a stuck peer can't stack up: the loop serializes
        sends per peer.
        """
        with self.lock:
            if self.state != LEADER:
                return
            self._last_hb = time.monotonic()
            for p in self._repl_targets():
                t = self._repl_threads.get(p)
                if t is None or not t.is_alive():
                    t = threading.Thread(
                        target=self._peer_loop, args=(p,), daemon=True,
                        name=f"raft-repl-{self.node_id}-{p}")
                    self._repl_threads[p] = t
                    t.start()
            self._repl_cv.notify_all()
        self._advance_commit()

    def _peer_loop(self, peer: str):
        """Persistent replicator for one follower; exits on step-down or
        when the peer leaves the configuration (update_peers)."""
        while True:
            with self.lock:
                if not self.alive or self.state != LEADER \
                        or peer not in self._repl_targets():
                    return
            ok = self._replicate_one(peer)
            self._advance_commit()
            with self._repl_cv:
                # a propose() notify that landed while we were mid-send
                # must not cost a full heartbeat of commit latency: skip
                # the wait when unreplicated entries are pending — but
                # ONLY if the peer answered the last send (otherwise a
                # dead peer + pending entries = a busy-spin hammering
                # the transport at full speed)
                if ok and self.alive and self.state == LEADER and \
                        self.next_index.get(peer, 1 << 62) <= \
                        self.wal.synced_index():
                    continue
                self._repl_cv.wait(self.hb)

    def _replicate_one(self, peer: str) -> bool:
        """One append_entries round; returns True iff the peer replied."""
        with self.lock:
            if self.state != LEADER:
                return False
            term = self.current_term
            nxt = self.next_index.get(peer, self.wal.last_index() + 1)
            if nxt <= self.snap_index:
                return self._send_snapshot(peer)
            prev_idx = nxt - 1
            if prev_idx == self.snap_index:
                prev_term = self.snap_term
            else:
                prev_term = self.wal.term_of(prev_idx) or 0
            max_batch = max(1, int(get_config().get("raft_max_batch")))
            # clamp to the durable index: a follower must never hold an
            # entry this leader could still lose to a crash (group
            # commit defers the leader's fsync; see Wal.sync_to)
            end = min(nxt + max_batch - 1, self.wal.synced_index())
            entries = [(i, t, _b64(d)) for (i, t, d)
                       in self.wal.read_range(nxt, end)]
            commit = self.commit_index
        if entries:
            from ..utils.stats import stats as _metrics
            _metrics().observe("raft_replication_batch_size",
                               len(entries), buckets=REPL_BATCH_BUCKETS)
        try:
            # armed raise == this append_entries round lost to the
            # network (peer partitioned); the caller treats it exactly
            # like a transport no-reply
            fail.hit("raft:replicate", key=self.group)
        except FailpointError:
            return False
        t_send = time.monotonic()
        r = self.transport.send(peer, self.group, "append_entries", {
            "_from": self.node_id, "term": term, "leader": self.node_id,
            "prev_index": prev_idx, "prev_term": prev_term,
            "entries": entries, "leader_commit": commit})
        if r is None:
            return False
        with self.lock:
            self._last_ack[peer] = t_send
            if r["term"] > self.current_term:
                self._step_down(r["term"])
                return True
            if self.state != LEADER:
                return True
            if r.get("ok"):
                if entries:
                    self.match_index[peer] = entries[-1][0]
                    self.next_index[peer] = entries[-1][0] + 1
            else:
                # back off; follower tells us its last index when known
                hint = r.get("hint")
                self.next_index[peer] = max(
                    1, hint + 1 if hint is not None else nxt - 1)
        return True

    def _send_snapshot(self, peer: str):
        if self.snapshot_cb is None:
            return
        snap_file = self._meta_path + ".snap"
        data = b""
        if os.path.exists(snap_file):
            with open(snap_file, "rb") as f:
                data = f.read()
        payload = {
            "_from": self.node_id, "term": self.current_term,
            "leader": self.node_id, "last_index": self.snap_index,
            "last_term": self.snap_term, "data": _b64(data)}
        self.lock.release()
        try:
            r = self.transport.send(peer, self.group, "install_snapshot",
                                    payload)
        finally:
            self.lock.acquire()
        if r and r.get("ok"):
            self.next_index[peer] = self.snap_index + 1
            self.match_index[peer] = self.snap_index

    def _advance_commit(self):
        with self.lock:
            if self.state != LEADER:
                return
            # never past the DURABLE index: the leader's own vote only
            # counts for fsynced entries (with peers the match_index
            # cap enforces this implicitly — replication is clamped to
            # synced_index — but a no-peers group has no such cap, and
            # a sibling proposer's flushed-but-unsynced tail must not
            # commit off the heartbeat tick)
            top = min(self.wal.last_index(), self.wal.synced_index())
            for n in range(top, self.commit_index, -1):
                if self.wal.term_of(n) != self.current_term:
                    break               # §5.4.2: only current-term entries
                cnt = 1 + sum(1 for p in self.peers
                              if self.match_index.get(p, 0) >= n)
                if cnt * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    self.commit_cv.notify_all()
                    break

    def _apply_committed(self):
        with self._apply_mu:
            while True:
                with self.lock:
                    if self.last_applied >= self.commit_index:
                        return
                    idx = self.last_applied + 1
                    r = self.wal.read(idx)
                    self.last_applied = idx
                # r is None for snapshot-covered gaps; empty payloads are
                # leader-election no-ops — neither reaches the state machine
                if r is not None and r[1]:
                    self.apply_cb(idx, r[1])
                self._maybe_snapshot()

    def _maybe_snapshot(self):
        if self.snapshot_cb is None:
            return
        with self.lock:
            if (self.last_applied - self.snap_index) < self.snapshot_threshold:
                return
            data = self.snapshot_cb()
            self.snap_index = self.last_applied
            self.snap_term = self.wal.term_of(self.snap_index) or self.snap_term
            with open(self._meta_path + ".snap", "wb") as f:
                f.write(data)
            self._save_meta()
            self.wal.compact_to(self.snap_index)

    # -- membership / leadership (BALANCE DATA / BALANCE LEADER) ----------

    def update_peers(self, replicas: List[str],
                     learners: Optional[List[str]] = None):
        """Adopt a new replica configuration (the balance/repair plan's
        membership change; reference raftex addPeer/removePeer).
        `learners=None` keeps the current learner set (legacy callers).

        Not joint consensus: the change is instantaneous on each member.
        Safety comes from the orchestration protocol — the part map is
        itself serialized through the metad raft group, and the shared
        membership engine (cluster/repair.py) applies changes with one
        side per step (add XOR remove; a learner→voter promotion only
        GROWS the voter set by an already-caught-up member), so any two
        consecutive configurations share a quorum."""
        promoted: List[str] = []
        with self.lock:
            new = [p for p in replicas if p != self.node_id]
            # a node named in `replicas` is a voter, full stop — it can
            # never linger in the learner set (promotion removes it)
            new_learners = [l for l in (self.learners if learners is None
                                        else learners)
                            if l not in replicas]
            if new == self.peers and new_learners == self.learners:
                return
            was_learner = set(self.learners)
            promoted = [p for p in replicas
                        if p in was_learner and p not in new_learners]
            self.peers = new
            self.learners = new_learners
            if self.state == LEADER:
                nxt = self.wal.last_index() + 1
                targets = self._repl_targets()
                for p in targets:
                    self.next_index.setdefault(p, max(1, nxt - 1))
                    self.match_index.setdefault(p, 0)
                for p in list(self.next_index):
                    if p not in targets:
                        self.next_index.pop(p, None)
                        self.match_index.pop(p, None)
            self._repl_cv.notify_all()
        if promoted:
            # a caught-up learner became a voter: from here its acks
            # count toward quorum and it may campaign / grant votes
            fail.hit("raft:promote_learner", key=self.group)
            _trace.record_phase("raft:promote_learner", 0.0,
                                group=self.group, peers=promoted)
        if self.is_leader():
            self._replicate_all()   # new follower gets snapshot/catch-up

    def transfer_leadership(self, target: str) -> bool:
        """Leader steps aside for `target` (raft §3.10 TimeoutNow): bring
        the target fully up to date (bounded rounds — concurrent writes
        may outrun a single 64-entry batch), tell it to start an election
        NOW, and step down immediately.  Stepping down on send is what
        keeps has_lease() honest: the target elects itself INSIDE the old
        leader's lease window (TimeoutNow bypasses the election timeout
        the lease bound is derived from), so the old leader must not
        serve lease reads past this point."""
        with self.lock:
            if self.state != LEADER or target not in self.peers:
                return False
            term = self.current_term
        # bounded catch-up with a CONSTANT entry budget (~4096, the
        # pre-knob 64×64): rounds scale inversely with raft_max_batch
        # so tuning the batch size down doesn't quietly shrink how far
        # behind a transfer target may be
        mb = max(1, int(get_config().get("raft_max_batch")))
        for _ in range(max(8, (4096 + mb - 1) // mb)):
            self._replicate_one(target)
            with self.lock:
                if self.state != LEADER or self.current_term != term:
                    return False
                if self.match_index.get(target, 0) >= self.wal.last_index():
                    break
        else:
            return False            # target can't catch up; abort
        r = self.transport.send(target, self.group, "timeout_now",
                                {"_from": self.node_id, "term": term})
        if not (r and r.get("ok")):
            return False
        with self.lock:
            if self.state == LEADER and self.current_term == term:
                self.state = FOLLOWER
                self._last_ack.clear()
                self._reset_election_deadline()
        return True

    # -- client API -------------------------------------------------------

    def is_leader(self) -> bool:
        with self.lock:
            return self.alive and self.state == LEADER

    @staticmethod
    def _lease_margin_s() -> float:
        try:
            return max(float(get_config().get("raft_lease_margin_ms")),
                       0.0) / 1e3
        except Exception:  # noqa: BLE001 — config not initialized
            return 0.025

    def has_lease(self) -> bool:
        """Heartbeat-majority leader lease for linearizable-ish reads.

        A deposed leader on the minority side of a partition keeps
        believing it leads until it learns the higher term; serving reads
        only while a majority acked within the minimum election timeout
        bounds that stale window: no new leader can have been elected
        during an interval in which this leader held a quorum's
        heartbeat acks.  A clock-skew margin (`raft_lease_margin_ms`,
        ISSUE 11 satellite) is subtracted from that bound: a follower
        whose clock runs slightly fast starts its election timer early,
        so the raw minimum election timeout overstates how long the
        no-vote promise is good for.  margin >= the election timeout
        disables the lease fast path (window <= 0 → always False)."""
        with self.lock:
            if not (self.alive and self.state == LEADER):
                return False
            if not self.peers:
                return True
            window = self.eto[0] - self._lease_margin_s()
            if window <= 0:
                return False
            horizon = time.monotonic() - window
            acked = sum(1 for p in self.peers
                        if self._last_ack.get(p, 0.0) >= horizon)
            return (acked + 1) * 2 > len(self.peers) + 1

    # -- read path (ISSUE 11): read-index / lease reads -------------------

    def applied_index(self) -> int:
        with self.lock:
            return self.last_applied

    def leader_contact_age(self) -> float:
        """Seconds since this replica provably tracked a live leader —
        the staleness clock for bounded_stale reads.  For a follower:
        age of the last append_entries/snapshot from a leader.  For a
        leader: age of the freshest heartbeat round a MAJORITY acked (a
        deposed-but-unaware leader on the minority side goes stale here
        exactly like a cut-off follower).  inf when never in contact."""
        now = time.monotonic()
        with self.lock:
            if not self.alive:
                return float("inf")
            if self.state == LEADER:
                if not self.peers:
                    return 0.0
                # VOTER acks only: learner replication also lands in
                # _last_ack, but a learner's ack proves nothing about
                # quorum freshness (a deposed leader kept fresh by its
                # learner must still go stale here)
                acks = sorted((v for p, v in self._last_ack.items()
                               if p in self.peers), reverse=True)
                need = (len(self.peers) + 1) // 2   # peers for a quorum
                if len(acks) < need:
                    return float("inf")
                return max(now - acks[need - 1], 0.0)
            if self._leader_contact <= 0.0:
                return float("inf")
            return max(now - self._leader_contact, 0.0)

    def read_index(self, timeout: float = 1.0) -> Optional[int]:
        """Linearizable read barrier (raft §6.4): an index such that a
        read observing every entry applied up to it sees everything
        committed before this call started.  On the leader the lease
        fast path answers from `commit_index` for free; a leader whose
        lease lapsed confirms its leadership with one live quorum round
        first (a deposed-but-unaware leader fails that round and
        returns None).  On a follower the call forwards to the known
        leader.  None = no leader reachable/confirmed — the caller
        walks replicas like any leader-change."""
        try:
            fail.hit("raft:read_index", key=self.group)
        except FailpointError:
            return None
        from ..utils.stats import stats as _metrics
        with self.lock:
            if not self.alive:
                return None
            leading = self.state == LEADER
            target = self.leader_id
            commit = self.commit_index
        if leading:
            if self.has_lease():
                _metrics().inc_labeled("raft_read_index",
                                       {"path": "lease"})
                return commit
            idx = self._quorum_confirm(timeout)
            if idx is not None:
                _metrics().inc_labeled("raft_read_index",
                                       {"path": "quorum"})
            return idx
        if not target or target == self.node_id:
            return None
        r = self.transport.send(target, self.group, "read_index",
                                {"_from": self.node_id})
        if not r or not r.get("ok"):
            return None
        _metrics().inc_labeled("raft_read_index", {"path": "forward"})
        return int(r["index"])

    def _quorum_confirm(self, timeout: float) -> Optional[int]:
        """Leadership confirmation for a lease-less read_index: one live
        append_entries round to every peer; success = a majority
        replied while our term survived.  Returns the commit index the
        confirmation covers (taken BEFORE the round — any entry
        committed before the call is <= it), or None."""
        with self.lock:
            if not (self.alive and self.state == LEADER):
                return None
            term = self.current_term
            commit = self.commit_index
            peers = list(self.peers)
        if not peers:
            return commit
        acks = [1]
        mu = threading.Lock()
        done = threading.Event()

        def ping(p):
            if not self._replicate_one(p):
                return
            with self.lock:
                if not (self.alive and self.state == LEADER
                        and self.current_term == term):
                    done.set()
                    return
            with mu:
                acks[0] += 1
                if acks[0] * 2 > len(peers) + 1:
                    done.set()

        for p in peers:
            threading.Thread(target=ping, args=(p,), daemon=True,
                             name=f"raft-readidx-{self.node_id}").start()
        done.wait(timeout)
        with self.lock:
            if not (self.alive and self.state == LEADER
                    and self.current_term == term):
                return None
        with mu:
            if acks[0] * 2 > len(peers) + 1:
                return commit
        return None

    def wait_applied(self, index: int, timeout: float = 5.0) -> bool:
        """Block until the local state machine has applied `index`
        (the follower half of a read-index read).  Drives apply itself
        when commits are already known locally; otherwise waits for the
        leader's next append_entries to advance commit_index."""
        dl = time.monotonic() + timeout
        while True:
            self._apply_committed()
            with self.lock:
                if self.last_applied >= index:
                    return True
                if not self.alive:
                    return False
                left = dl - time.monotonic()
                if left <= 0:
                    return False
                self.commit_cv.wait(min(left, 0.05))

    def propose(self, data: bytes, timeout: float = 5.0) -> Optional[int]:
        """Append + replicate + wait for commit.  Returns the entry's log
        index (truthy) on commit; None if not leader or timed out (caller
        retries against the current leader)."""
        idxs = self.propose_batch([data], timeout=timeout)
        return idxs[-1] if idxs else None

    def propose_batch(self, datas: List[bytes],
                      timeout: float = 5.0) -> Optional[List[int]]:
        """Group commit: append ALL entries under one lock hold, pay one
        (coalesced) WAL sync and one replication wake for the whole
        batch, and wait for the last entry's commit.  Returns the log
        indices on commit; None if not leader or timed out (caller
        retries against the current leader — per-entry apply outcomes
        are the state machine's business, see storage_service).

        Concurrent callers coalesce twice: the WAL group sync
        (Wal.sync_to — one fsync covers every batch flushed before it
        started) and the replication round (followers receive all
        pending entries of all callers in one append_entries, capped by
        raft_max_batch).  Commit waiters wake by index off commit_cv."""
        from ..utils.stats import stats as _metrics
        if not datas:
            return []
        t0 = time.monotonic()
        with self.lock:
            if not self.alive or self.state != LEADER:
                return None
            term = self.current_term
            idx0 = self.wal.last_index() + 1
            entries = [(idx0 + j, term, d) for j, d in enumerate(datas)]
            # buffered write only — the fsync happens OUTSIDE the part
            # lock so sibling proposers can stage entries meanwhile
            self.wal.append_batch(entries, sync=False)
            last = entries[-1][0]
        # pre/post bracket the durability point: a crash armed BEFORE
        # loses the batch, one armed AFTER loses only the ack
        fail.hit("raft:pre_fsync", key=self.group)
        self.wal.sync_to(last)          # group fsync (shared with siblings)
        fail.hit("raft:post_fsync", key=self.group)
        with self.lock:
            if not self.peers and self.state == LEADER:
                # single-node group: durable == committed — advance to
                # the SYNCED index only (a sibling's flushed-but-not-
                # fsynced tail must not commit off our fsync)
                durable = self.wal.synced_index()
                if self.commit_index < durable:
                    self.commit_index = durable
                    self.commit_cv.notify_all()
        _metrics().inc("raft_appends", len(entries))
        _metrics().inc("raft_propose_batches")
        self._replicate_all()
        fail.hit("raft:pre_commit", key=self.group)
        deadline = time.monotonic() + timeout
        with self.lock:
            while self.commit_index < last:
                left = deadline - time.monotonic()
                if left <= 0 or not self.alive or self.state != LEADER:
                    return None
                self.commit_cv.wait(left)
            # a deposal + truncation + foreign recommit can land while
            # waiting (the loop tolerates losing-then-regaining
            # leadership — the entry survives in OUR log across that):
            # ack only if the tail index still holds OUR term's entry
            t_last = self.wal.term_of(last)
            if t_last is not None and t_last != term:
                return None
        # serve-after-commit: apply before returning so leader reads see it
        self._apply_committed()
        _metrics().inc("raft_commits", len(entries))
        _metrics().observe("raft_commit_latency_ms",
                           (time.monotonic() - t0) * 1e3,
                           buckets=COMMIT_LATENCY_BUCKETS_MS)
        return [i for (i, _, _) in entries]

    # -- RPC handlers -----------------------------------------------------

    def handle(self, method: str, p: Dict[str, Any]) -> Dict[str, Any]:
        if not self.alive:
            raise RuntimeError(f"raft part {self.group} is stopped")
        if method == "request_vote":
            return self._on_request_vote(p)
        if method == "append_entries":
            return self._on_append_entries(p)
        if method == "install_snapshot":
            return self._on_install_snapshot(p)
        if method == "timeout_now":
            return self._on_timeout_now(p)
        if method == "read_index":
            return self._on_read_index(p)
        raise ValueError(f"unknown raft method {method}")

    def _on_read_index(self, p):
        """A follower asked us (its view of the leader) for a read
        barrier.  Only answered while actually leading — a fellow
        follower must NOT forward onward (two stale leader_id hints
        could otherwise chase each other in a cycle)."""
        with self.lock:
            if self.state != LEADER:
                return {"term": self.current_term, "ok": False}
        idx = self.read_index()
        return {"term": self.current_term, "ok": idx is not None,
                "index": idx}

    def _on_timeout_now(self, p):
        with self.lock:
            if p["term"] != self.current_term:
                return {"term": self.current_term, "ok": False}
        self._start_election()
        return {"term": self.current_term, "ok": True}

    def _on_request_vote(self, p):
        with self.lock:
            if p["term"] > self.current_term:
                self._step_down(p["term"])
            if self.node_id in self.learners:
                # a learner holds NO vote: even a candidate with a stale
                # config that asks must not be able to count us toward
                # its majority (unit-asserted, ISSUE 14)
                return {"term": self.current_term, "granted": False}
            granted = False
            if p["term"] == self.current_term and \
                    self.voted_for in (None, p["candidate"]):
                lli, llt = self._last_log()
                up_to_date = (p["last_log_term"], p["last_log_index"]) >= (llt, lli)
                if up_to_date:
                    granted = True
                    self.voted_for = p["candidate"]
                    self._save_meta()
                    self._reset_election_deadline()
            return {"term": self.current_term, "granted": granted}

    def _on_append_entries(self, p):
        with self.lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "ok": False}
            if p["term"] > self.current_term or self.state != FOLLOWER:
                self._step_down(p["term"])
            self.leader_id = p["leader"]
            self._leader_contact = time.monotonic()
            self._reset_election_deadline()

            prev_idx, prev_term = p["prev_index"], p["prev_term"]
            if prev_idx > 0 and prev_idx > self.snap_index:
                t = self.wal.term_of(prev_idx)
                if t is None:
                    return {"term": self.current_term, "ok": False,
                            "hint": self.wal.last_index()}
                if t != prev_term:
                    self.wal.truncate_from(prev_idx)
                    return {"term": self.current_term, "ok": False,
                            "hint": max(self.snap_index, prev_idx - 1)}
            # collect the suffix to append, then write it as ONE batch
            # (one buffered write + one fsync — the follower half of
            # group commit; `append` per entry was one fsync each).
            # Entries are contiguous ascending, so once the first new
            # index is found nothing after it can already exist.
            to_append: List[Tuple[int, int, bytes]] = []
            for (idx, term, d64) in p["entries"]:
                if to_append:
                    to_append.append((idx, term, _unb64(d64)))
                    continue
                have = self.wal.term_of(idx)
                if have is not None:
                    if have != term:
                        self.wal.truncate_from(idx)
                    else:
                        continue
                if idx <= self.snap_index:
                    continue
                to_append.append((idx, term, _unb64(d64)))
            if to_append:
                self.wal.append_batch(to_append)
                from ..utils.stats import stats as _metrics
                _metrics().inc("raft_appends", len(to_append))
            if p["leader_commit"] > self.commit_index:
                self.commit_index = min(p["leader_commit"],
                                        self.wal.last_index())
                self.commit_cv.notify_all()
        self._apply_committed()
        return {"term": self.current_term, "ok": True}

    def _on_install_snapshot(self, p):
        with self.lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "ok": False}
            self._step_down(p["term"])
            self.leader_id = p["leader"]
            self._leader_contact = time.monotonic()
            self._reset_election_deadline()
            data = _unb64(p["data"])
            if self.restore_cb:
                self.restore_cb(data)
            with open(self._meta_path + ".snap", "wb") as f:
                f.write(data)
            self.snap_index = p["last_index"]
            self.snap_term = p["last_term"]
            self.commit_index = max(self.commit_index, self.snap_index)
            self.last_applied = max(self.last_applied, self.snap_index)
            self.wal.reset(self.snap_index + 1)  # snapshot replaces the log
            self._save_meta()
            return {"term": self.current_term, "ok": True}
