"""Storage service — one storaged host.

Owns the partitions the meta part map assigns to it, replicates writes
through one Raft group per (space, part), serves reads from part
leaders.  Analog of the reference's StorageServer + processors over
NebulaStore/RaftPart (reference: src/storage + src/kvstore [UNVERIFIED —
empty mount, SURVEY §0]); the storage op set mirrors storage.thrift
(SURVEY §2 rows 6, 12, 13).

Ops are part-local: graphd resolves schema defaults and splits edge
writes into out/in halves (TOSS chain) before routing, so the raft
command stream of a part replays deterministically on its replicas.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..core.wire import from_wire, to_wire
from ..graphstore.store import GraphStore
from .meta_client import MetaClient
from .raft import RaftPart
from .rpc import RpcError, RpcRaftTransport, RpcServer


class StorageService:
    def __init__(self, my_addr: str, meta: MetaClient, data_dir: str,
                 server: RpcServer):
        self.my_addr = my_addr
        self.meta = meta
        self.data_dir = data_dir
        self.store = GraphStore(catalog=meta.catalog)
        self.parts: Dict[Tuple[int, int], RaftPart] = {}   # (space_id, pid)
        self.parts_lock = threading.RLock()
        self.transport = RpcRaftTransport()
        self.server = server
        server.register_service(self, prefix="storage.")
        # raft traffic for all my part groups rides the same server
        from .rpc import serve_raft_parts

        class _Groups(dict):
            def get(inner, key, default=None):  # noqa: N805
                return self._group_by_name(key)
        serve_raft_parts(server, _Groups())
        meta._hb_parts_fn = self.owned_parts
        meta.on_refresh = self.reconcile_parts

    # -- part lifecycle ---------------------------------------------------

    def _group_name(self, space_id: int, pid: int) -> str:
        return f"s{space_id}p{pid}"

    def _group_by_name(self, name: str) -> Optional[RaftPart]:
        with self.parts_lock:
            for (sid, pid), part in self.parts.items():
                if self._group_name(sid, pid) == name:
                    return part
        # raft message for a part we should own but haven't created yet
        self.reconcile_parts()
        with self.parts_lock:
            for (sid, pid), part in self.parts.items():
                if self._group_name(sid, pid) == name:
                    return part
        return None

    def owned_parts(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        with self.parts_lock:
            for (sid, pid) in self.parts:
                name = next((n for n, sp in self.meta.catalog.spaces.items()
                             if sp.space_id == sid), str(sid))
                out.setdefault(name, []).append(pid)
        return out

    def reconcile_parts(self):
        """Create/drop raft groups to match the meta part map."""
        self.store.catalog = self.meta.catalog
        with self.meta.lock:
            pm = dict(self.meta.part_map)
        for space_name, parts in pm.items():
            sp = self.meta.catalog.spaces.get(space_name)
            if sp is None:
                continue
            for pid, replicas in enumerate(parts):
                if self.my_addr not in replicas:
                    continue
                key = (sp.space_id, pid)
                with self.parts_lock:
                    if key in self.parts:
                        continue
                    gname = self._group_name(sp.space_id, pid)
                    part = RaftPart(
                        gname, self.my_addr, list(replicas), self.transport,
                        os.path.join(self.data_dir, "wal"),
                        apply_cb=self._make_apply(space_name),
                        # part state IS the raft snapshot: bounds WAL
                        # replay on restart + serves laggard catch-up
                        snapshot_cb=self._make_snapshot(space_name, pid),
                        restore_cb=self._make_restore(space_name, pid),
                        snapshot_threshold=2000)
                    self.parts[key] = part
                part.start()

    def _make_snapshot(self, space_name: str, pid: int):
        def snap() -> bytes:
            return self.store.export_part_state(space_name, pid)
        return snap

    def _make_restore(self, space_name: str, pid: int):
        def restore(data: bytes):
            if data:
                self.store.install_part_state(space_name, pid, data)
        return restore

    def _make_apply(self, space_name: str):
        def apply(idx: int, data: bytes):
            cmd = pickle.loads(data)
            self._apply_cmd(space_name, cmd)
        return apply

    def _apply_cmd(self, space: str, cmd: Tuple):
        op = cmd[0]
        st = self.store
        if op == "vertex":
            _, vid, tag, ver, row = cmd
            st.apply_vertex(space, vid, tag, ver, row)
        elif op == "edge_half":
            _, src, etype, dst, rank, row, which = cmd
            st.apply_edge_half(space, src, etype, dst, rank, row, which)
        elif op == "del_vertex":
            st.apply_delete_vertex(space, cmd[1])
        elif op == "del_edge_half":
            _, src, etype, dst, rank, which = cmd
            st.apply_delete_edge_half(space, src, etype, dst, rank, which)
        elif op == "upd_vertex":
            _, vid, tag, updates = cmd
            st.apply_update_vertex(space, vid, tag, updates)
        elif op == "upd_edge_half":
            _, src, etype, dst, rank, updates, which = cmd
            st.apply_update_edge_half(space, src, etype, dst, rank,
                                      updates, which)
        elif op == "del_tag":
            st.delete_tag(space, cmd[1], cmd[2])
        elif op == "rebuild_index":
            st.rebuild_index(space, cmd[1], parts=[cmd[2]])
        else:
            raise ValueError(f"unknown storage op {op!r}")

    def start(self):
        self.meta.start_heartbeat(parts_fn=self.owned_parts)

    def stop(self):
        self.meta.stop_heartbeat()
        with self.parts_lock:
            for p in self.parts.values():
                p.stop()

    # -- helpers ----------------------------------------------------------

    def _leader_part(self, space: str, pid: int) -> RaftPart:
        sp = self.meta.catalog.spaces.get(space)
        if sp is None:
            self.meta.refresh(force=True)
            sp = self.meta.catalog.spaces.get(space)
            if sp is None:
                raise RpcError(f"space `{space}' not found")
        part = self.parts.get((sp.space_id, pid))
        if part is None:
            self.reconcile_parts()
            part = self.parts.get((sp.space_id, pid))
        if part is None:
            raise RpcError(f"part {pid} of `{space}' not hosted here")
        if not part.is_leader():
            raise RpcError(f"part_leader_changed: {part.leader_id or ''}")
        return part

    # -- write RPCs: {"space", "part", "cmds": [wire-encoded tuples]} -----

    def rpc_write(self, p):
        space, pid = p["space"], p["part"]
        part = self._leader_part(space, pid)
        for cmd in p["cmds"]:
            data = pickle.dumps(tuple(from_wire(cmd)))
            if part.propose(data) is None:
                raise RpcError("part_leader_changed: write not committed")
        return len(p["cmds"])

    # -- read RPCs (leader reads) ----------------------------------------

    def rpc_get_neighbors(self, p):
        space, pid = p["space"], p["part"]
        self._leader_part(space, pid)
        vids = from_wire(p["vids"])
        rows = []
        for (src, et, rank, other, props, sd) in self.store.get_neighbors(
                space, vids, p.get("edge_types"), p.get("direction", "out")):
            rows.append([to_wire(src), et, rank, to_wire(other),
                         {k: to_wire(v) for k, v in props.items()}, sd])
        return rows

    def rpc_get_vertex(self, p):
        self._leader_part(p["space"], p["part"])
        tv = self.store.get_vertex(p["space"], from_wire(p["vid"]))
        if tv is None:
            return None
        return {t: {k: to_wire(v) for k, v in row.items()}
                for t, row in tv.items()}

    def rpc_get_edge(self, p):
        self._leader_part(p["space"], p["part"])
        row = self.store.get_edge(p["space"], from_wire(p["src"]),
                                  p["etype"], from_wire(p["dst"]),
                                  p.get("rank", 0))
        if row is None:
            return None
        return {k: to_wire(v) for k, v in row.items()}

    def rpc_scan_vertices(self, p):
        self._leader_part(p["space"], p["part"])
        out = []
        for vid, tag, row in self.store.scan_vertices(
                p["space"], p.get("tag"), parts=[p["part"]]):
            out.append([to_wire(vid), tag,
                        {k: to_wire(v) for k, v in row.items()}])
        return out

    def rpc_scan_edges(self, p):
        self._leader_part(p["space"], p["part"])
        out = []
        for src, et, rank, dst, row in self.store.scan_edges(
                p["space"], p.get("etype"), parts=[p["part"]]):
            out.append([to_wire(src), et, rank, to_wire(dst),
                        {k: to_wire(v) for k, v in row.items()}])
        return out

    def rpc_index_scan(self, p):
        self._leader_part(p["space"], p["part"])
        rng = p.get("range")
        if rng is not None:
            from ..graphstore.index import MAX, MIN
            lo, hi, li, hi_inc = rng
            lo = MIN if lo is None else from_wire(lo)
            hi = MAX if hi is None else from_wire(hi)
            rng = (lo, hi, li, hi_inc)
        ents = self.store.index_scan(p["space"], p["index"],
                                     from_wire(p["eq"]), rng,
                                     parts=[p["part"]])
        return [to_wire(list(e) if isinstance(e, tuple) else e)
                for e in ents]

    def rpc_rebuild_index(self, p):
        # rebuild rides the part's raft log so replicas backfill too —
        # followers must serve identical index state after failover
        part = self._leader_part(p["space"], p["part"])
        data = pickle.dumps(("rebuild_index", p["index"], p["part"]))
        if part.propose(data) is None:
            raise RpcError("part_leader_changed: rebuild not committed")
        sd = self.store.space(p["space"])
        idx = sd.index_data.get(p["index"])
        return len(idx.parts[p["part"]]) if idx is not None else 0

    def rpc_part_stats(self, p):
        sd = self.store.space(p["space"])
        pid = p["part"]
        part = sd.parts[pid]
        return {"vertices": len(part.vertices),
                "edges": part.edge_count(), "epoch": sd.epoch}

    def rpc_export_part(self, p):
        """Bulk CSR export of one part — the north-star storage addition
        (the device plane pins partitions from these; BASELINE.json)."""
        sd = self.store.space(p["space"])
        self._leader_part(p["space"], p["part"])
        with sd.lock:
            part = sd.parts[p["part"]]
            return _pk_part(part, sd)


def _pk_part(part, sd):
    import base64
    payload = {
        "part_id": part.part_id,
        "vertices": part.vertices,
        "out_edges": part.out_edges,
        "in_edges": part.in_edges,
        "part_count": sd.part_counts[part.part_id],
        "vid_to_dense": {v: d for v, d in sd.vid_to_dense.items()
                         if d % sd.num_parts == part.part_id},
    }
    return base64.b64encode(pickle.dumps(payload)).decode()
