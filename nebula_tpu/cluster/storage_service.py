"""Storage service — one storaged host.

Owns the partitions the meta part map assigns to it, replicates writes
through one Raft group per (space, part), serves reads at the caller's
requested consistency level — lease-gated leader reads by default,
read-index follower reads and bounded-staleness local reads on request
(`_read_part`, ISSUE 11; the raftex lease/read-index lineage).  Analog
of the reference's StorageServer + processors over
NebulaStore/RaftPart (reference: src/storage + src/kvstore [UNVERIFIED —
empty mount, SURVEY §0]); the storage op set mirrors storage.thrift
(SURVEY §2 rows 6, 12, 13).

Ops are part-local: graphd resolves schema defaults and splits edge
writes into out/in halves (TOSS chain) before routing, so the raft
command stream of a part replays deterministically on its replicas.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..core import wire
from ..core.wire import from_wire, to_wire
from ..graphstore.store import GraphStore
from ..utils import cancel as _cancel
from ..utils import consistency as _consistency
from ..utils import trace as _trace
from ..utils.config import get_config
from ..utils.failpoints import fail
from .meta_client import MetaClient
from .raft import RaftPart
from .rpc import RpcError, RpcRaftTransport, RpcServer

_STORAGE_OPS = frozenset({
    "vertex", "edge_half", "del_vertex", "del_edge_half", "upd_vertex",
    "upd_edge_half", "del_tag", "rebuild_index", "rebuild_fulltext",
    "chain_mark", "chain_done", "batch", "clear_part"})


class BoundedErrorMap:
    """(group, idx) → apply-error string, bounded with insertion-order
    eviction.

    The consumer contract is pop-on-ack (rpc_write claims its indices'
    errors after propose returns), but a propose that TIMES OUT returns
    None while its entry can still commit and fail apply later — that
    error is never claimed.  An unbounded dict therefore leaks one
    entry per timed-out-then-failed write for the life of the process
    (ISSUE 3 satellite); this map evicts the oldest records past `cap`
    instead."""

    def __init__(self, cap: int = 1024):
        from collections import OrderedDict
        self.cap = cap
        self._d: "OrderedDict[Tuple[str, int], str]" = OrderedDict()
        self._lock = threading.Lock()

    def record(self, key: Tuple[str, int], err: str):
        with self._lock:
            self._d.pop(key, None)
            self._d[key] = err
            while len(self._d) > self.cap:
                self._d.popitem(last=False)

    def pop(self, key: Tuple[str, int], default=None):
        with self._lock:
            return self._d.pop(key, default)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d


def _validate_cmd(cmd) -> tuple:
    """Decode-check a client write command BEFORE it reaches consensus —
    a malformed entry must be rejected at the RPC boundary, never
    committed where replay would poison every replica's apply loop."""
    decoded = tuple(from_wire(cmd))
    if not decoded or decoded[0] not in _STORAGE_OPS:
        raise RpcError(f"unknown storage op {decoded[0] if decoded else None!r}")
    if decoded[0] == "batch":
        for sub in decoded[1]:
            sub = tuple(sub)
            if not sub or sub[0] not in _STORAGE_OPS or sub[0] == "batch":
                raise RpcError(f"bad batch sub-op {sub[:1]!r}")
    return decoded


class _ReadBucket:
    """Token bucket behind `storage_read_capacity_qps` (ISSUE 11): a
    per-storaged read admission rate.  Over-rate reads shed with the
    PR 8 structured E_OVERLOAD + a retry-after priced at the bucket's
    refill — so a follower-readable client walks to a replica with
    spare capacity NOW instead of waiting this one out."""

    __slots__ = ("_tokens", "_t", "_mu")

    def __init__(self):
        self._tokens = 0.0
        self._t = 0.0
        self._mu = threading.Lock()

    def take(self, rate: float) -> Optional[float]:
        """None = admitted; else seconds until a token frees up."""
        import time as _t
        now = _t.monotonic()
        burst = max(rate / 10.0, 8.0)
        with self._mu:
            if self._t == 0.0:
                self._tokens, self._t = burst, now
            else:
                self._tokens = min(self._tokens
                                   + (now - self._t) * rate, burst)
                self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return None
            return max((1.0 - self._tokens) / rate, 0.001)


def _neighbors_columnar(raw) -> Optional[Dict[str, Any]]:
    """Columnar wire form of a get_neighbors reply (ISSUE 2): when the
    scan is single-edge-type with int vids and schema-uniform prop rows
    — the GO/MATCH bulk shape — ship src/rank/dst/sd and each prop as
    ONE typed blob instead of one JSON row per edge.  Row order is
    preserved column-wise.  Returns None for small or mixed replies
    (legacy row encoding)."""
    n = len(raw)
    if n < 64:
        return None
    from ..core.wire import encode_column
    et0 = raw[0][1]
    keys0 = tuple(raw[0][4])
    for (_, et, _, _, props, _) in raw:
        if et is not et0 and et != et0:
            return None
        if tuple(props) != keys0:
            return None                   # mixed schema versions: rows
    src = encode_column([r[0] for r in raw])
    dst = encode_column([r[3] for r in raw])
    if src is None or dst is None or src["dt"] != "<i8" \
            or dst["dt"] != "<i8":
        return None                       # string vids: legacy rows
    rank = encode_column([r[2] for r in raw])
    sd = encode_column([r[5] for r in raw])
    if rank is None or sd is None:
        return None
    pcols: Dict[str, Any] = {}
    for i, k in enumerate(keys0):
        col = [r[4][k] for r in raw]
        enc = encode_column(col)
        pcols[k] = enc if enc is not None \
            else {"v": [to_wire(x) for x in col]}
    return {"cols": True, "n": n, "et": et0, "src": src, "rank": rank,
            "dst": dst, "sd": sd, "props": pcols}


class StorageService:
    def __init__(self, my_addr: str, meta: MetaClient, data_dir: str,
                 server: RpcServer):
        self.my_addr = my_addr
        self.meta = meta
        self.data_dir = data_dir
        self.store = GraphStore(catalog=meta.catalog)
        self.parts: Dict[Tuple[int, int], RaftPart] = {}   # (space_id, pid)
        from ..utils.racecheck import make_lock
        self.parts_lock = make_lock("storage_parts")
        self._resume_alive = False
        self._resume_thread: Optional[threading.Thread] = None
        # (group, idx) → error string for entries whose apply failed;
        # checked by rpc_write so a client is never acked for a write
        # that did not actually land.  Bounded: a timed-out propose
        # never claims its error (see BoundedErrorMap).
        self._apply_errors = BoundedErrorMap()
        # per-part write census (device delta feed): applied raft
        # entries counted per writer token.  A graphd's delta log can
        # only trust its dirty keys if EVERY write since its watch came
        # through it — rpc_part_stats ships (total, from-you) counts so
        # the client proves exactly that before skipping a re-pin.
        # Counts are apply-side (replayed on restart, replica-local);
        # a snapshot-install or failover skews them only toward
        # MISmatch, which degrades to a full rebuild — never staleness.
        self._write_census: Dict[Tuple[str, int], Dict[Any, int]] = {}
        self._census_lock = threading.Lock()
        self._read_bucket = _ReadBucket()
        # per-partition heat map (ISSUE 16): read/write QPS + latency
        # EWMAs per (space, part), snapshotted onto the heartbeat so
        # metad can rank hotspots cluster-wide (SHOW HOTSPOTS) and the
        # replica router / BALANCE planner can consult heat_of()
        from ..utils.insights import PartHeatTable
        self.part_heat = PartHeatTable()
        # cluster-coherent cache epochs (ISSUE 20): per-space store
        # epochs ride the heartbeat as (boot, epoch, bump_ts).  boot_id
        # distinguishes this process incarnation — store epochs reset on
        # restart, and the graphd-side fold must treat a restarted
        # host's low epoch as news, not as a regression.
        import uuid
        self.boot_id = uuid.uuid4().hex[:12]
        from ..utils.epochs import EpochClock
        self._epoch_clock = EpochClock()
        self.transport = RpcRaftTransport()
        self.server = server
        server.service_role = "storaged"
        server.register_service(self, prefix="storage.")
        # raft traffic for all my part groups rides the same server
        from .rpc import serve_raft_parts

        class _Groups(dict):
            def get(inner, key, default=None):  # noqa: N805
                return self._group_by_name(key)
        serve_raft_parts(server, _Groups())
        meta._hb_parts_fn = self.owned_parts
        meta.on_refresh = self.reconcile_parts

    # -- part lifecycle ---------------------------------------------------

    def _group_name(self, space_id: int, pid: int) -> str:
        return f"s{space_id}p{pid}"

    def _group_by_name(self, name: str) -> Optional[RaftPart]:
        with self.parts_lock:
            for (sid, pid), part in self.parts.items():
                if self._group_name(sid, pid) == name:
                    return part
        # raft message for a part we should own but haven't created yet
        self.reconcile_parts()
        with self.parts_lock:
            for (sid, pid), part in self.parts.items():
                if self._group_name(sid, pid) == name:
                    return part
        return None

    def owned_parts(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        with self.parts_lock:
            for (sid, pid) in self.parts:
                name = next((n for n, sp in self.meta.catalog.spaces.items()
                             if sp.space_id == sid), str(sid))
                out.setdefault(name, []).append(pid)
        return out

    def reconcile_parts(self):
        """Create/update/drop raft groups to match the meta part map.

        BALANCE DATA changes the map; this reconciliation is what makes
        the change real on each storaged: new replicas spin up a raft
        member (and catch up via leader snapshot install), existing
        members adopt the new peer set, and replicas no longer in the
        map stop serving and release the part's state."""
        self.store.catalog = self.meta.catalog
        with self.meta.lock:
            pm = dict(self.meta.part_map)
            lm = {sp: [list(ls) for ls in lss]
                  for sp, lss in self.meta.learner_map.items()}
        sid_to_name = {sp.space_id: n
                       for n, sp in self.meta.catalog.spaces.items()}
        for space_name, parts in pm.items():
            sp = self.meta.catalog.spaces.get(space_name)
            if sp is None:
                continue
            sp_learners = lm.get(space_name, [])
            for pid, replicas in enumerate(parts):
                learners = list(sp_learners[pid]) \
                    if pid < len(sp_learners) else []
                if self.my_addr not in replicas \
                        and self.my_addr not in learners:
                    continue
                key = (sp.space_id, pid)
                with self.parts_lock:
                    existing = self.parts.get(key)
                    if existing is not None:
                        # adopting the new config may PROMOTE a learner
                        # (ISSUE 14): from here its acks count toward
                        # quorum and it may vote
                        existing.update_peers(list(replicas), learners)
                        continue
                    gname = self._group_name(sp.space_id, pid)
                    part = RaftPart(
                        gname, self.my_addr, list(replicas), self.transport,
                        os.path.join(self.data_dir, "wal"),
                        apply_cb=self._make_apply(space_name, pid, gname),
                        # part state IS the raft snapshot: bounds WAL
                        # replay on restart + serves laggard catch-up
                        snapshot_cb=self._make_snapshot(space_name, pid),
                        restore_cb=self._make_restore(space_name, pid),
                        snapshot_threshold=2000,
                        learners=learners)
                    self.parts[key] = part
                part.start()
        # drop parts this host no longer replicates — pop under the lock,
        # stop/clear OUTSIDE it (stop joins threads for up to 2s and
        # clear rebuilds indexes; holding parts_lock across that would
        # stall every concurrent write/raft-route on this host)
        dropped = []
        with self.parts_lock:
            for key in list(self.parts):
                sid, pid = key
                name = sid_to_name.get(sid)
                space_parts = pm.get(name, []) if name else []
                replicas = space_parts[pid] if pid < len(space_parts) \
                    else None
                sp_l = lm.get(name, []) if name else []
                learners = sp_l[pid] if pid < len(sp_l) else []
                if replicas is None or (self.my_addr not in replicas
                                        and self.my_addr not in learners):
                    dropped.append((self.parts.pop(key), name, pid))
        for part, name, pid in dropped:
            part.stop()
            if name is not None:
                try:
                    self.store.clear_part(name, pid)
                except Exception:  # noqa: BLE001 — space dropped
                    pass

    def _make_snapshot(self, space_name: str, pid: int):
        def snap() -> bytes:
            return self.store.export_part_state(space_name, pid)
        return snap

    def _make_restore(self, space_name: str, pid: int):
        def restore(data: bytes):
            if data:
                self.store.install_part_state(space_name, pid, data)
        return restore

    def _make_apply(self, space_name: str, pid: int, group: str):
        def apply(idx: int, data: bytes):
            # entries are wire-JSON (peers can inject raft traffic; an
            # unpickler here would be remote code execution).  A bad
            # entry must never kill the raft thread (it would re-crash
            # on every restart replay); the failure is recorded so the
            # leader's rpc_write can refuse to ack it.  Commands are
            # deterministic, so replicas fail identically — no
            # divergence from skipping.
            writer = None
            try:
                cmd = tuple(wire.loads(data))
                if cmd and cmd[0] == "v":
                    # version-stamped entry: best-effort catalog sync
                    # before apply (a failed refresh degrades to the
                    # old stale-cache behavior, never stalls the log)
                    if cmd[1] > self.meta.version:
                        try:
                            self.meta.refresh(force=True)
                        except Exception:  # noqa: BLE001
                            pass
                    cmd = tuple(cmd[2])
                if cmd and cmd[0] == "dbatch":
                    writer = cmd[2]
                self._apply_cmd(space_name, cmd)
            except Exception as ex:      # noqa: BLE001
                from ..utils.stats import stats
                stats().inc("storage_apply_errors")
                self._apply_errors.record((group, idx), str(ex))
            finally:
                # epoch bump-timestamp (ISSUE 20): every applied entry
                # may have advanced the space epoch — stamp the advance
                # so the heartbeat can ship a true bump ts and graphds
                # measure propagation lag, not heartbeat cadence.
                # Apply-side, so followers stamp their own applies too.
                try:
                    self._epoch_clock.note(
                        space_name, self.store.space(space_name).epoch)
                except Exception:  # noqa: BLE001 — space dropped mid-apply
                    pass
                # census counts EVERY entry, applied or failed, dedup-
                # skipped or not — symmetry is what matters: the client
                # compares (total - baseline) against (mine - baseline),
                # so any uniform counting rule works, and over-breaking
                # only costs a rebuild
                with self._census_lock:
                    c = self._write_census.setdefault(
                        (space_name, pid), {"total": 0})
                    c["total"] += 1
                    if writer is not None:
                        c[writer] = c.get(writer, 0) + 1
        return apply

    def _apply_cmd(self, space: str, cmd: Tuple):
        op = cmd[0]
        st = self.store
        if op == "batch":
            # one raft entry, several ops: TOSS chain_mark + out-half
            # must commit atomically or the journal could promise an
            # in-half whose out-half never landed
            for sub in cmd[1]:
                self._apply_cmd(space, tuple(sub))
        elif op == "dbatch":
            # exactly-once apply gate (ISSUE 5): a tokened write request
            # rides the log as ONE entry; a duplicate proposal of the
            # same (writer, seq) — client re-send after a lost reply,
            # racing the original's commit under a new leader — is
            # recognized HERE, deterministically on every replica, and
            # skipped.  This is what makes the mid-call-abort →
            # replica-walk-retry flip safe.
            _, pid, writer, seq, cmds = cmd
            self._apply_dbatch(space, pid, writer, seq, cmds)
        elif op == "vertex":
            _, vid, tag, ver, row = cmd
            st.apply_vertex(space, vid, tag, ver, row)
        elif op == "edge_half":
            _, src, etype, dst, rank, row, which = cmd
            st.apply_edge_half(space, src, etype, dst, rank, row, which)
        elif op == "del_vertex":
            st.apply_delete_vertex(space, cmd[1])
        elif op == "del_edge_half":
            _, src, etype, dst, rank, which = cmd
            st.apply_delete_edge_half(space, src, etype, dst, rank, which)
        elif op == "upd_vertex":
            _, vid, tag, updates = cmd
            st.apply_update_vertex(space, vid, tag, updates)
        elif op == "upd_edge_half":
            _, src, etype, dst, rank, updates, which = cmd
            st.apply_update_edge_half(space, src, etype, dst, rank,
                                      updates, which)
        elif op == "del_tag":
            st.delete_tag(space, cmd[1], cmd[2])
        elif op == "clear_part":
            st.clear_part(space, cmd[1])
        elif op == "rebuild_index":
            st.rebuild_index(space, cmd[1], parts=[cmd[2]])
        elif op == "rebuild_fulltext":
            st.rebuild_fulltext_index(space, cmd[1], parts=[cmd[2]])
        elif op == "chain_mark":
            _, pid, cid, in_pid, in_cmd, ts = cmd
            st.apply_chain_mark(space, pid, cid,
                                {"part": in_pid, "cmd": list(in_cmd),
                                 "ts": ts})
        elif op == "chain_done":
            st.apply_chain_done(space, cmd[1], cmd[2])
        else:
            raise ValueError(f"unknown storage op {op!r}")

    def _apply_dbatch(self, space: str, pid: int, writer: str, seq: int,
                      cmds):
        from ..utils.stats import stats
        rec = self.store.dedup_seen(space, pid, writer, seq)
        if rec is not None:
            # already applied (the original proposal committed despite
            # the client's lost reply): exact-once means NO re-apply —
            # and the skip must report the SAME outcome the original
            # recorded, including its failure (silently succeeding here
            # would ack the retry of a write whose apply FAILED)
            stats().inc("storage_write_dedup_apply_skips")
            if rec.get("err"):
                raise ValueError(rec["err"])
            return
        errs = []
        for sub in cmds:
            try:
                self._apply_cmd(space, tuple(sub))
            except Exception as ex:      # noqa: BLE001
                errs.append(str(ex))
        # the outcome (including a per-command apply failure) is part of
        # the record: a deduped retry must report the SAME result the
        # original would have
        self.store.dedup_record(space, pid, writer, seq,
                                {"n": len(cmds),
                                 "err": errs[0] if errs else None})
        if errs:
            raise ValueError(errs[0] + (f" (+{len(errs) - 1} more)"
                                        if len(errs) > 1 else ""))

    def epochs_for_heartbeat(self) -> Dict[str, list]:
        """{space: [boot_id, epoch, bump_ts]} for every space with local
        data — the per-host leg of the cluster epoch vector (ISSUE 20).
        bump_ts is None for an epoch that advanced outside the apply
        path's clock (no lag sample for it, never a wrong one)."""
        out: Dict[str, list] = {}
        for sd in list(self.store.data.values()):
            ep = sd.epoch
            if ep <= 0:
                continue
            name = sd.desc.name
            out[name] = [self.boot_id, ep, self._epoch_clock.ts_for(name, ep)]
        return out

    def start(self):
        self.meta.start_heartbeat(parts_fn=self.owned_parts,
                                  heat_fn=self.part_heat.snapshot,
                                  epochs_fn=self.epochs_for_heartbeat)
        self._resume_alive = True
        self._resume_thread = threading.Thread(
            target=self._chain_resume_loop, daemon=True,
            name=f"toss-resume-{self.my_addr}")
        self._resume_thread.start()

    def stop(self):
        self._resume_alive = False
        self.meta.stop_heartbeat()
        with self.parts_lock:
            for p in self.parts.values():
                p.stop()

    # -- TOSS chain resume (SURVEY §2 row 14) ----------------------------

    CHAIN_GRACE_S = 2.0      # graphd normally finishes the chain itself

    def _chain_resume_loop(self):
        import time as _t
        while self._resume_alive:
            _t.sleep(0.5)
            try:
                self._resume_chains()
            except Exception:    # noqa: BLE001 — keep the janitor alive
                pass

    def _resume_chains(self):
        """Finish TOSS chains whose graphd died between the two halves:
        the out-half part leader re-drives the recorded in-half to the
        dst part, then retires the journal entry through its own log.

        Batched chains (ISSUE 3: dstore coalesces one chain per
        (src_pid, dst_pid) pair) journal their in-half as a single
        `batch` command covering every edge of the pair — re-driving it
        is idempotent per edge (same-row overwrite), so a chain the
        graphd actually finished, or a janitor pass that raced another
        replica's, converges to the same state.  The chain_done
        retirements for one part ride ONE batched proposal."""
        import time as _t
        from .storage_client import StorageClient
        with self.parts_lock:
            items = list(self.parts.items())
        now = _t.time()
        sc = None
        for (sid, pid), part in items:
            if not part.is_leader():
                continue
            space = next((n for n, sp in self.meta.catalog.spaces.items()
                          if sp.space_id == sid), None)
            if space is None:
                continue
            done = []
            for cid, entry in self.store.pending_chains(space, pid).items():
                if now - entry.get("ts", 0.0) < self.CHAIN_GRACE_S:
                    continue
                if sc is None:
                    sc = StorageClient(self.meta)
                # in-half apply is idempotent (same row overwrite), so
                # re-driving a chain the graphd actually finished is safe
                sc._call_part(space, entry["part"], "storage.write",
                              {"cmds": [to_wire(list(entry["cmd"]))],
                               "cat_ver": self.meta.version})
                done.append(wire.dumps(("chain_done", pid, cid)))
            if done:
                part.propose_batch(done)
                from ..utils.stats import stats
                stats().inc("toss_chains_resumed", len(done))

    # -- helpers ----------------------------------------------------------

    def _local_part(self, space: str, pid: int) -> RaftPart:
        sp = self.meta.catalog.spaces.get(space)
        if sp is None:
            self.meta.refresh(force=True)
            sp = self.meta.catalog.spaces.get(space)
            if sp is None:
                raise RpcError(f"space `{space}' not found")
        part = self.parts.get((sp.space_id, pid))
        if part is None:
            self.reconcile_parts()
            part = self.parts.get((sp.space_id, pid))
        if part is None:
            raise RpcError(f"part {pid} of `{space}' not hosted here")
        return part

    def _leader_part(self, space: str, pid: int,
                     lease: bool = True) -> RaftPart:
        part = self._local_part(space, pid)
        if not part.is_leader():
            raise RpcError(f"part_leader_changed: {part.leader_id or ''}")
        if lease and not part.has_lease():
            # deposed-but-unaware leader (minority side of a partition)
            # must not serve stale reads; client retries elsewhere
            # (writes skip this: propose itself fails safely without quorum)
            raise RpcError(f"part_leader_changed: {part.leader_id or ''}")
        return part

    def _read_part(self, space: str, pid: int, p) -> RaftPart:
        """Serve-or-reject gate for a read RPC at its requested
        consistency level (ISSUE 11 tentpole).

          leader        — today's lease-gated leader read (default).
          follower      — read-index: obtain a read barrier from the
                          leader (lease fast path / quorum confirm /
                          follower forward) and wait for LOCAL apply to
                          reach it, so the reply observes everything
                          committed before the read started.
          bounded_stale — serve purely locally while this replica heard
                          from a live leader within read_max_stale_ms
                          AND its applied index covers the caller's
                          read-your-writes floor (`min_applied`); else
                          reject with a structured E_STALE + lag hint
                          and the client walks to a fresher replica.

        Successful non-leader-consistency serves stamp the serving
        replica + applied index into the statement's trace (the
        `storage:follower_read` phase rides the reply envelope) and
        count into the reply cost record (`follower_reads`)."""
        from ..utils.stats import current_cost, stats
        try:
            cap = float(get_config().get("storage_read_capacity_qps"))
        except Exception:  # noqa: BLE001 — config not initialized
            cap = 0.0
        if cap > 0:
            retry = self._read_bucket.take(cap)
            if retry is not None:
                from ..utils.admission import overload_error
                stats().inc_labeled("overload_server_rejections",
                                    {"op": "storage.read_capacity",
                                     "role": "storaged"})
                raise RpcError(overload_error(
                    retry, "storaged:read_capacity",
                    f"read capacity {cap:g}/s exhausted"))
        lvl = p.get("consistency") or _consistency.LEADER
        if lvl == _consistency.LEADER:
            part = self._leader_part(space, pid)
            self._heat_read(space, pid)
            return part
        if lvl not in _consistency.LEVELS:
            raise RpcError(f"unknown consistency level {lvl!r}")
        part = self._local_part(space, pid)
        if part.node_id in part.learners:
            # a catching-up learner (ISSUE 14) serves NOTHING — not even
            # bounded_stale: its applied index is mid-install and the
            # part map never routes here, so any arrival is a stale map
            raise RpcError(f"part_leader_changed: {part.leader_id or ''}")
        fail.hit("storage:follower_read", key=f"{part.group}|{lvl}")
        min_applied = int(p.get("min_applied") or 0)
        if lvl == _consistency.BOUNDED_STALE:
            part._apply_committed()       # drain locally-known commits
            lag_s = part.leader_contact_age()
            try:
                bound_ms = float(get_config().get("read_max_stale_ms"))
            except Exception:  # noqa: BLE001 — config not initialized
                bound_ms = 5000.0
            lag_ms = int(min(lag_s * 1e3, 10 ** 9))
            applied = part.applied_index()
            if lag_ms > bound_ms or applied < min_applied:
                stats().inc("stale_read_rejects")
                raise RpcError(
                    f"E_STALE: replica lag {lag_ms}ms over bound "
                    f"{int(bound_ms)}ms (applied={applied}, "
                    f"min_applied={min_applied}); lag_ms={lag_ms}")
        else:                             # follower: read-index
            idx = part.read_index()
            if idx is None:
                # no leader reachable/confirmed: same walk contract as
                # a leader change — the client tries the next replica
                raise RpcError(
                    f"part_leader_changed: {part.leader_id or ''}")
            target = max(idx, min_applied)
            if part.applied_index() < target:
                stats().inc("read_index_waits")
                rem = _cancel.remaining()
                timeout = min(rem, 5.0) if rem is not None else 5.0
                if not part.wait_applied(target,
                                         timeout=max(timeout, 0.001)):
                    raise RpcError(
                        f"part_leader_changed: {part.leader_id or ''}")
        stats().inc_labeled("follower_read_total", {"consistency": lvl})
        _trace.record_phase("storage:follower_read", 0.0, part=pid,
                            addr=self.my_addr, consistency=lvl,
                            applied=part.applied_index())
        cc = current_cost()
        if cc is not None:
            cc.add("follower_reads", 1)
        self._heat_read(space, pid)
        return part

    def _heat_read(self, space: str, pid: int):
        """Heat is SERVED load: bumped only when the gate admits — a
        client walking replicas for the leader must not triple-count
        one logical read across the part's hosts."""
        from ..utils.insights import StatementRegistry
        if StatementRegistry.enabled():
            self.part_heat.record_read(space, pid)

    # -- write RPCs: {"space", "part", "cmds": [wire-encoded tuples]} -----

    def rpc_write(self, p):
        space, pid = p["space"], p["part"]
        cat_ver = p.get("cat_ver", -1)
        if cat_ver > self.meta.version:
            # the write issuer has seen newer DDL than our cache:
            # refresh first so derived state (indexes/fulltext/TTL)
            # is maintained against the schema the writer validated on
            self.meta.refresh(force=True)
        part = self._leader_part(space, pid, lease=False)
        # cmds arrive wire-encoded; decode-validate ALL of them BEFORE
        # propose (a malformed command must fail the whole request up
        # front, not poison the log or land after committed siblings),
        # then the raft entries store the canonical wire form —
        # version-stamped so FOLLOWERS apply against a catalog at
        # least as new as the issuer's (the leader-only RPC check
        # would leave replica index state stale until failover)
        ver = max(cat_ver, self.meta.version)
        tok = p.get("token")
        if tok is not None:
            # exactly-once (ISSUE 5): the request's (writer_id, seq)
            # token gates a fast-path ack — if the ORIGINAL send already
            # applied (reply lost, client walked to us), return its
            # recorded outcome instead of re-proposing.  The window is
            # replicated state (written in dbatch apply), so this check
            # is correct on a freshly-failed-over leader too; the
            # _apply_committed() brings the window up to this leader's
            # commit index first.  Even a miss here is safe: the dbatch
            # apply gate skips duplicates deterministically.
            writer, seq = tok[0], int(tok[1])
            part._apply_committed()
            rec = self.store.dedup_seen(space, pid, writer, seq)
            if rec is not None:
                from ..utils.stats import current_cost, stats
                stats().inc("storage_write_dedup_hits")
                # trace + cost coverage (ISSUE 8 satellite): the fast-
                # path hit is a zero-duration leaf in the statement's
                # trace (shipped back in the reply spans) and a
                # `dedup_hits` field in the reply cost record
                _trace.record_phase("storage:dedup_hit", 0.0, part=pid,
                                    writer=writer, seq=seq)
                cc = current_cost()
                if cc is not None:
                    cc.add("dedup_hits", 1)
                if rec.get("err"):
                    raise RpcError(f"write apply failed: {rec['err']}")
                # applied index rides the ack (ISSUE 11): the original
                # proposal is applied locally (_apply_committed above),
                # so last_applied covers it — the caller's per-part
                # read-your-writes floor even on the dedup-retry path
                return {"n": rec.get("n", len(p["cmds"])),
                        "applied": part.applied_index(),
                        "epoch": self.store.space(space).epoch}
            stamped = [wire.dumps(
                ("v", ver, ["dbatch", pid, writer, seq,
                            [list(_validate_cmd(c)) for c in p["cmds"]]]))]
        else:
            stamped = [wire.dumps(("v", ver, list(_validate_cmd(cmd))))
                       for cmd in p["cmds"]]
        # chaos hook: the leader-kill-mid-batch schedule arms a crash
        # callable here — the request is validated but not yet proposed
        fail.hit("storage:pre_propose", key=part.group)
        # ONE batched proposal for the request: one WAL sync + one
        # replication wake for N commands (group commit, ISSUE 3)
        import time as _t
        t0 = _t.monotonic()
        with _trace.span("raft:propose_batch", group=part.group,
                         entries=len(stamped)):
            idxs = part.propose_batch(stamped)
        if idxs is None:
            raise RpcError("part_leader_changed: write not committed")
        from ..utils.insights import StatementRegistry
        if StatementRegistry.enabled():
            self.part_heat.record_write(
                space, pid, rows=len(p["cmds"]),
                latency_us=(_t.monotonic() - t0) * 1e6)
        # per-entry apply semantics are unchanged: any command whose
        # apply failed fails the request — a client is never acked for
        # a write that did not actually land
        errs = [e for e in (self._apply_errors.pop((part.group, i))
                            for i in idxs) if e is not None]
        if errs:
            raise RpcError(f"write apply failed: {errs[0]}"
                           + (f" (+{len(errs) - 1} more)"
                              if len(errs) > 1 else ""))
        # the ack carries the write's raft index (propose_batch applies
        # before returning): clients record it as the part's
        # read-your-writes floor for follower/bounded_stale reads —
        # plus the post-apply store epoch, the group-commit ack path
        # that feeds the device delta plane's freshness accounting
        return {"n": len(p["cmds"]), "applied": idxs[-1],
                "epoch": self.store.space(space).epoch}

    # -- read RPCs (consistency-gated via _read_part) --------------------

    def rpc_get_neighbors(self, p):
        """The storage exec DAG's scan stage + pushed-down filter/limit
        (SURVEY §2 row 12): a WHERE the graphd marked pushable arrives as
        nGQL text, parses once, and drops rows BEFORE they reach the
        wire — the candidate set never ships."""
        from .pushdown import apply_edge_filter, filter_from_wire
        space, pid = p["space"], p["part"]
        self._read_part(space, pid, p)
        vids = from_wire(p["vids"])
        edge_filter = filter_from_wire(p.get("filter"))
        limit = p.get("limit_per_src")
        with _trace.span("store:get_neighbors", space=space, part=pid,
                         vids=len(vids)) as sp_rec:
            it = self.store.get_neighbors(
                space, vids, p.get("edge_types"),
                p.get("direction", "out"))
            if edge_filter is not None or limit is not None:
                etypes = p.get("edge_types") or sorted(
                    e.name for e in self.store.catalog.edges(space))
                etype_ids = {et: self.store.catalog.get_edge(space,
                                                             et).edge_type
                             for et in etypes}
                it = apply_edge_filter(it, space, edge_filter, etype_ids,
                                       limit,
                                       stats_prefix="storage_pushdown")
            raw = list(it)
            # per-hop cost record (ISSUE 8): the reply envelope tells
            # the coordinator how many rows this part produced — the
            # remote half of PROFILE's per-node attribution
            self._cost_rows(len(raw))
            cols = _neighbors_columnar(raw)
            if cols is not None:
                if sp_rec is not None:
                    sp_rec.setdefault("attrs", {})["rows"] = cols["n"]
                return cols
            rows = []
            for (src, et, rank, other, props, sd) in raw:
                rows.append([to_wire(src), et, rank, to_wire(other),
                             {k: to_wire(v) for k, v in props.items()},
                             sd])
            if sp_rec is not None:
                sp_rec.setdefault("attrs", {})["rows"] = len(rows)
        return rows

    def rpc_get_vertex(self, p):
        self._read_part(p["space"], p["part"], p)
        tv = self.store.get_vertex(p["space"], from_wire(p["vid"]))
        if tv is None:
            return None
        return {t: {k: to_wire(v) for k, v in row.items()}
                for t, row in tv.items()}

    def rpc_get_edge(self, p):
        self._read_part(p["space"], p["part"], p)
        row = self.store.get_edge(p["space"], from_wire(p["src"]),
                                  p["etype"], from_wire(p["dst"]),
                                  p.get("rank", 0))
        if row is None:
            return None
        return {k: to_wire(v) for k, v in row.items()}

    @staticmethod
    def _cost_rows(n: int):
        from ..utils.stats import current_cost
        cc = current_cost()
        if cc is not None:
            cc.add("rows", n)

    def rpc_scan_vertices(self, p):
        self._read_part(p["space"], p["part"], p)
        out = []
        for vid, tag, row in self.store.scan_vertices(
                p["space"], p.get("tag"), parts=[p["part"]]):
            out.append([to_wire(vid), tag,
                        {k: to_wire(v) for k, v in row.items()}])
        self._cost_rows(len(out))
        return out

    def rpc_scan_edges(self, p):
        self._read_part(p["space"], p["part"], p)
        out = []
        for src, et, rank, dst, row in self.store.scan_edges(
                p["space"], p.get("etype"), parts=[p["part"]]):
            out.append([to_wire(src), et, rank, to_wire(dst),
                        {k: to_wire(v) for k, v in row.items()}])
        self._cost_rows(len(out))
        return out

    def rpc_index_scan(self, p):
        self._read_part(p["space"], p["part"], p)
        rng = p.get("range")
        if rng is not None:
            from ..graphstore.index import MAX, MIN
            lo, hi, li, hi_inc = rng
            lo = MIN if lo is None else from_wire(lo)
            hi = MAX if hi is None else from_wire(hi)
            rng = (lo, hi, li, hi_inc)
        ents = self.store.index_scan(p["space"], p["index"],
                                     from_wire(p["eq"]), rng,
                                     parts=[p["part"]])
        self._cost_rows(len(ents))
        return [to_wire(list(e) if isinstance(e, tuple) else e)
                for e in ents]

    def rpc_index_scan_geo(self, p):
        self._read_part(p["space"], p["part"], p)
        ents = self.store.index_scan_geo(
            p["space"], p["index"], [tuple(r) for r in p["ranges"]],
            parts=[p["part"]])
        return [to_wire(list(e) if isinstance(e, tuple) else e)
                for e in ents]

    def rpc_rebuild_index(self, p):
        # rebuild rides the part's raft log so replicas backfill too —
        # followers must serve identical index state after failover.
        # Version-stamped like rpc_write: the issuer has just seen the
        # CREATE INDEX DDL, so a storaged whose catalog cache predates
        # it must refresh BEFORE applying or the rebuild raises "index
        # not found" inside apply (swallowed) and the job reports
        # FINISHED over an empty index.
        cat_ver = p.get("cat_ver", -1)
        if cat_ver > self.meta.version:
            self.meta.refresh(force=True)
        part = self._leader_part(p["space"], p["part"])
        data = wire.dumps(("v", max(cat_ver, self.meta.version),
                           ["rebuild_index", p["index"], p["part"]]))
        if part.propose(data) is None:
            raise RpcError("part_leader_changed: rebuild not committed")
        sd = self.store.space(p["space"])
        idx = sd.index_data.get(p["index"])
        return len(idx.parts[p["part"]]) if idx is not None else 0

    def _ft_catalog_sync(self, p):
        """Force-refresh the catalog cache when the caller's view of the
        index generation (want_id) is newer — a search right after
        DROP + re-CREATE must not serve the old incarnation."""
        want = p.get("want_id")
        if want is None:
            return
        try:
            d = next((x for x in self.store.catalog.fulltext_indexes(
                p["space"]) if x.name == p["index"]), None)
        except Exception:  # noqa: BLE001 — space unknown to stale cache
            d = None
        if d is None or d.index_id != want:
            self.meta.refresh(force=True)

    def rpc_fulltext_search(self, p):
        """Text-search one part's slice of the full-text sink (SURVEY
        §2 row 10 Listener; the ES-query hop of the reference)."""
        self._read_part(p["space"], p["part"], p)
        self._ft_catalog_sync(p)
        ents = self.store.fulltext_search(p["space"], p["index"],
                                          p["op"], p["pattern"],
                                          parts=[p["part"]])
        return [to_wire(list(e) if isinstance(e, tuple) else e)
                for e in ents]

    def rpc_rebuild_fulltext(self, p):
        part = self._leader_part(p["space"], p["part"])
        self._ft_catalog_sync(p)
        # version-stamped for the same follower-staleness reason as
        # rpc_rebuild_index (the _ft_catalog_sync above only fixes the
        # leader's cache)
        data = wire.dumps(("v", self.meta.version,
                           ["rebuild_fulltext", p["index"], p["part"]]))
        if part.propose(data) is None:
            raise RpcError("part_leader_changed: rebuild not committed")
        sd = self.store.space(p["space"])
        ft = sd.ft_data.get(p["index"])
        return len(ft.values[p["part"]]) if ft is not None else 0

    def rpc_part_stats(self, p):
        if p.get("detail"):
            # per-schema counts are served authoritatively by the
            # leader by default (a lagging follower would under-count)
            # but honor an explicit weaker consistency; the plain
            # totals/epoch probe stays replica-readable so device
            # epoch checks survive a failover window
            self._read_part(p["space"], p["part"], p)
        sd = self.store.space(p["space"])
        pid = p["part"]
        part = sd.parts[pid]
        out = {"vertices": len(part.vertices),
               "edges": part.edge_count(), "epoch": sd.epoch}
        if "writer" in p:
            # delta-feed coverage probe: how many raft entries has this
            # part applied in total, and how many carried the asking
            # writer's token — equality of the two deltas since a
            # baseline proves no foreign writes slipped past the
            # asker's dirty-key log
            with self._census_lock:
                c = self._write_census.get((p["space"], pid)) or {}
                out["writes_total"] = c.get("total", 0)
                out["writes_from"] = c.get(p["writer"], 0)
        if p.get("detail"):
            out["detail"] = self.store.stats_detail(p["space"],
                                                    parts=[pid])
        return out

    def rpc_part_raft_info(self, p):
        """Raft progress of one local part replica — the BALANCE
        orchestrator polls this to decide a new replica has caught up
        before removing the old one."""
        sp = self.meta.catalog.spaces.get(p["space"])
        part = self.parts.get((sp.space_id, p["part"])) if sp else None
        if part is None or not part.alive:
            # a STOPPED part must answer like a missing one: its state
            # fields freeze at stop time (`state` can still read
            # "leader"), and a membership engine that believed a
            # zombie's leadership would anchor catch-up on a commit
            # index nobody serves anymore (ISSUE 14)
            raise RpcError(f"part {p['space']}/{p['part']} not here")
        with part.lock:
            return {"is_leader": part.state == "leader",
                    "is_learner": part.node_id in part.learners,
                    "learners": list(part.learners),
                    "term": part.current_term,
                    "commit_index": part.commit_index,
                    "last_applied": part.last_applied,
                    "last_index": part.wal.last_index(),
                    "snap_index": part.snap_index}

    def rpc_transfer_part_leader(self, p):
        """BALANCE LEADER: step aside for the named replica."""
        sp = self.meta.catalog.spaces.get(p["space"])
        part = self.parts.get((sp.space_id, p["part"])) if sp else None
        if part is None:
            raise RpcError(f"part {p['space']}/{p['part']} not here")
        if not part.is_leader():
            return {"ok": False, "reason": "not leader"}
        return {"ok": part.transfer_leadership(p["to"])}

    def rpc_reconcile(self, p):
        """Meta part-map changed (balance) — re-align local raft groups
        now instead of waiting for the next heartbeat."""
        self.meta.refresh(force=True)
        self.reconcile_parts()
        return True

    def rpc_export_part(self, p):
        """Bulk CSR export of one part — the north-star storage addition
        (the device plane pins partitions from these; BASELINE.json).
        Same payload vocabulary as the raft snapshot/checkpoint
        (GraphStore.part_state_payload) so the formats cannot drift."""
        self._leader_part(p["space"], p["part"])
        return to_wire(self.store.part_state_payload(p["space"],
                                                     p["part"]))
