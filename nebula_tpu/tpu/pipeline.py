"""TpuMatchPipeline: columnar MATCH-pipeline fusion (VERDICT r5 missing
#2 / next-round #2).

TpuMatchAgg fuses ONE chain ending in a count — but IC-shaped pipelines
(`MATCH …KNOWS*1..2` → `WITH DISTINCT` → second `MATCH` → `OPTIONAL
MATCH` → aggregate) ran their tails on per-row host executors, and the
device plane LOST to the host on both IC configs.  This module fuses the
WHOLE pipeline: the optimizer rule compiles a multi-clause plan subtree
into one `TpuMatchPipeline` node holding a straight-line segment program
(seed / chain / vmask / vpred / edist / project / dedup / join / agg /
sort / limit / result) interpreted over `ColumnarFrame`s — dense-id
columns + null masks (exec/frame.py) — so Python rows are never built
mid-plan:

  * chains run through `TpuRuntime.traverse_hops` (one device dispatch
    per warm shape per chain; consecutive uniform 1-hop Traverses merge
    into one multi-hop dispatch) with the same layered-HopFrame trail
    assembly TpuMatchAgg uses;
  * `WITH DISTINCT` is a lexsort dedup over id columns; joins are
    sort-merge joins over shared code spaces; `OPTIONAL MATCH` is a
    frame-level left join whose misses null-extend the right columns
    (3VL: predicates over null columns evaluate exactly like the host's
    NULL propagation);
  * aggregates are grouped counts over code columns; ORDER BY / LIMIT
    are columnar lexsorts.

Fusion bails out PER NODE at plan time — any node or expression the
compiler can't prove leaves that node (and everything above it) on the
row executors, counted in `match_pipeline_fallback{reason}` — and the
whole node falls back to the stashed original subplan on any runtime
device failure, so fusion is never wrong, only absent.  Parity contract
(tests/unit/test_frame_pipeline.py): fused rows == host row-executor
rows == brute-force oracle, including OPTIONAL MATCH null extension and
first-occurrence dedup/group order.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import expr as E
from ..core.expr import to_bool3
from ..core.value import NULL, DataSet, ColumnarDataSet, is_null
from ..exec.context import RowContext
from ..exec.executors import executor, run_node
from ..exec.frame import (ColumnarFrame, EdgeCol, OpaqueCol, ValCol,
                          VidCol, col_codes, group_ids, join_codes,
                          materialize_column)
from ..graphstore.csr import INT_NULL
from ..graphstore.schema import PropType
from ..query import optimizer as opt
from ..query.plan import PlanNode, walk_plan
from ..utils import admission as _admission  # noqa: F401 — defines the
# overload flags (tpu_dispatch_queue_cap) before any config lookup
from ..utils import cancel as _cancel
from ..utils import trace
from ..utils.failpoints import FailpointError, fail
from ..utils.config import define_flag, get_config
from ..utils.stats import stats
from .device import TpuUnavailable
from .exprjit import (CannotCompile, compilable,
                      compile_vertex_predicate_np, vertex_compilable)
from .match_agg import _exists_flat, _seed_vids, _tag_flat

try:
    import jax
    _JAX_RT_ERRORS = (jax.errors.JaxRuntimeError,)
except (ImportError, AttributeError):
    _JAX_RT_ERRORS = ()

define_flag("tpu_match_pipeline", True,
            "fuse multi-clause MATCH pipelines into one columnar "
            "device node (off = only single-chain fusions)")


# ---------------------------------------------------------------------------
# Compile-time schema
# ---------------------------------------------------------------------------


class _Sch:
    """Per-register column typing: name → kind, plus which vertex
    columns carry an existence check (prop reads / predicates are only
    valid on checked columns — host parity over shell vertices)."""
    __slots__ = ("names", "kinds", "checked")

    def __init__(self, names, kinds, checked):
        self.names = list(names)
        self.kinds = dict(kinds)
        self.checked = set(checked)

    def copy(self) -> "_Sch":
        return _Sch(self.names, self.kinds, self.checked)


class _Stash:
    """Original subtree kept for the runtime host fallback; repr-opaque
    so EXPLAIN doesn't inline the whole subplan."""
    __slots__ = ("node",)

    def __init__(self, node: PlanNode):
        self.node = node

    def __repr__(self):
        return f"<subplan {self.node.kind}#{self.node.id}>"


def _is_count_agg(e: E.Expr) -> bool:
    return isinstance(e, E.AggExpr) and e.func == "count"


def _rehome_edge_filter(ef: E.Expr, alias: Optional[str]) -> E.Expr:
    """A Traverse edge filter references the edge via its pattern alias
    (`membership.joinDate > …`); the device predicate compiler speaks
    `__edge__`.  Rewrite alias-qualified prop reads onto the traversed
    edge — the same binding the row executor's RowContext installs."""
    if not alias:
        return ef

    def sub(x: E.Expr):
        if isinstance(x, E.AttributeExpr) and isinstance(x.obj, E.LabelExpr) \
                and x.obj.name == alias:
            return E.EdgeProp("__edge__", x.attr)
        if isinstance(x, E.EdgeProp) and x.edge == alias:
            return E.EdgeProp("__edge__", x.name)
        return None

    return E.rewrite(ef, sub)


# ---------------------------------------------------------------------------
# Compiler: plan subtree → segment program
# ---------------------------------------------------------------------------


class _Compiler:
    def __init__(self, uses: Dict[int, int]):
        self.uses = uses
        self.ops: List[Dict[str, Any]] = []
        self.schemas: List[_Sch] = []
        self.var2reg: Dict[str, int] = {}
        self.gone_vars: set = set()      # absorbed, not register-backed
        self.nodes: set = set()
        self.space: Optional[str] = None
        self.memo: Dict[int, int] = {}
        self.n_chains = 0

    # -- helpers ---------------------------------------------------------

    def _emit(self, op: Dict[str, Any], sch: _Sch) -> int:
        self.ops.append(op)
        self.schemas.append(sch)
        op["out"] = len(self.schemas) - 1
        return op["out"]

    def _space(self, sp) -> None:
        if sp is None:
            raise CannotCompile("node without space")
        if self.space is None:
            self.space = sp
        elif self.space != sp:
            raise CannotCompile("cross-space pipeline")

    def _vid_col(self, sch: _Sch, name: str) -> None:
        if sch.kinds.get(name) != "vid":
            raise CannotCompile(f"column {name!r} is not a vertex column")

    # -- entry -----------------------------------------------------------

    def compile(self, node: PlanNode) -> int:
        got = self.memo.get(node.id)
        if got is not None:
            return got
        fn = _NODE_COMPILERS.get(node.kind)
        if fn is None:
            raise CannotCompile(f"node:{node.kind}")
        reg = fn(self, node)
        self.memo[node.id] = reg
        self.var2reg[node.output_var] = reg
        self.nodes.add(node.id)
        return reg

    # -- leaves ----------------------------------------------------------

    def _c_get_vertices(self, node: PlanNode) -> int:
        a = node.args
        self._space(a.get("space"))
        if a.get("src_col") or a.get("tags"):
            raise CannotCompile("GetVertices over input rows")
        if node.deps and not (len(node.deps) == 1
                              and node.dep().kind == "Start"):
            raise CannotCompile("GetVertices with deps")
        vids = a.get("vids") or []
        for v in vids:
            if isinstance(v, E.Expr) and not isinstance(v, E.Literal):
                raise CannotCompile("non-literal seed vid")
        alias = a.get("as_col") or (node.col_names[0] if node.col_names
                                    else None)
        if not alias:
            raise CannotCompile("GetVertices without alias")
        sch = _Sch([alias], {alias: "vid"}, {alias})
        return self._emit({"op": "seed", "vids": list(vids),
                           "alias": alias}, sch)

    def _c_argument(self, node: PlanNode) -> int:
        fv = node.args.get("from_var")
        reg = self.var2reg.get(fv)
        if reg is None:
            raise CannotCompile("argument-outside-region")
        col = node.args.get("col")
        src = self.schemas[reg]
        self._vid_col(src, col)
        sch = _Sch([col], {col: "vid"},
                   {col} if col in src.checked else ())
        return self._emit({"op": "argument", "in": reg, "col": col}, sch)

    # -- chains ----------------------------------------------------------

    def _c_traverse(self, node: PlanNode) -> int:
        a = node.args
        self._space(a.get("space"))
        etypes = list(a.get("edge_types") or [])
        direction = a.get("direction")
        min_hop, max_hop = a.get("min_hop"), a.get("max_hop")
        if min_hop is None or max_hop is None or max_hop < 1 \
                or min_hop < 0 or min_hop > max_hop:
            raise CannotCompile("unbounded or malformed hop range")
        var_len = not (min_hop == 1 and max_hop == 1)
        ef = a.get("edge_filter")
        if ef is not None:
            ef = _rehome_edge_filter(ef, a.get("edge_filter_alias"))
            if not compilable(ef, etypes):
                raise CannotCompile("edge filter not device-compilable")

        # merge a chain of uniform 1-hop Traverses (with optional
        # filter-compilable AppendVertices between them) into ONE
        # multi-hop device dispatch — the TpuMatchAgg chain walk,
        # generalized to any pipeline position
        chain = [node]            # outermost (= last hop) first
        mid_specs: Dict[int, PlanNode] = {}   # index into chain → AppendV
        cur = node.dep()
        if not var_len and ef is None:
            while True:
                spec = None
                nxt = cur
                if nxt.kind == "AppendVertices" \
                        and self.uses.get(nxt.id, 2) == 1 \
                        and len(nxt.deps) == 1 \
                        and nxt.args.get("space") == a.get("space") \
                        and nxt.args.get("col") == chain[-1].args.get(
                            "src_col") \
                        and nxt.dep().kind == "Traverse":
                    filt = nxt.args.get("filter")
                    if filt is not None and not vertex_compilable(
                            filt, nxt.args.get("col")):
                        break
                    spec = nxt
                    nxt = nxt.dep()
                if nxt.kind != "Traverse" \
                        or self.uses.get(nxt.id, 2) != 1:
                    break
                ia = nxt.args
                if (ia.get("edge_types") != a.get("edge_types")
                        or ia.get("direction") != direction
                        or ia.get("space") != a.get("space")
                        or ia.get("min_hop") != 1 or ia.get("max_hop") != 1
                        or ia.get("edge_filter") is not None
                        or ia.get("dst_alias") != chain[-1].args.get(
                            "src_col")):
                    break
                if spec is not None:
                    mid_specs[len(chain)] = spec
                    self.nodes.add(spec.id)
                    self.gone_vars.add(spec.output_var)
                chain.append(nxt)
                cur = nxt.dep()
        hops_nodes = chain[::-1]               # innermost (hop 1) first

        in_reg = self.compile(cur)
        in_sch = self.schemas[in_reg]
        src_col = hops_nodes[0].args.get("src_col")
        self._vid_col(in_sch, src_col)

        names = list(in_sch.names)
        kinds = dict(in_sch.kinds)
        checked = set(in_sch.checked)
        hops: List[Dict[str, Any]] = []
        steps = max_hop if var_len else len(hops_nodes)
        for i, h in enumerate(hops_nodes):
            ha = h.args
            e_alias, d_alias = ha.get("edge_alias"), ha.get("dst_alias")
            if not e_alias or not d_alias or e_alias in kinds \
                    or d_alias in kinds or e_alias == d_alias:
                raise CannotCompile("alias rebound inside a chain")
            spec = mid_specs.get(len(hops_nodes) - 1 - i)
            hop = {"edge": e_alias, "dst": d_alias,
                   "labels": list(spec.args.get("labels") or [])
                   if spec is not None else [],
                   "pred": spec.args.get("filter")
                   if spec is not None else None,
                   "checked": spec is not None}
            hops.append(hop)
            kinds[e_alias] = "opaque" if var_len else "edge"
            kinds[d_alias] = "vid"
            if spec is not None:
                checked.add(d_alias)
            names += [e_alias, d_alias]
            self.nodes.add(h.id)
            if h is not node:
                self.gone_vars.add(h.output_var)
        self.n_chains += 1
        sch = _Sch(names, kinds, checked)
        return self._emit(
            {"op": "chain", "in": in_reg, "src": src_col,
             "etypes": etypes, "direction": direction,
             "min_hop": min_hop if var_len else steps, "steps": steps,
             "var_len": var_len, "edge_filter": ef, "hops": hops}, sch)

    def _c_append_vertices(self, node: PlanNode) -> int:
        a = node.args
        self._space(a.get("space"))
        in_reg = self.compile(node.dep())
        sch = self.schemas[in_reg].copy()
        col = a.get("col")
        self._vid_col(sch, col)
        filt = a.get("filter")
        if filt is not None and not vertex_compilable(filt, col):
            raise CannotCompile("vertex filter not compilable")
        sch.checked.add(col)
        return self._emit({"op": "vmask", "in": in_reg, "col": col,
                           "labels": list(a.get("labels") or []),
                           "pred": filt}, sch)

    # -- row-set ops -----------------------------------------------------

    def _c_filter(self, node: PlanNode) -> int:
        cond = node.args.get("condition")
        if cond is None:
            raise CannotCompile("filter without condition")
        reg = self.compile(node.dep())
        sch = self.schemas[reg]
        for c in E.split_conjuncts(cond):
            if (isinstance(c, E.FunctionCall)
                    and c.name == "_edges_distinct"
                    and all(isinstance(x, E.LabelExpr) for x in c.args)):
                aliases = [x.name for x in c.args]
                for al in aliases:
                    if sch.kinds.get(al) != "edge":
                        raise CannotCompile(
                            "edge-uniqueness over a var-len binding")
                reg = self._emit({"op": "edist", "in": reg,
                                  "aliases": aliases}, sch.copy())
                sch = self.schemas[reg]
                continue
            placed = False
            for al in sch.names:
                if sch.kinds.get(al) == "vid" and al in sch.checked \
                        and vertex_compilable(c, al):
                    reg = self._emit({"op": "vpred", "in": reg,
                                      "alias": al, "pred": c}, sch.copy())
                    sch = self.schemas[reg]
                    placed = True
                    break
            if not placed:
                raise CannotCompile("filter conjunct not columnar")
        return reg

    def _col_desc(self, e: E.Expr, sch: _Sch) -> Tuple:
        if isinstance(e, (E.LabelExpr, E.InputProp)) \
                and e.name in sch.kinds:
            if sch.kinds[e.name] == "opaque":
                raise CannotCompile("opaque column read")
            return ("col", e.name)
        if (isinstance(e, E.FunctionCall) and e.name == "id"
                and len(e.args) == 1
                and isinstance(e.args[0], E.LabelExpr)
                and sch.kinds.get(e.args[0].name) == "vid"):
            return ("id", e.args[0].name)
        if isinstance(e, E.LabelTagProp) \
                and sch.kinds.get(e.var) == "vid":
            if e.var not in sch.checked:
                # host shells answer NULL for every prop — the snapshot
                # gather would answer real values; refuse
                raise CannotCompile("prop read on unchecked column")
            return ("prop", e.var, e.tag, e.prop)
        if isinstance(e, E.AttributeExpr) \
                and isinstance(e.obj, E.LabelExpr) \
                and sch.kinds.get(e.obj.name) == "vid":
            if e.obj.name not in sch.checked:
                raise CannotCompile("prop read on unchecked column")
            return ("vattr", e.obj.name, e.attr)
        if isinstance(e, E.Literal) and (
                e.value is None
                or isinstance(e.value, (bool, int, float, str))):
            return ("lit", e.value)
        raise CannotCompile(f"expression not columnar: {e.kind}")

    def _desc_kind(self, d: Tuple, sch: _Sch) -> Tuple[str, bool]:
        if d[0] == "col":
            return sch.kinds[d[1]], d[1] in sch.checked
        return ("val", False)

    def _c_project(self, node: PlanNode) -> int:
        a = node.args
        if a.get("empty"):
            raise CannotCompile("empty-marker project")
        if any(a.get(f) for f in ("go_row", "lookup_row", "fetch_row")):
            raise CannotCompile("non-MATCH project context")
        reg = self.compile(node.dep())
        sch = self.schemas[reg]
        descs, names, kinds, checked = [], [], {}, set()
        for e, n in a.get("columns") or []:
            d = self._col_desc(e, sch)
            descs.append((d, n))
            names.append(n)
            k, ck = self._desc_kind(d, sch)
            kinds[n] = k
            if ck:
                checked.add(n)
        return self._emit({"op": "project", "in": reg, "cols": descs},
                          _Sch(names, kinds, checked))

    def _c_dedup(self, node: PlanNode) -> int:
        reg = self.compile(node.dep())
        sch = self.schemas[reg]
        if any(sch.kinds[n] == "opaque" for n in sch.names):
            raise CannotCompile("dedup over a var-len binding")
        return self._emit({"op": "dedup", "in": reg}, sch.copy())

    def _c_join(self, node: PlanNode, outer: bool) -> int:
        keys = node.args.get("keys") or []
        if len(node.deps) != 2 or not keys:
            raise CannotCompile("join shape")
        l = self.compile(node.dep(0))
        r = self.compile(node.dep(1))
        ls, rs = self.schemas[l], self.schemas[r]
        for k in keys:
            lk, rk = ls.kinds.get(k), rs.kinds.get(k)
            if lk is None or rk is None or lk != rk \
                    or lk not in ("vid", "val"):
                raise CannotCompile("join key not columnar")
        r_extra = [n for n in rs.names if n not in ls.names]
        names = list(ls.names) + r_extra
        kinds = dict(ls.kinds)
        checked = set(ls.checked)
        for n in r_extra:
            kinds[n] = rs.kinds[n]
            if n in rs.checked:
                checked.add(n)
        return self._emit({"op": "join", "left": l, "right": r,
                           "keys": list(keys), "outer": outer,
                           "r_extra": r_extra},
                          _Sch(names, kinds, checked))

    def _c_aggregate(self, node: PlanNode) -> int:
        a = node.args
        reg = self.compile(node.dep())
        sch = self.schemas[reg]
        group_keys = a.get("group_keys") or []
        key_descs = [self._col_desc(k, sch) for k in group_keys]
        key_texts = [E.to_text(k) for k in group_keys]
        cols = []
        names, kinds, checked = [], {}, set()
        for e, n in a.get("columns") or []:
            names.append(n)
            if _is_count_agg(e):
                if e.arg is None:
                    cols.append((("count", None, False), n))
                else:
                    d = self._col_desc(e.arg, sch)
                    cols.append((("count", d, bool(e.distinct)), n))
                kinds[n] = "val"
                continue
            txt = E.to_text(e)
            if txt in key_texts:
                ki = key_texts.index(txt)
                cols.append((("key", ki), n))
                k, ck = self._desc_kind(key_descs[ki], sch)
                kinds[n] = k
                if ck:
                    checked.add(n)
                continue
            raise CannotCompile("aggregate column not a count/group key")
        return self._emit({"op": "agg", "in": reg, "keys": key_descs,
                           "cols": cols}, _Sch(names, kinds, checked))

    def _c_sort(self, node: PlanNode, topn: bool) -> int:
        a = node.args
        reg = self.compile(node.dep())
        sch = self.schemas[reg]
        factors = []
        for e, asc in a.get("factors") or []:
            d = self._col_desc(e, sch)
            if d[0] == "col" and sch.kinds[d[1]] == "edge":
                raise CannotCompile("sort key over an edge column")
            if d[0] == "lit":
                continue                     # constant key: no-op factor
            factors.append((d, bool(asc)))
        op = {"op": "sort", "in": reg, "factors": factors}
        if topn:
            op["offset"] = a.get("offset", 0) or 0
            op["count"] = a.get("count")
        return self._emit(op, sch.copy())

    def _c_limit(self, node: PlanNode) -> int:
        reg = self.compile(node.dep())
        return self._emit({"op": "limit", "in": reg,
                           "offset": node.args.get("offset", 0) or 0,
                           "count": node.args.get("count")},
                          self.schemas[reg].copy())


_NODE_COMPILERS = {
    "GetVertices": _Compiler._c_get_vertices,
    "Argument": _Compiler._c_argument,
    "Traverse": _Compiler._c_traverse,
    "AppendVertices": _Compiler._c_append_vertices,
    "Filter": _Compiler._c_filter,
    "Project": _Compiler._c_project,
    "Dedup": _Compiler._c_dedup,
    "HashInnerJoin": lambda c, n: _Compiler._c_join(c, n, False),
    "HashLeftJoin": lambda c, n: _Compiler._c_join(c, n, True),
    "Aggregate": _Compiler._c_aggregate,
    "Sort": lambda c, n: _Compiler._c_sort(c, n, False),
    "TopN": lambda c, n: _Compiler._c_sort(c, n, True),
    "Limit": _Compiler._c_limit,
}


# ---------------------------------------------------------------------------
# Fusion rule
# ---------------------------------------------------------------------------

_ROOT_KINDS = frozenset((
    "TopN", "Sort", "Limit", "Aggregate", "Project", "Dedup",
    "HashInnerJoin", "HashLeftJoin", "Filter"))
_TAIL_KINDS = frozenset((
    "Dedup", "HashInnerJoin", "HashLeftJoin", "Aggregate"))


def make_match_pipeline_rule(uses: Dict[int, int],
                             root: Optional[PlanNode] = None):
    if not get_config().get("tpu_match_pipeline"):
        return lambda node: None
    # Argument nodes anywhere in the plan: fusing a region one of them
    # reads INTO from outside would orphan its from_var
    plan_args = [] if root is None else \
        [(n.id, n.args.get("from_var"))
         for n in walk_plan(root) if n.kind == "Argument"]
    state = {"counted": False}

    def rule(node: PlanNode) -> Optional[PlanNode]:
        if node.kind not in _ROOT_KINDS:
            return None
        kinds = set()
        n_traverse = 0
        for n in walk_plan(node):
            kinds.add(n.kind)
            if n.kind == "Traverse":
                n_traverse += 1
        if n_traverse == 0:
            return None
        if not (kinds & _TAIL_KINDS) and n_traverse < 2:
            return None                  # single-clause: existing rules
        try:
            c = _Compiler(uses)
            out = c.compile(node)
            for n in node.col_names:
                if c.schemas[out].kinds.get(n) == "opaque":
                    raise CannotCompile("var-len binding at the boundary")
            for aid, fv in plan_args:
                if aid not in c.nodes and (fv in c.var2reg
                                           or fv in c.gone_vars):
                    raise CannotCompile("region referenced from outside")
            c.ops.append({"op": "result", "in": out,
                          "cols": list(node.col_names)})
        except CannotCompile as ex:
            if not state["counted"]:
                state["counted"] = True
                stats().inc_labeled(
                    "match_pipeline_fallback",
                    {"stage": "plan", "reason": str(ex)[:60]})
            return None
        stats().inc("match_pipeline_fused_plans")
        return PlanNode(
            "TpuMatchPipeline", deps=[],
            args={"space": c.space, "ops": c.ops,
                  "n_chains": c.n_chains,
                  "fallback": _Stash(node)},
            col_names=list(node.col_names))

    return rule


opt.TPU_RULES.append(make_match_pipeline_rule)


# ---------------------------------------------------------------------------
# Runtime: segment interpreter over ColumnarFrames
# ---------------------------------------------------------------------------


def _vertex_mask_fn(snap, sd, alias, labels, pred, check_exists=True):
    """Combined existence + label + predicate mask over dense ids
    (compile once, evaluate per batch — same contract as
    match_agg._position_mask_fn)."""
    tag_flats = []
    dead = False
    for lb in labels:
        tf = _tag_flat(snap, lb)
        if tf is None:
            dead = True
            break
        tag_flats.append(tf)
    pred_fn = compile_vertex_predicate_np(pred, alias, snap, sd) \
        if pred is not None else None
    exists = _exists_flat(snap) if check_exists else None

    def mask(dense: np.ndarray) -> np.ndarray:
        if dead:
            return np.zeros(dense.shape, bool)
        m = exists[dense] if exists is not None \
            else np.ones(dense.shape, bool)
        for tf in tag_flats:
            m &= tf[dense]
        if pred_fn is not None:
            m &= pred_fn(dense)
        return m

    return mask


def _null_extend(col, n: int):
    """An all-null column shaped like `col` with n rows (left-join miss
    extension)."""
    ones = np.ones(n, bool)
    if col.kind == "vid":
        return VidCol(np.zeros(n, np.int64), ones, col.checked)
    if col.kind == "val":
        dt = col.vals.dtype
        return ValCol(np.zeros(n, dt) if dt != object
                      else np.full(n, None, object), ones, col.vkind)
    if col.kind == "edge":
        z = np.zeros(n, np.int64)
        return EdgeCol(z, z, z, z, col.frame, z, ones)
    return OpaqueCol()


class _Runner:
    def __init__(self, qctx, ectx, rt, space: str):
        self.qctx, self.ectx, self.rt = qctx, ectx, rt
        self.space = space
        store = qctx.store
        try:
            sd = store.space(space)
            sd.dense_id
        except AttributeError:
            raise TpuUnavailable("store has no dense-id surface")
        self.store, self.sd = store, sd
        self.dev = rt.pin(store, space)
        self.snap = self.dev.host
        from .runtime import _d2v
        self.d2v = _d2v(self.snap)
        self.regs: List[ColumnarFrame] = []
        from .runtime import TraverseStats
        self.stats = TraverseStats()

    # -- ops -------------------------------------------------------------

    def run(self, ops: List[Dict[str, Any]]):
        import time as _time
        out = None
        for op in ops:
            # KILL QUERY / deadline between segments (ISSUE 5
            # satellite): a fused pipeline used to be uninterruptible
            # until the result boundary — a kill now lands at the next
            # segment instead of after the whole program
            _cancel.check()
            # per-SEGMENT attribution (ISSUE 8 tentpole): each segment
            # records its own wall time, output rows and device-
            # dispatch delta, so PROFILE breaks the fused node down
            # instead of reporting one opaque TpuMatchPipeline row
            t0 = _time.perf_counter()
            dev0 = self.stats.device_s
            # live workload row (ISSUE 9): finer-than-node progress —
            # SHOW QUERIES shows WHICH fused segment is running, not
            # just the opaque TpuMatchPipeline node
            from ..utils.workload import current_live
            lv = current_live()
            if lv is not None:
                lv.set_operator(f"TpuMatchPipeline/{op['op']}")
            out = getattr(self, "_x_" + op["op"])(op)
            seg = {"op": op["op"],
                   "us": int((_time.perf_counter() - t0) * 1e6)}
            dev_us = int((self.stats.device_s - dev0) * 1e6)
            if dev_us:
                seg["device_us"] = dev_us
            if isinstance(out, ColumnarFrame):
                self.regs.append(out)
                seg["rows"] = out.n
            elif out is not None and hasattr(out, "rows"):
                try:
                    seg["rows"] = len(out)
                except TypeError:
                    pass
            self.stats.segments.append(seg)
        return out

    def _frame(self, op, key="in") -> ColumnarFrame:
        return self.regs[op[key]]

    def _x_seed(self, op) -> ColumnarFrame:
        vids = _seed_vids({"vids": op["vids"]})
        ds = []
        for v in vids:
            d = self.sd.dense_id(v)
            ds.append(-1 if d is None else int(d))
        dense = np.asarray(ds, np.int64) if ds else np.empty(0, np.int64)
        if dense.size:
            dense = dense[dense >= 0]
            dense = dense[_exists_flat(self.snap)[dense]]
        alias = op["alias"]
        return ColumnarFrame(int(dense.size), [alias],
                             {alias: VidCol(dense, checked=True)})

    def _x_argument(self, op) -> ColumnarFrame:
        f = self._frame(op)
        col = f.col(op["col"])
        _, reps = group_ids(col_codes(col, f.n), f.n)
        return ColumnarFrame(int(reps.size), [op["col"]],
                             {op["col"]: col.take(reps)})

    def _x_vmask(self, op) -> ColumnarFrame:
        f = self._frame(op)
        col = f.col(op["col"])
        nn = ~col.null_mask(f.n)
        keep = np.zeros(f.n, bool)
        if nn.any():
            mfn = _vertex_mask_fn(self.snap, self.sd, op["col"],
                                  op["labels"], op["pred"])
            d = col.dense[nn]
            keep[nn] = mfn(d)
        out = f.take(np.flatnonzero(keep))
        oc = out.cols[op["col"]]
        out.cols[op["col"]] = VidCol(oc.dense, oc.null, True)
        return out

    def _x_vpred(self, op) -> ColumnarFrame:
        f = self._frame(op)
        col = f.col(op["alias"])
        nullm = col.null_mask(f.n)
        keep = np.zeros(f.n, bool)
        nn = ~nullm
        if nn.any():
            mfn = _vertex_mask_fn(self.snap, self.sd, op["alias"], [],
                                  op["pred"], check_exists=False)
            keep[nn] = mfn(col.dense[nn])
        if nullm.any():
            # every null row evaluates the predicate with the alias
            # bound to NULL — one constant 3VL evaluation (IS NULL forms
            # keep such rows; anything else propagates NULL → dropped)
            rc = RowContext(self.qctx, self.space, {op["alias"]: NULL})
            keep[nullm] = to_bool3(op["pred"].eval(rc)) is True
        return f.take(np.flatnonzero(keep))

    def _x_edist(self, op) -> ColumnarFrame:
        f = self._frame(op)
        cols = [f.col(a) for a in op["aliases"]]
        keep = np.ones(f.n, bool)
        for i in range(len(cols)):
            for j in range(i + 1, len(cols)):
                a, b = cols[i], cols[j]
                eq = ((a.et == b.et) & (a.ks == b.ks)
                      & (a.kd == b.kd) & (a.rank == b.rank))
                eq &= ~a.null_mask(f.n) & ~b.null_mask(f.n)
                keep &= ~eq
        return f.take(np.flatnonzero(keep))

    def _x_chain(self, op) -> ColumnarFrame:
        f = self._frame(op)
        col = f.col(op["src"])
        nullm = col.null_mask(f.n)
        codes = col.dense.copy()
        codes[nullm] = -1
        gid, reps = group_ids([codes], f.n)
        rep_vals = codes[reps]
        live = rep_vals >= 0
        seed_dense = rep_vals[live]
        g2s = np.full(reps.size, -1, np.int64)
        g2s[live] = np.arange(int(live.sum()), dtype=np.int64)
        row_seed = g2s[gid]                 # -1 on null-src rows
        n_seeds = int(seed_dense.size)

        steps = op["steps"]
        hops = op["hops"]
        if n_seeds:
            # chaos site: an armed raise here == the device rejected
            # the dispatch (OOM, resets); the executor's fallback path
            # runs the stashed row subplan — never wrong, only absent
            fail.hit("tpu:dispatch", key=self.space)
            vids = [self.d2v[d] for d in seed_dense.tolist()]
            frames, st = self.rt.traverse_hops(
                self.store, self.space, vids, op["etypes"],
                op["direction"], steps, edge_filter=op["edge_filter"])
            self._merge_stats(st)
        else:
            from .runtime import HopFrame
            frames = [HopFrame.empty() for _ in range(steps)]

        tracker = getattr(self.ectx, "tracker", None)
        new_names = []
        for h in hops:
            new_names += [h["edge"], h["dst"]]

        if op["var_len"]:
            min_hop = op["min_hop"]
            em_ord: List[np.ndarray] = []
            em_dst: List[np.ndarray] = []
            sidx = np.arange(n_seeds, dtype=np.int64)
            last = seed_dense
            path: List[np.ndarray] = []
            if min_hop == 0:
                em_ord.append(sidx.copy())
                em_dst.append(seed_dense.copy())
            from .runtime import join_frontier_trails, trail_distinct_keep
            for h in range(steps):
                if last.size == 0 or frames[h].n == 0:
                    break
                parent, fidx = join_frontier_trails(frames[h], last)
                if fidx.size == 0:
                    break
                if path:
                    keep = trail_distinct_keep(frames, path, parent,
                                               frames[h], fidx)
                    sel = np.flatnonzero(keep)
                    parent, fidx = parent[sel], fidx[sel]
                    if fidx.size == 0:
                        break
                sidx = sidx[parent]
                last = frames[h].dst[fidx]
                path = [p[parent] for p in path] + [fidx]
                if tracker is not None:
                    tracker.charge(int(fidx.size) * 8 * (h + 2))
                if h + 1 >= max(min_hop, 1):
                    em_ord.append(sidx)
                    em_dst.append(last)
            ords = np.concatenate(em_ord) if em_ord \
                else np.empty(0, np.int64)
            dsts = np.concatenate(em_dst) if em_dst \
                else np.empty(0, np.int64)
            new_cols = {hops[0]["edge"]: OpaqueCol(),
                        hops[0]["dst"]: VidCol(dsts, checked=False)}
            return self._attach(f, row_seed, n_seeds, ords,
                                new_names, new_cols)

        # fixed-length (possibly merged) chain: assemble trails hop by
        # hop, pruning each mid position by its absorbed AppendVertices
        from .runtime import join_frontier_trails
        sidx = np.arange(n_seeds, dtype=np.int64)
        vcols = [seed_dense]
        path: List[np.ndarray] = []
        for h in range(steps):
            if vcols[-1].size == 0 or frames[h].n == 0:
                sidx = np.empty(0, np.int64)
                vcols = [np.empty(0, np.int64)] * (steps + 1)
                path = [np.empty(0, np.int64)] * steps
                break
            parent, fidx = join_frontier_trails(frames[h], vcols[-1])
            nxt = frames[h].dst[fidx]
            hop = hops[h]
            if hop["checked"] and fidx.size:
                mfn = _vertex_mask_fn(self.snap, self.sd, hop["dst"],
                                      hop["labels"], hop["pred"])
                sel = np.flatnonzero(mfn(nxt))
                parent, fidx, nxt = parent[sel], fidx[sel], nxt[sel]
            sidx = sidx[parent]
            vcols = [c[parent] for c in vcols] + [nxt]
            path = [p[parent] for p in path] + [fidx]
            if tracker is not None and fidx.size:
                tracker.charge(int(fidx.size) * 8 * (h + 2))
        new_cols = {}
        for h, hop in enumerate(hops):
            new_cols[hop["edge"]] = EdgeCol.from_frame(frames[h], path[h]) \
                if path[h].size or frames[h].n else \
                EdgeCol.from_frame(frames[h], np.empty(0, np.int64))
            new_cols[hop["dst"]] = VidCol(vcols[h + 1],
                                          checked=hop["checked"])
        return self._attach(f, row_seed, n_seeds, sidx,
                            new_names, new_cols)

    def _attach(self, f: ColumnarFrame, row_seed: np.ndarray,
                n_seeds: int, ords: np.ndarray, new_names: List[str],
                new_cols: Dict[str, Any]) -> ColumnarFrame:
        """Join chain emissions (ords = seed ordinal per emission, in
        chain order) back to the input rows: per input row, its seed's
        emissions in chain order — the host Traverse's (input row,
        expansion) nesting."""
        order = np.argsort(ords, kind="stable")
        so = ords[order]
        starts = np.searchsorted(so, np.arange(n_seeds, dtype=np.int64))
        ends = np.searchsorted(so, np.arange(1, n_seeds + 1,
                                             dtype=np.int64))
        safe = np.maximum(row_seed, 0)
        cnt = np.where(row_seed >= 0, ends[safe] - starts[safe], 0) \
            if n_seeds else np.zeros(f.n, np.int64)
        ecum = np.cumsum(cnt)
        total = int(ecum[-1]) if cnt.size else 0
        if total == 0:
            prow = np.empty(0, np.int64)
            esel = np.empty(0, np.int64)
        else:
            k = np.arange(total, dtype=np.int64)
            prow = np.searchsorted(ecum, k, side="right")
            within = k - (ecum[prow] - cnt[prow])
            esel = order[starts[row_seed[prow]] + within]
        out_cols = {nm: f.cols[nm].take(prow) for nm in f.names}
        for nm in new_names:
            out_cols[nm] = new_cols[nm].take(esel)
        return ColumnarFrame(total, list(f.names) + new_names, out_cols)

    def _x_project(self, op) -> ColumnarFrame:
        f = self._frame(op)
        cols, names = {}, []
        for d, n in op["cols"]:
            cols[n] = self._desc_col(f, d)
            names.append(n)
        return ColumnarFrame(f.n, names, cols)

    def _desc_col(self, f: ColumnarFrame, d: Tuple):
        if d[0] == "col":
            return f.col(d[1])
        if d[0] == "id":
            col = f.col(d[1])
            vals = self.d2v[col.dense]
            vk = "int" if vals.dtype != object else "obj"
            return ValCol(vals, col.null, vk)
        if d[0] == "prop":
            return self._prop_col(f, d[1], d[2], d[3])
        if d[0] == "vattr":
            return self._attr_col(f, d[1], d[2])
        if d[0] == "lit":
            v = d[1]
            n = f.n
            if v is None or is_null(v):
                return ValCol(np.zeros(n, np.int64), np.ones(n, bool),
                              "int")
            if isinstance(v, bool):
                return ValCol(np.full(n, v, bool), None, "bool")
            if isinstance(v, int):
                return ValCol(np.full(n, v, np.int64), None, "int")
            if isinstance(v, float):
                return ValCol(np.full(n, v, np.float64), None, "float")
            return ValCol(np.full(n, v, object), None, "str")
        raise CannotCompile(f"descriptor {d[0]}")

    def _prop_col(self, f: ColumnarFrame, alias: str, tag: str,
                  prop: str) -> ValCol:
        col = f.col(alias)
        n = f.n
        nullm = col.null_mask(n).copy()
        tt = self.snap.tags.get(tag)
        if tt is None or prop not in tt.props:
            return ValCol(np.zeros(n, np.int64), np.ones(n, bool), "int")
        P = self.snap.num_parts
        d = np.where(nullm, 0, col.dense)
        raw = tt.props[prop][d % P, d // P]
        pt = tt.prop_types[prop]
        return self._decode_raw(raw, pt, nullm, n)

    def _attr_col(self, f: ColumnarFrame, alias: str, prop: str) -> ValCol:
        """Tag-less `v.prop`: merged across every tag carrying the prop
        (exprjit.merged_attr_columns — later tag wins), then decoded."""
        from .exprjit import merged_attr_columns, merged_attr_raw
        col = f.col(alias)
        n = f.n
        nullm = col.null_mask(n).copy()
        parts = merged_attr_columns(self.snap, prop)
        if not parts:
            return ValCol(np.zeros(n, np.int64), np.ones(n, bool), "int")
        pts = {p[3] for p in parts}
        if len(pts) > 1:
            raise CannotCompile(f"attr {prop} mixes prop types")
        d = np.where(nullm, 0, col.dense)
        raw = merged_attr_raw(self.snap, parts, d)
        return self._decode_raw(raw, parts[0][3], nullm, n)

    def _decode_raw(self, raw: np.ndarray, pt, nullm: np.ndarray,
                    n: int) -> ValCol:
        if pt in (PropType.FLOAT, PropType.DOUBLE):
            vals = raw.astype(np.float64)
            return ValCol(vals, nullm | np.isnan(vals), "float")
        if pt == PropType.BOOL:
            return ValCol(raw != 0, nullm | (raw == INT_NULL), "bool")
        if pt in (PropType.STRING, PropType.FIXED_STRING):
            pool = self.snap.pool
            ns = len(pool.strings)
            bad = (raw < 0) | (raw >= ns)
            if ns == 0:
                vals = np.full(n, None, object)
            else:
                vals = pool.obj_array()[np.where(bad, 0, raw)]
            return ValCol(vals, nullm | bad, "str")
        if pt in (PropType.DATE, PropType.DATETIME, PropType.TIME,
                  PropType.DURATION, PropType.GEOGRAPHY):
            from ..graphstore.csr import decode_prop
            nullm = nullm | (raw == INT_NULL)
            vals = np.empty(n, object)
            nn = np.flatnonzero(~nullm)
            for i in nn.tolist():
                vals[i] = decode_prop(pt, raw[i], self.snap.pool)
            return ValCol(vals, nullm, "obj")
        return ValCol(raw.astype(np.int64), nullm | (raw == INT_NULL),
                      "int")

    def _x_dedup(self, op) -> ColumnarFrame:
        f = self._frame(op)
        codes: List[np.ndarray] = []
        for nm in f.names:
            codes.extend(col_codes(f.col(nm), f.n))
        _, reps = group_ids(codes, f.n)
        return f.take(reps)

    def _x_join(self, op) -> ColumnarFrame:
        l = self.regs[op["left"]]
        r = self.regs[op["right"]]
        outer = op["outer"]
        lc_all: List[np.ndarray] = []
        rc_all: List[np.ndarray] = []
        for k in op["keys"]:
            lc, rc = join_codes(l.col(k), r.col(k), l.n, r.n)
            lc_all.extend(lc)
            rc_all.extend(rc)
        both = [np.concatenate([a, b]) for a, b in zip(lc_all, rc_all)]
        gid, _ = group_ids(both, l.n + r.n)
        lg, rg = gid[:l.n], gid[l.n:]
        rorder = np.argsort(rg, kind="stable")
        rs = rg[rorder]
        starts = np.searchsorted(rs, lg)
        ends = np.searchsorted(rs, lg, side="right")
        cnt = ends - starts
        eff = np.maximum(cnt, 1) if outer else cnt
        ecum = np.cumsum(eff) if eff.size else eff
        total = int(ecum[-1]) if eff.size else 0
        if total == 0:
            prow = np.empty(0, np.int64)
            matched = np.empty(0, bool)
            rsel = np.empty(0, np.int64)
        else:
            k = np.arange(total, dtype=np.int64)
            prow = np.searchsorted(ecum, k, side="right")
            within = k - (ecum[prow] - eff[prow])
            matched = within < cnt[prow]
            if r.n:
                idx = np.minimum(starts[prow] + within, rs.size - 1)
                rsel = rorder[idx]
            else:
                rsel = np.zeros(total, np.int64)
        out_cols = {nm: l.cols[nm].take(prow) for nm in l.names}
        for nm in op["r_extra"]:
            col = r.cols[nm]
            if r.n:
                taken = col.take(rsel)
                if outer and not matched.all() \
                        and taken.kind != "opaque":
                    miss = ~matched
                    nl = taken.null_mask(total).copy()
                    nl |= miss
                    taken.null = nl
                out_cols[nm] = taken
            else:
                out_cols[nm] = _null_extend(col, total)
        return ColumnarFrame(total, list(l.names) + op["r_extra"],
                             out_cols)

    def _x_agg(self, op) -> ColumnarFrame:
        f = self._frame(op)
        key_cols = [self._desc_col(f, d) for d in op["keys"]]
        codes: List[np.ndarray] = []
        for c in key_cols:
            codes.extend(col_codes(c, f.n))
        gid, reps = group_ids(codes, f.n)
        ng = int(reps.size)
        if not op["keys"] and ng == 0:
            # global aggregate over empty input: one all-zero count row
            names = [n for _, n in op["cols"]]
            cols = {n: ValCol(np.zeros(1, np.int64), None, "int")
                    for n in names}
            return ColumnarFrame(1, names, cols)
        names, cols = [], {}
        for spec, n in op["cols"]:
            names.append(n)
            if spec[0] == "key":
                cols[n] = key_cols[spec[1]].take(reps)
                continue
            _, d, distinct = spec
            if d is None:
                counts = np.bincount(gid, minlength=ng)
            else:
                c = self._desc_col(f, d)
                nn = ~c.null_mask(f.n)
                if not distinct:
                    counts = np.bincount(gid[nn], minlength=ng)
                else:
                    sub = np.flatnonzero(nn)
                    ccodes = [x[sub] for x in col_codes(c, f.n)]
                    _, reps2 = group_ids([gid[sub]] + ccodes,
                                         int(sub.size))
                    counts = np.bincount(gid[sub[reps2]], minlength=ng)
            cols[n] = ValCol(counts.astype(np.int64), None, "int")
        return ColumnarFrame(ng, names, cols)

    def _sort_key(self, f: ColumnarFrame, d: Tuple, asc: bool
                  ) -> np.ndarray:
        col = self._desc_col(f, d)
        if col.kind == "vid":
            vals = self.d2v[col.dense]
            col = ValCol(vals, col.null,
                         "int" if vals.dtype != object else "obj")
        if col.kind != "val":
            raise CannotCompile("sort key not a value column")
        try:
            codes = col_codes(col, f.n, ordered=True)[0]
        except TypeError:
            raise CannotCompile("sort key not totally ordered")
        ncodes = int(codes.max()) + 1 if codes.size else 0
        key = np.where(codes < 0, ncodes, codes)   # nulls last (asc)
        return key if asc else -key

    def _x_sort(self, op) -> ColumnarFrame:
        f = self._frame(op)
        keys = [self._sort_key(f, d, asc) for d, asc in op["factors"]]
        order = np.lexsort(keys[::-1]) if keys \
            else np.arange(f.n, dtype=np.int64)
        if "count" in op:
            off = op.get("offset", 0) or 0
            cnt = op.get("count")
            end = None if cnt is None or cnt < 0 else off + cnt
            order = order[off:end]
        return f.take(order)

    def _x_limit(self, op) -> ColumnarFrame:
        f = self._frame(op)
        off = op.get("offset", 0) or 0
        cnt = op.get("count")
        end = f.n if cnt is None or cnt < 0 else min(f.n, off + cnt)
        return f.take(np.arange(off, max(off, end), dtype=np.int64))

    def _x_result(self, op):
        f = self._frame(op)
        arrays = [materialize_column(f.col(nm), f.n, self.qctx,
                                     self.space, self.d2v)
                  for nm in op["cols"]]
        return ColumnarDataSet(list(op["cols"]), arrays)

    def _merge_stats(self, st):
        s = self.stats
        s.hop_edges.extend(st.hop_edges)
        s.frontier_sizes.extend(st.frontier_sizes)
        s.result_edges += st.result_edges
        s.steps += st.steps
        s.retries += st.retries
        s.f_cap = st.f_cap          # bucket shapes: report the last chain's
        s.e_cap = st.e_cap
        s.compiles += getattr(st, "compiles", 0)
        s.hbm_bytes = max(s.hbm_bytes, getattr(st, "hbm_bytes", 0))
        for ph in ("pin_s", "put_s", "fetch_s", "mat_s", "device_s",
                   "total_s", "queue_s"):
            setattr(s, ph, getattr(s, ph) + getattr(st, ph, 0.0))


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


def _run_subplan(root: PlanNode, qctx, ectx, space):
    """Inline host interpreter for the stashed original subtree: every
    node in deps-then-from_var order, results registered under the
    nodes' own output vars (the scheduler's sequencing contract)."""
    order: List[PlanNode] = []
    seen: set = set()
    by_var: Dict[str, PlanNode] = {}
    for n in walk_plan(root):
        by_var[n.output_var] = n

    def rec(n: PlanNode):
        if n.id in seen:
            return
        seen.add(n.id)
        fv = n.args.get("from_var") if n.args else None
        if fv and fv in by_var:
            rec(by_var[fv])
        for d in n.deps:
            rec(d)
        order.append(n)

    rec(root)
    ds = DataSet()
    for n in order:
        ds = run_node(n, qctx, ectx, space)
        ectx.set_result(n.output_var, ds)
    return ds


def _dispatch_overloaded() -> bool:
    """Device dispatch-queue depth cap (ISSUE 10): beyond
    `tpu_dispatch_queue_cap` queued dispatches, fused pipelines degrade
    to their stashed host subplan instead of piling onto the device —
    never wrong, only slower.  0 (the default) disables the cap."""
    try:
        cap = int(get_config().get("tpu_dispatch_queue_cap"))
    except Exception:  # noqa: BLE001 — config not initialized
        return False
    if cap <= 0:
        return False
    from ..utils.workload import dispatch_table
    if dispatch_table().queued_depth() < cap:
        return False
    stats().inc("tpu_dispatch_queue_shed")
    return True


@executor("TpuMatchPipeline")
def _tpu_match_pipeline(node, qctx, ectx, space):
    a = node.args
    rt = getattr(qctx, "tpu_runtime", None)
    reason = "no-runtime"
    if rt is not None and get_config().get("tpu_match_device") \
            and _dispatch_overloaded():
        reason = "overload"
        rt = None       # fall through to the stashed host subplan
    if rt is not None and get_config().get("tpu_match_device"):
        try:
            with trace.span("tpu:match_pipeline",
                            segments=len(a["ops"]),
                            chains=a.get("n_chains", 0)):
                runner = _Runner(qctx, ectx, rt, a["space"])
                ds = runner.run(a["ops"])
            qctx.last_tpu_stats = runner.stats
            stats().inc("match_pipeline_fused")
            return ds
        except (CannotCompile, TpuUnavailable, FailpointError) \
                + _JAX_RT_ERRORS as ex:
            # FailpointError here is the injected device-dispatch
            # failure (chaos schedule 5): same contract as a real
            # runtime fault — fall back to the stashed row subplan.
            # QueryKilled/DeadlineExceeded are NOT in this tuple: a
            # killed statement must die, not fall back.
            qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
            reason = f"runtime:{type(ex).__name__}"
    elif rt is not None:
        reason = "device-flag-off"
    stats().inc_labeled("match_pipeline_fallback",
                        {"stage": "execute", "reason": reason})
    return _run_subplan(a["fallback"].node, qctx, ectx, space)
