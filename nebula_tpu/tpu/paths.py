"""FIND SHORTEST PATH on device: BFS kernel + host path reconstruction.

The device computes per-vertex BFS depth (tpu/bfs.py); the host then
walks predecessors (dist[u] == dist[v]-1 along the reversed direction)
to enumerate ALL shortest paths — the exact path set of the host
oracle's multi-parent BFS (exec/algorithms.py::find_path_host), which
the parity tests assert row-for-row.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.value import DataSet, Edge, hashable_key
from ..exec.algorithms import (_vids_from, make_path_fn, make_vertex_fn,
                               sort_path_rows)

_REVERSE = {"out": "in", "in": "out", "both": "both"}


def find_shortest_device(node, qctx, ectx) -> DataSet:
    a = node.args
    space = a["space"]
    etypes = a["edge_types"]
    direction = a["direction"]
    upto = a["upto"]
    filt = a.get("filter")
    if filt is not None:
        # the mask compiler resolves props against ONE block's schema;
        # multi-etype prop predicates (or non-vectorizable ones) must
        # fall back BEFORE touching the kernel (same gate as the other
        # device drivers) — raises CannotCompile for the executor
        from .exprjit import CannotCompile, compilable
        if not compilable(filt, etypes):
            raise CannotCompile(
                "shortest-path filter does not vectorize "
                "over these edge types")
    rt = qctx.tpu_runtime
    store = qctx.store
    cat = store.catalog
    etype_ids = {e: cat.get_edge(space, e).edge_type for e in etypes}
    sd = store.space(space)

    def edge_ok(e: Edge) -> bool:
        """Host-side re-check during path reconstruction — the device
        mask pruned reachability, but predecessors are rediscovered by
        reverse scans which must apply the same filter."""
        if filt is None:
            return True
        from ..core.expr import to_bool3
        from ..exec.context import RowContext
        rc = RowContext(qctx, space,
                        {"_src": e.src, "_edge": e, "_dst": e.dst})
        return to_bool3(filt.eval(rc)) is True

    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    srcs = _vids_from(a, "src_vids", "src_ref", ectx)
    dsts = _vids_from(a, "dst_vids", "dst_ref", ectx)

    mk_vertex = make_vertex_fn(qctx, space, bool(a.get("with_prop")))
    path_of = make_path_fn(mk_vertex)

    rev = _REVERSE[direction]
    col = node.col_names[0]
    rows: List[List[Any]] = []

    for s in srcs:
        dist, stats = rt.bfs(store, space, [s], etypes, direction, upto,
                             edge_filter=filt)
        qctx.last_tpu_stats = stats      # PROFILE breadcrumb
        P = dist.shape[0]

        def depth_of(vid) -> int:
            d = sd.dense_id(vid)
            if d < 0:
                return -1
            return int(dist[d % P, d // P])

        def preds(v, lv):
            """(u, Edge-as-forward) wherein dist[u] == lv-1."""
            for (vv, et, rank, u, props, sdir) in store.get_neighbors(
                    space, [v], etypes, rev):
                if depth_of(u) == lv - 1:
                    eid = etype_ids[et]
                    # reverse-sd → forward edge sign (see bfs.py parity)
                    e = Edge(u, v, et, rank, dict(props),
                             etype=eid if sdir < 0 else -eid)
                    if edge_ok(e):
                        yield u, e

        memo: Dict[Any, List[Tuple[List[Any], List[Edge]]]] = {}

        def all_paths_to(v) -> List[Tuple[List[Any], List[Edge]]]:
            kv = hashable_key(v)
            if kv in memo:
                return memo[kv]
            lv = depth_of(v)
            if lv == 0:
                memo[kv] = [([v], [])]
                return memo[kv]
            out = []
            for (u, e) in preds(v, lv):
                for (vc, ec) in all_paths_to(u):
                    out.append((vc + [v], ec + [e]))
            memo[kv] = out
            return out

        ks = hashable_key(s)
        for d in dsts:
            if hashable_key(d) == ks:
                continue
            lv = depth_of(d)
            if 0 < lv <= upto:
                for (vc, ec) in all_paths_to(d):
                    rows.append([path_of(vc, ec)])

    sort_path_rows(rows)
    return DataSet([col], rows)
