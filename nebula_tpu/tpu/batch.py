"""Multi-query batch former: drain concurrent compatible dispatches
into one padded multi-lane kernel launch (ISSUE 15 tentpole).

Under concurrency every small GO/MATCH statement used to pay its own
device dispatch — the PR 7 concurrency bench measures `queue_wait_share`
for exactly that, and the PR 8 admission wait queue already RELEASES
compatible statements in bursts that nobody exploited.  This module is
the missing half: when K statements that would compile to the SAME
device program (kernel family + shape bucket + predicate/yield program
— the compatibility key the runtime derives from its jit-cache key)
reach the dispatch boundary together, they enroll in a forming GROUP;
after a bounded `batch_wait_us` window (or as soon as the group fills
to `batch_max_lanes`) ONE member launches a single lane-batched kernel
(`hop.build_traverse_fn_lanes` on a single chip, or the lanes × shards
`hop.build_traverse_fn_lanes_sharded` grid program on a multi-device
mesh — PR 17) for everyone, and each member de-muxes its own lane back
out through the per-statement attribution machinery (rows, WorkCounters,
cost sinks, flight entries stay exactly per-statement — the PR 7
concurrent-attribution contract).

Mesh composition (PR 17): the compatibility key the runtime submits
INCLUDES the mesh identity — (lanes, parts, mesh epoch) via
`TpuRuntime._mesh_key()` — so a `set_mesh` re-shard mid-form can never
merge lanes compiled for different launch grids: members enrolled
against the old grid keep their group (its key names the old epoch)
while post-re-shard arrivals form a NEW group under the bumped epoch.
If the old group's launch runs after the re-shard donated its
snapshot's buffers, the runtime's retired-snapshot check surfaces
TpuUnavailable to every member, which take their usual re-pin/host
fallback — never a silently merged cross-grid launch.

Design points:

  * `batch_max_lanes = 0` (the default) is the OFF switch — the former
    is never consulted and the dispatch path is byte-identical to the
    pre-batching runtime.
  * No dedicated thread and no leader hand-off: every member waits on
    the group condition; whichever member's wait expires first CLAIMS
    the launch (group state FORMING → LAUNCHING → DONE).  A member
    killed or deadline-expired while FORMING withdraws (its lane never
    launches); once LAUNCHING, a cancelled member's lane rides along
    and its result is simply discarded at de-mux — batchmates complete
    unaffected either way.
  * Single-query latency is preserved: a statement only waits the
    forming window when there is EVIDENCE of concurrency — another
    forming group member, >1 live statement, or a recent multi-
    statement admission drain burst (`AdmissionController.
    concurrency_hint()`, the admission→former hand-off).  A lone
    statement takes the solo dispatch path untouched.
  * One batched launch consumes ONE dispatch-table slot (the launcher's
    `_gated_dispatch`), so `tpu_dispatch_queue_cap` judges batches, not
    lanes — turning batching ON can only DECREASE the host-shed rate
    (ISSUE 15 satellite; regression-tested).

Metrics: `tpu_batches_formed`, `tpu_batch_lanes`,
`tpu_batch_form_wait_us`; span `tpu:batch` (emitted by the runtime's
lane escalation); failpoint `tpu:batch_form` at the enrollment boundary
(`raise` = this statement dispatches solo, `delay` = held forming).
Docs: docs/PERFORMANCE.md §10, docs/OBSERVABILITY.md catalogues,
docs/ROBUSTNESS.md failpoint table.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils import cancel as _cancel
from ..utils.config import define_flag, get_config
from ..utils.failpoints import fail

define_flag("batch_max_lanes", 0,
            "max statements coalesced into one multi-lane device "
            "launch; 0/1 = batching OFF (byte-identical to the "
            "pre-batching dispatch path); runtime-updatable via "
            "UPDATE CONFIGS")
define_flag("batch_wait_us", 1500,
            "bounded batch-forming window: a dispatch with concurrent "
            "compatible company waits at most this long for "
            "batchmates before launching (the group launches early "
            "the moment it fills to batch_max_lanes); runtime-"
            "updatable via UPDATE CONFIGS")

_FORMING, _LAUNCHING, _DONE = 0, 1, 2


class _Member:
    __slots__ = ("dense", "withdrawn", "lane", "t_enq", "live")

    def __init__(self, dense: Sequence[int], live):
        self.dense = list(dense)
        self.withdrawn = False
        self.lane: Optional[int] = None   # assigned at launch claim
        self.t_enq = time.monotonic()
        self.live = live


class _Group:
    __slots__ = ("key", "bid", "cond", "state", "deadline", "ready",
                 "members", "res", "info", "error", "t_launch")

    def __init__(self, key, bid: int, deadline: float):
        self.key = key
        self.bid = bid
        self.cond = threading.Condition()
        self.state = _FORMING
        self.deadline = deadline
        self.ready = False            # filled to batch_max_lanes
        self.members: List[_Member] = []
        self.res = None
        self.info: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.t_launch = deadline


class LaneResult:
    """One statement's slice of a shared launch: the lane index into
    the batched result arrays plus the launch-level info the runtime
    needs for per-lane attribution."""

    __slots__ = ("lane", "res", "info", "form_wait_us", "lanes",
                 "batch_id")

    def __init__(self, lane: int, res, info, form_wait_us: int,
                 lanes: int, batch_id: int):
        self.lane = lane
        self.res = res
        self.info = info
        self.form_wait_us = form_wait_us
        self.lanes = lanes
        self.batch_id = batch_id


class BatchFormer:
    """Process-wide: groups compatible in-flight dispatches per key and
    runs each group as one lane-batched launch."""

    #: waiter poll slice while forming/awaiting launch — the cadence of
    #: the KILL/deadline re-check (same rationale as the admission
    #: controller's POLL_S: "detaches immediately" stays honest)
    POLL_S = 0.005

    def __init__(self):
        self._mu = threading.Lock()
        self._groups: Dict[Any, _Group] = {}
        self._bid = 0

    # -- flags ------------------------------------------------------------

    @staticmethod
    def _flag_int(name: str, dflt: int) -> int:
        try:
            return int(get_config().get(name))
        except Exception:  # noqa: BLE001 — config not initialized
            return dflt

    def max_lanes(self) -> int:
        return self._flag_int("batch_max_lanes", 0)

    def wait_s(self) -> float:
        return max(self._flag_int("batch_wait_us", 1500), 0) / 1e6

    def enabled(self) -> bool:
        # one lane cannot share anything: <=1 is the off sentinel
        return self.max_lanes() > 1

    # -- the admission→former hand-off ------------------------------------

    @staticmethod
    def _concurrency_hint() -> bool:
        """Is there evidence that batchmates may arrive?  Without any,
        the statement dispatches solo with ZERO added latency — the
        forming window only ever delays statements that provably have
        concurrent company."""
        from ..utils.workload import live_registry
        if len(live_registry()) > 1:
            return True
        from ..utils.admission import admission
        return admission().concurrency_hint()

    # -- enrollment --------------------------------------------------------

    def submit(self, key, dense: Sequence[int],
               launch: Callable[[List[List[int]]], Any],
               kernel: str = "traverse",
               gate_busy: Optional[Callable[[], bool]] = None
               ) -> Optional[LaneResult]:
        """Enroll one dispatch under `key`.  Returns the statement's
        LaneResult after the shared launch, or None when the caller
        should dispatch solo (batching off / no concurrency evidence /
        lost a forming race).  `launch(lane_dense)` runs the actual
        lane-batched escalation and returns (res, info) — called by
        exactly ONE member per group.  Raises QueryKilled /
        DeadlineExceeded when THIS statement is cancelled (mid-form:
        its lane withdraws before launch; mid-flight: its lane's
        result is discarded) and re-raises the launch error to every
        member when the shared launch fails.

        `gate_busy` (optional) probes the runtime's dispatch gate: a
        group whose forming window expires while a writer holds the
        gate (re-pin / delta apply / compaction swap) RE-ARMS the
        window instead of launching — launching would only queue the
        fully-formed batch behind the hold with `batch_wait_us`
        already spent, while statements arriving during the hold piled
        into fresh groups (ISSUE 19 satellite)."""
        max_lanes = self.max_lanes()
        if max_lanes <= 1:
            return None
        # failpoint at the enrollment boundary: `raise` rejects
        # batching for this statement (it dispatches solo — never
        # wrong, never host-fallback), `delay` holds it here
        fail.hit("tpu:batch_form", key=kernel)
        with self._mu:
            g = self._groups.get(key)
            join = (g is not None and g.state == _FORMING
                    and len(g.members) < max_lanes)
            if not join and not self._concurrency_hint():
                return None     # solo fast path: no company, no wait
            if not join:
                self._bid += 1
                g = _Group(key, self._bid,
                           time.monotonic() + self.wait_s())
                self._groups[key] = g
            from ..utils.workload import current_live
            lv = current_live()
            m = _Member(dense, lv)
            g.members.append(m)
            lane_provisional = len(g.members) - 1
            if len(g.members) >= max_lanes:
                g.ready = True
        if lv is not None:
            # SHOW QUERIES shows BatchId/lane while enrolled (ISSUE 15
            # satellite); the launch claim re-stamps the final lane
            lv.batch_id, lv.lane = g.bid, lane_provisional
        try:
            return self._wait_and_demux(key, g, m, launch, kernel,
                                        gate_busy)
        finally:
            if lv is not None:
                lv.batch_id, lv.lane = None, None

    def _wait_and_demux(self, key, g: _Group, m: _Member, launch,
                        kernel: str, gate_busy=None
                        ) -> Optional[LaneResult]:
        launcher = False
        with g.cond:
            while g.state != _DONE:
                if g.state == _FORMING and (
                        g.ready or time.monotonic() >= g.deadline):
                    if not g.ready and gate_busy is not None \
                            and gate_busy():
                        # window expired under a write-gate hold: re-arm
                        # so the group keeps forming through the hold
                        # and gets a FRESH window once the gate frees
                        # (a full group skips this — it cannot grow, so
                        # it may as well queue at the gate).  One waiter
                        # moves the deadline per expiry: the loop holds
                        # g.cond, so re-arms are serialized.
                        g.deadline = time.monotonic() + self.wait_s()
                        from ..utils.stats import stats
                        stats().inc("tpu_batch_gate_rearms")
                        continue
                    g.state = _LAUNCHING
                    launcher = True
                    break
                kill = _cancel.current_kill()
                if kill is not None and kill.is_set():
                    forming = g.state == _FORMING
                    self._withdraw(key, g, m)
                    raise _cancel.QueryKilled(
                        "query was killed while batch-forming"
                        if forming else
                        "query was killed awaiting a batched launch")
                rem = _cancel.remaining()
                if rem is not None and rem <= 0:
                    self._withdraw(key, g, m)
                    raise _cancel.DeadlineExceeded(
                        "deadline exhausted while batch-forming")
                timeout = self.POLL_S
                if g.state == _FORMING and not g.ready:
                    timeout = min(timeout, max(
                        g.deadline - time.monotonic(), 0.0) + 1e-4)
                g.cond.wait(timeout)
        if launcher:
            self._launch(key, g, launch, kernel)
        return self._demux(g, m)

    def _withdraw(self, key, g: _Group, m: _Member):
        """Mark a forming member withdrawn (caller holds g.cond and
        raises right after).  A group left with NO live members has no
        future launcher — remove it from the forming map so the next
        compatible statement opens a FRESH group instead of joining an
        expired husk (and so space/epoch-churned keys cannot leak
        all-withdrawn groups).  Taking self._mu under g.cond is safe:
        no thread ever blocks on g.cond while holding self._mu."""
        m.withdrawn = True
        if g.state == _FORMING and all(mm.withdrawn
                                       for mm in g.members):
            g.state = _DONE
            with self._mu:
                if self._groups.get(key) is g:
                    del self._groups[key]
            g.cond.notify_all()

    def _demux(self, g: _Group, m: _Member) -> Optional[LaneResult]:
        # -- DONE: de-mux ---------------------------------------------
        if g.error is not None:
            # shared failure (escalation non-convergence, device fault):
            # every member surfaces the same error; executors apply
            # their usual fallback contract to it
            raise g.error
        kill = _cancel.current_kill()
        if kill is not None and kill.is_set():
            # mid-flight cancel: the lane launched, its result is
            # discarded right here — batchmates are untouched
            raise _cancel.QueryKilled("query was killed")
        rem = _cancel.remaining()
        if rem is not None and rem <= 0:
            raise _cancel.DeadlineExceeded(
                "deadline exhausted during a batched launch")
        if m.lane is None:
            # joined in the claim race window after lanes were frozen:
            # not part of the launch — dispatch solo instead
            return None
        from ..utils.stats import stats
        form_wait_us = int(max(g.t_launch - m.t_enq, 0.0) * 1e6)
        stats().observe("tpu_batch_form_wait_us", form_wait_us)
        return LaneResult(m.lane, g.res, g.info, form_wait_us,
                          lanes=g.info["lanes"] if g.info else 1,
                          batch_id=g.bid)

    def _launch(self, key, g: _Group, launch, kernel: str):
        """Run the shared launch for every non-withdrawn member.  The
        claiming member executes on its own thread; per-statement TLS
        attribution is suppressed inside (the runtime's lane
        escalation), and each member attributes its own lane at
        de-mux."""
        with self._mu:
            if self._groups.get(key) is g:
                del self._groups[key]   # new arrivals form a new group
        with g.cond:
            lanes = [mm for mm in g.members if not mm.withdrawn]
            for i, mm in enumerate(lanes):
                mm.lane = i
                if mm.live is not None:
                    mm.live.lane = i
                    if len(lanes) > 1:
                        # lane share for the insights registry (ISSUE
                        # 16): how many statements this launch was
                        # amortized across
                        mm.live.batch_lanes = len(lanes)
        g.t_launch = time.monotonic()
        try:
            if len(lanes) > 1:
                from ..utils.stats import stats
                stats().inc("tpu_batches_formed")
                stats().observe("tpu_batch_lanes", len(lanes))
                g.res, g.info = launch([mm.dense for mm in lanes])
            else:
                # a 1-lane "batch" shares nothing: leave res unset —
                # the lone member falls back to the SOLO dispatch path
                # (solo jit cache, no lane program, no batch metrics),
                # so a too-short forming window costs only the window
                for mm in lanes:
                    mm.lane = None
        except BaseException as ex:  # noqa: BLE001 — fan the error out
            g.error = ex
        finally:
            with g.cond:
                g.state = _DONE
                g.cond.notify_all()

    # -- introspection / tests ---------------------------------------------

    def forming(self) -> Dict[Any, int]:
        """key → enrolled member count of currently-forming groups."""
        with self._mu:
            return {k: len(g.members) for k, g in self._groups.items()
                    if g.state == _FORMING}

    def reset(self):
        """Test isolation: abandon forming groups.  Enrolled members
        wake with no lane assigned and fall back to solo dispatch
        (submit returns None) — nothing blocks, nothing errors."""
        with self._mu:
            groups = list(self._groups.values())
            self._groups.clear()
        for g in groups:
            with g.cond:
                if g.state == _FORMING:
                    g.state = _DONE
                    g.cond.notify_all()


_former = BatchFormer()


def batch_former() -> BatchFormer:
    """The process-wide former (the runtime submits; tests introspect)."""
    return _former
