"""TPU device plane: HBM-pinned CSR snapshots + sharded traversal kernels.

This package is the TPU-native replacement for the reference's storage
read hot path (per-request RocksDB prefix scans in GetNeighborsProcessor
plus the per-hop storage.thrift fan-out in StorageClient / TraverseExecutor;
reference: src/storage/query, src/clients/storage, src/graph/executor
[UNVERIFIED — empty mount, SURVEY §0]).  Design per SURVEY §7 step 5:

  * a `jax.sharding.Mesh(('part',))` with one graph partition per device;
  * the space's CSR snapshot `device_put` across the mesh (device.py);
  * a multi-hop traversal kernel under `shard_map`: per-hop local CSR
    expansion (vectorized segment gather), compiled predicate mask,
    sorted-unique dedup, hash routing + `lax.all_to_all` frontier
    re-shard over ICI (hop.py);
  * a predicate compiler lowering nGQL expression subtrees to jnp mask
    functions with exact three-valued-logic semantics (exprjit.py);
  * a runtime with power-of-two bucket escalation for dynamic frontier /
    expansion sizes (runtime.py);
  * the `TpuTraverse` fused plan node: executor + optimizer rule
    (traverse.py).

Importing this package enables 64-bit mode in jax: property columns are
int64 (epoch-millisecond timestamps etc. overflow int32).
"""
import jax

jax.config.update("jax_enable_x64", True)

from .device import (DeviceSnapshot, make_mesh, make_mesh2,          # noqa: E402
                     mesh_lanes, mesh_parts, pin_snapshot)
from . import batch                                                  # noqa: E402  (defines the batch_* flags)
from .runtime import TpuRuntime                                      # noqa: E402
from . import traverse                                               # noqa: E402  (registers executor+rule)
from . import match_agg                                              # noqa: E402  (registers executor+rule)
from . import pipeline                                               # noqa: E402  (registers executor+rule; MUST follow match_agg — rule order)

__all__ = ["DeviceSnapshot", "make_mesh", "make_mesh2", "mesh_lanes",
           "mesh_parts", "pin_snapshot", "TpuRuntime"]
