"""TpuRuntime: snapshot pinning lifecycle + traversal dispatch.

Owns the mesh, the per-space DeviceSnapshots (epoch-checked against the
host store: a write bumps the space epoch, the next traversal re-pins —
the serve-epoch-N-while-building-N+1 model of SURVEY §7 hard-part #6 in
its simplest correct form), the jit cache keyed by bucket configuration,
and the power-of-two escalation loop around the hop kernel.

The host materialization contract: the device returns (src, dst, rank,
eidx, keep) per block; property decode happens on host straight out of
the numpy CsrSnapshot columns at eidx — properties cross HBM only when
a predicate needs them.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core import expr as E
from ..core.value import ColumnarDataSet, Edge
from ..graphstore.csr import (build_snapshot, decode_prop_column,
                              decode_prop_column_np)
from ..graphstore.delta import (DeltaOverflow, DeltaUnsupported, HostDelta,
                                pow2 as _delta_pow2)
from ..graphstore.store import GraphStore
from .device import (DeviceSnapshot, TpuUnavailable, make_mesh,
                     mesh_lanes, mesh_parts, pin_snapshot,
                     put_delta_blocks)
from .exprjit import (CannotCompile, compile_predicate, eval_yield_column,
                      eval_yield_column_np)
from .hop import (a2a_payload_bytes, build_traverse_fn,
                  build_traverse_fn_lanes, build_traverse_fn_lanes_sharded,
                  build_traverse_fn_local)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _d2v(host) -> np.ndarray:
    """Cached dense-id → vid array for batch vid decode (shared by the
    GO materializer and the MATCH frame builder).  INT64 when every vid
    is an int (the common case — object-array gathers over millions of
    result edges cost ~10× an int64 gather), object otherwise."""
    arr = getattr(host, "_d2v_arr", None)
    if arr is None or len(arr) != len(host.dense_to_vid):
        d2v = host.dense_to_vid
        # gate on an ACTUAL int vid: np.asarray would happily parse
        # digit STRINGS ('12' → 12), silently retyping FIXED_STRING
        # results — a space's vids are homogeneous, so one sample
        # decides (None slots are deleted vids → object path)
        sample = next((v for v in d2v if v is not None), None)
        if isinstance(sample, int) and not isinstance(sample, bool):
            try:
                arr = np.asarray(d2v, dtype=np.int64)
            except (TypeError, ValueError, OverflowError):
                arr = np.asarray(d2v, dtype=object)
        else:
            arr = np.asarray(d2v, dtype=object)
        # sequential-int-vid spaces (LDBC-style imports, the array
        # ingest path) have dense == vid: one cached pass here lets the
        # materializers skip a multi-million-row identity gather per
        # query (~0.65 s at north-star scale on the bench host).
        # Identity flag is published BEFORE the array: a concurrent
        # reader that sees the cached array must also see the flag.
        host._d2v_identity = bool(
            arr.dtype.kind == "i"
            and (arr == np.arange(len(arr), dtype=arr.dtype)).all())
        host._d2v_arr = arr
    return arr


def _cap_keys_for_yields(yields, device_props=()) -> Optional[set]:
    """Which capture arrays a yield list reads: a subset of {'src',
    'dst','rank','eidx'} plus 'prop:<name>' for props the kernel
    gathers on device, or None (fetch everything) when a yield isn't
    fully recognized.  Mirrors eval_yield_column_np's access pattern."""
    if yields is None:
        return None
    need = set()
    for e, _ in yields:
        for x in E.walk(e):
            k = x.kind
            # exactly the kinds the fusion gate (exprjit.yieldable)
            # admits — anything else means this walker is stale vs the
            # eval surface, so fetch everything
            if k in ("literal", "function", "edge_prop", "edge"):
                if k == "function":
                    name = getattr(x, "name", "")
                    if name == "src":
                        need.add("src")
                    elif name == "dst":
                        need.add("dst")
                    elif name == "rank":
                        need.add("rank")
                    elif name in ("type", "typeid"):
                        pass             # per-block constants
                    else:
                        return None      # unknown function: fetch all
                elif k == "edge_prop":
                    if x.name == "_rank":
                        need.add("rank")
                    elif x.name == "_src":
                        need.add("src")
                    elif x.name == "_dst":
                        need.add("dst")
                    elif x.name == "_type":
                        pass             # per-block constant
                    elif x.name in device_props:
                        need.add("prop:" + x.name)
                    else:
                        need.add("eidx")
            else:
                return None              # unmodeled expr: fetch all
    return need


def _cat_parts(parts, dtype=None):
    """Concatenate per-part kept-prefix slices of a capture array (the
    device compacts kept entries to the front of each part row) —
    contiguous slices instead of a 2D fancy gather, preserving
    (part, slot) order.  Always returns an owned array: a view of the
    K-padded capture buffer must not escape into long-lived results
    (it would pin the whole bucket for a handful of rows)."""
    if dtype is not None:
        if len(parts) > 1:
            return np.concatenate(parts, dtype=dtype)   # one pass
        return parts[0].astype(dtype)
    if len(parts) > 1:
        return np.concatenate(parts)
    return parts[0].copy()


def _cat_prefix(arr, bi, pids, kc, dtype=None):
    return _cat_parts([arr[p, bi, :kc[p]] for p in pids], dtype)


class _DispatchGate:
    """Read-write gate serializing device dispatch against snapshot
    re-pin (ISSUE 9 satellite: the serve-while-repin fix).

    jaxlib's CPU client has a latent race where concurrent jitted
    dispatches can deadlock against a device_put re-pinning a bumped
    epoch (CHANGES.md PR 6 note: both reader threads blocked inside
    the jitted call, no Python-level locks held).  Dispatches are
    READERS — they share, so concurrent queries still overlap on the
    chip — and a re-pin is the WRITER: it waits for in-flight
    dispatches to drain and excludes new ones while the put runs.
    Writer preference (a waiting writer blocks NEW readers) so a
    steady dispatch stream cannot starve the epoch bump forever.

    acquire_* returns the seconds spent waiting — the dispatch side's
    wait is the statement's queue time (tpu_dispatch_queue_us)."""

    __slots__ = ("_cond", "_readers", "_writer", "_writers_waiting")

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> float:
        t0 = time.perf_counter()
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        return time.perf_counter() - t0

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> float:
        t0 = time.perf_counter()
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
                self._writer = True
            finally:
                self._writers_waiting -= 1
        return time.perf_counter() - t0

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    def write_held(self) -> bool:
        """True while a writer holds or waits for the gate — the batch
        former's probe (ISSUE 19 satellite): a formed multi-lane batch
        would otherwise queue its whole batch_wait_us budget behind the
        writer, so the former re-arms its window instead."""
        with self._cond:
            return bool(self._writer or self._writers_waiting)


class TraverseStats:
    __slots__ = ("hop_edges", "frontier_sizes", "result_edges", "f_cap",
                 "e_cap", "retries", "device_s", "steps",
                 "pin_s", "put_s", "fetch_s", "mat_s", "total_s",
                 "compiles", "hbm_bytes", "segments", "queue_s",
                 "shards", "exchange_bytes")

    def __init__(self):
        self.hop_edges: List[int] = []
        self.frontier_sizes: List[int] = []   # popcount entering each hop
        self.result_edges = 0
        self.f_cap = 0
        self.e_cap = 0
        self.retries = 0
        self.device_s = 0.0
        self.steps = 0
        # per-phase wall time (PROFILE device-plane fields)
        self.pin_s = 0.0
        self.put_s = 0.0
        self.fetch_s = 0.0
        self.mat_s = 0.0
        self.total_s = 0.0
        # kernel-ledger fields (ISSUE 8): fresh XLA compiles this run
        # paid for (vs jit-cache hits) and the HBM high-water at
        # dispatch time; `segments` carries per-segment rows for fused
        # pipelines (tpu/pipeline.py fills it)
        self.compiles = 0
        self.hbm_bytes = 0
        self.segments: List[dict] = []
        # dispatch-gate wait before the kernel could run (ISSUE 9):
        # the queue-wait half of the wait-vs-run decomposition
        self.queue_s = 0.0
        # mesh facts (PR 17): part-axis shards this dispatch spanned and
        # the bit-packed frontier all_to_all payload it moved (0 in
        # single-chip local mode — there is no exchange)
        self.shards = 1
        self.exchange_bytes = 0

    def edges_traversed(self) -> int:
        return int(sum(self.hop_edges))


class HopFrame:
    """One hop's captured edge set, columnar, indexed for path assembly.

    src/dst: (n,) int64 dense vertex ids in capture order (block-major,
    then part, then per-src CSR slot order — matching the host
    get_neighbors iteration).  Edge OBJECTS are decoded lazily: the
    vectorized trail assembly touches only the entries that land on an
    emitted path, and the full `.edges` object array is built only for
    the DFS consumers (algorithms.py) that ask for it.

    Trail-dedup identity is columnar too: (key_et, key_s, key_d, rank)
    is the canonical physical-edge key (reverse-direction copies of one
    logical edge canonicalize equal), compared component-wise — no
    per-edge Python hashing.
    """
    __slots__ = ("src", "dst", "rank", "n", "order", "_us", "_ustart",
                 "_ucnt", "key_et", "key_s", "key_d",
                 "_segs", "_decode_seg", "_eobjs", "_edone", "_all_done")

    @classmethod
    def empty(cls) -> "HopFrame":
        f = cls()
        f.src = np.empty((0,), np.int64)
        f.dst = np.empty((0,), np.int64)
        f.rank = np.empty((0,), np.int64)
        f.key_et = np.empty((0,), np.int64)
        f.key_s = np.empty((0,), np.int64)
        f.key_d = np.empty((0,), np.int64)
        f.n = 0
        f.order = np.empty((0,), np.int64)
        f._us = np.empty((0,), np.int64)
        f._ustart = np.empty((0,), np.int64)
        f._ucnt = np.empty((0,), np.int64)
        f._segs = []
        f._decode_seg = None
        f._eobjs = np.empty((0,), object)
        f._edone = None
        f._all_done = True
        return f

    @classmethod
    def build(cls, src, dst, rank, key_et, key_s, key_d, segs,
              decode_seg) -> "HopFrame":
        """segs: list of (seg_start, seg_end, payload); decode_seg(
        payload, offsets) -> list[Edge] decodes a segment's entries at
        `offsets` (segment-relative)."""
        if src is None or src.size == 0:
            return cls.empty()
        f = cls()
        f.src, f.dst, f.rank = src, dst, rank
        f.key_et, f.key_s, f.key_d = key_et, key_s, key_d
        f.n = src.size
        f.order = np.argsort(src, kind="stable")
        ss = src[f.order]
        starts = np.flatnonzero(np.concatenate(
            [[True], ss[1:] != ss[:-1]]))
        f._us = ss[starts]
        f._ustart = starts
        f._ucnt = np.diff(np.concatenate([starts, [ss.size]]))
        f._segs = segs
        f._decode_seg = decode_seg
        f._eobjs = None
        f._edone = None
        f._all_done = False
        return f

    def out_edges(self, dense_id: int):
        """Indices (into src/dst/edges) of this hop's edges out of
        dense_id, in CSR order."""
        p = np.searchsorted(self._us, dense_id)
        if p >= self._us.size or self._us[p] != dense_id:
            return ()
        return self.order[self._ustart[p]:self._ustart[p]
                          + self._ucnt[p]]

    def src_slices(self):
        """(us, ustart, ucnt): sorted unique srcs with their slice into
        `order` — the vectorized join's lookup table."""
        return self._us, self._ustart, self._ucnt

    def decode(self, idx: np.ndarray) -> np.ndarray:
        """Edge objects for frame indices `idx` (object array, aligned
        with idx).  Decodes each entry at most once across calls."""
        if self._eobjs is None:
            self._eobjs = np.full((self.n,), None, dtype=object)
            self._edone = np.zeros((self.n,), bool)
        eo = self._eobjs
        if idx.size:
            uniq = np.unique(idx)
            need = uniq[~self._edone[uniq]]
            for (s0, s1, payload) in self._segs:
                m = need[(need >= s0) & (need < s1)]
                if m.size == 0:
                    continue
                eo[m] = self._decode_seg(payload, m - s0)
                self._edone[m] = True
        return eo[idx]

    @property
    def edges(self) -> np.ndarray:
        """All Edge objects (decodes the whole frame once) — the DFS
        consumers' (algorithms.py) contract.  O(1) once fully decoded
        (ADVICE r3: per-access `_edone.all()` made DFS replay O(n²))."""
        if not self._all_done:
            self.decode(np.arange(self.n, dtype=np.int64))
            self._all_done = True
        return self._eobjs


def join_frontier_trails(fr: "HopFrame", last: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """One searchsorted join of per-trail endpoints against a frame's
    src index.  Returns (parent, fidx): for every (trail, edge)
    continuation, the trail's index into `last` and the frame entry —
    in frame CSR order within each trail.  Shared by the unfused MATCH
    Traverse executor and the fused TpuMatchAgg assembly (single
    source for the join's edge cases)."""
    us, ustart, ucnt = fr.src_slices()
    p = np.searchsorted(us, last)
    p = np.minimum(p, max(us.size - 1, 0))
    hit = us[p] == last
    cnt = np.where(hit, ucnt[p], 0)
    start = np.where(hit, ustart[p], 0)
    ends = np.cumsum(cnt)
    total = int(ends[-1]) if cnt.size else 0
    if total == 0:
        return (np.empty(0, np.int64), np.empty(0, np.int64))
    k = np.arange(total, dtype=np.int64)
    parent = np.searchsorted(ends, k, side="right")
    within = k - (ends[parent] - cnt[parent])
    fidx = fr.order[start[parent] + within]
    return parent, fidx


def trail_distinct_keep(frames: List["HopFrame"], path: List[np.ndarray],
                        parent: np.ndarray, fr: "HopFrame",
                        fidx: np.ndarray) -> np.ndarray:
    """Relationship-uniqueness mask: for each candidate continuation,
    compare the new edge's canonical key against every earlier hop of
    its trail (componentwise over the frames' key columns)."""
    keep = np.ones(fidx.size, bool)
    for eh, pe in enumerate(path):
        pf = frames[eh]
        pidx = pe[parent]
        keep &= ~((pf.key_et[pidx] == fr.key_et[fidx])
                  & (pf.key_s[pidx] == fr.key_s[fidx])
                  & (pf.key_d[pidx] == fr.key_d[fidx])
                  & (pf.rank[pidx] == fr.rank[fidx]))
    return keep


class TpuRuntime:
    """One per process; holds the mesh and all pinned spaces."""

    def __init__(self, mesh=None, n_devices: Optional[int] = None):
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.mesh_size = mesh_parts(self.mesh)
        self.mesh_lanes = mesh_lanes(self.mesh)
        self.local_mode = self.mesh_size == 1
        # bumped by set_mesh: part of every lane-batch compatibility key
        # so lanes compiled for different launch grids never merge
        # (PR 12 composition fix)
        self._mesh_epoch = 0
        self.snapshots: Dict[str, DeviceSnapshot] = {}
        self._fns: Dict[Tuple, Any] = {}
        # program key → last kept-prefix fetch size: arms the
        # speculative single-phase result fetch (one device round trip
        # instead of two for repeat query shapes); in-memory only
        self._kmax: Dict[Tuple, int] = {}
        # seed-bitmap builder programs (bounded separately from _fns:
        # space-keyed pruning does not reach these target/vmax keys) and
        # the (key, pad bucket) pairs already compiled — the warm call
        # runs outside put_s so the metric stays transfer-only
        self._seed_fns: Dict[Tuple, Any] = {}
        self._seed_warm: set = set()
        # program → last converged (0, EB): repeat queries start AT the
        # converged bucket instead of re-climbing the escalation ladder
        # (the ladder re-runs the kernel once per rung, per query).
        # Value stays a 2-tuple for cache-file compat; slot 0 (the old
        # frontier bucket F) is always 0 with the bitmap frontier.
        self._buckets: Dict[Tuple, Tuple[int, int]] = {}
        # optional cross-process persistence (NEBULA_BUCKET_CACHE=path):
        # each escalation rung is a fresh XLA compile (~100s on a
        # tunneled chip) — a repeat bench/driver run should start at the
        # previously converged sizes, not re-climb
        import os as _os
        self._buckets_path = _os.environ.get("NEBULA_BUCKET_CACHE")
        if self._buckets_path:
            try:
                import ast as _ast
                import json as _json
                with open(self._buckets_path) as f:
                    # keys are repr'd tuples of primitives; literal_eval
                    # (never eval/pickle — the path is configurable)
                    self._buckets = {_ast.literal_eval(k): tuple(v)
                                     for k, v in _json.load(f).items()}
            except Exception:  # noqa: BLE001 — absent/corrupt cache
                self._buckets = {}
        self.max_retries = 10
        # dispatch-vs-repin gate (ISSUE 9): dispatches share, re-pins
        # exclude — see _DispatchGate
        self._gate = _DispatchGate()
        # collective-launch mutex (PR 17): a sharded program carries
        # all_to_all/psum rendezvous over the mesh; two such programs
        # running CONCURRENTLY on overlapping devices interleave their
        # rendezvous and deadlock (observed on the CPU virtual mesh,
        # same hazard on real ICI).  Local-mode programs are
        # collective-free and keep full dispatch concurrency.
        self._launch_mutex = threading.Lock()
        from ..utils.config import get_config
        # the bitmap frontier (round-4 redesign) has no size bucket;
        # the only escalating budget left is the per-block edge budget
        self.init_eb = int(get_config().get("tpu_init_edge_budget"))
        self.max_cap = 1 << 24          # escalation sanity bound

    # -- pinning ----------------------------------------------------------

    def _mesh_key(self) -> Tuple[int, int, int]:
        """(lanes, parts, epoch): the launch-grid identity every
        lane-batch compatibility key and bench A/B must carry."""
        return (self.mesh_lanes, self.mesh_size, self._mesh_epoch)

    def set_mesh(self, mesh) -> None:
        """Swap the runtime onto a different mesh (bench A/B, elastic
        re-shard).  Runs under the WRITE side of the dispatch gate:
        in-flight dispatches drain, every pinned snapshot's buffers are
        donated back (they are laid out for the OLD grid), the jit and
        seed caches drop, and the mesh epoch bumps so any batch group
        still forming against the old grid can never merge with lanes
        compiled for the new one."""
        self._gate.acquire_write()
        try:
            for dev in self.snapshots.values():
                dev.delete_buffers()
            self.snapshots.clear()
            self._fns.clear()
            self._kmax.clear()
            self._seed_fns.clear()
            self._seed_warm.clear()
            self.mesh = mesh
            self.mesh_size = mesh_parts(mesh)
            self.mesh_lanes = mesh_lanes(mesh)
            self.local_mode = self.mesh_size == 1
            self._mesh_epoch += 1
        finally:
            self._gate.release_write()
        self._emit_hbm_gauges()

    def _emit_hbm_gauges(self) -> None:
        """Re-state the HBM residency gauges: the total plus the
        per-shard ledger (`tpu_shard_hbm_bytes{shard}` summed over every
        pinned space) and the mesh width (`tpu_shards`).  Stale shard
        slots from a wider previous mesh are zeroed, not dropped —
        last-write-wins gauges would otherwise report a ghost shard."""
        from ..utils.stats import stats
        per: Dict[int, int] = {}
        for dev in self.snapshots.values():
            for p, b in dev.shard_hbm_bytes().items():
                per[p] = per.get(p, 0) + b
        st = stats()
        st.gauge("tpu_hbm_bytes_pinned", float(sum(per.values())))
        st.gauge("tpu_shards", float(self.mesh_size))
        known = st.labeled_gauges.get("tpu_shard_hbm_bytes", {})
        for p in range(self.mesh_size):
            st.gauge_labeled("tpu_shard_hbm_bytes", {"shard": p},
                             float(per.get(p, 0)))
        for key in list(known):
            shard = dict(key).get("shard")
            try:
                shard_i = int(shard)
            except (TypeError, ValueError):
                continue
            if shard_i >= self.mesh_size and shard_i not in per:
                st.gauge_labeled("tpu_shard_hbm_bytes",
                                 {"shard": shard_i}, 0.0)

    @staticmethod
    def _served_epoch(dev) -> int:
        """The store epoch a snapshot actually serves: the base pin
        epoch, advanced by every applied delta commit group."""
        return (dev.delta.applied_epoch if dev.delta is not None
                else dev.epoch)

    @staticmethod
    def _delta_flag() -> int:
        """Per-(block, part) delta edge capacity; 0 = delta plane off
        (byte-identical to the pre-delta runtime)."""
        from ..utils.config import get_config
        try:
            return int(get_config().get("tpu_delta_max_edges"))
        except Exception:  # noqa: BLE001 — config missing in odd embeds
            return 0

    @staticmethod
    def _delta_slack() -> int:
        from ..utils.config import get_config
        try:
            return max(int(get_config().get("tpu_delta_vmax_slack")), 0)
        except Exception:  # noqa: BLE001
            return 0

    def pin(self, store: GraphStore, space: str,
            force: bool = False) -> DeviceSnapshot:
        sd = store.space(space)
        cur = self.snapshots.get(space)
        # uid guards the (space-name, epoch) cache against a DIFFERENT
        # store object whose same-named space happens to share the epoch
        # value (one shared runtime + two stores served the wrong graph);
        # accessors without a uid (cluster _SpaceView, bench shims) keep
        # the plain epoch check
        if cur is not None and not force and getattr(
                cur, "space_uid", None) == getattr(sd, "uid", None):
            if self._served_epoch(cur) == sd.epoch:
                return cur
            if cur.delta is not None and hasattr(store, "delta_records"):
                # ISSUE 19 fast path: fold the dirty-key log into the
                # resident delta plane (one small put per commit group)
                # instead of a graph-sized rebuild + re-pin
                dev = self._try_delta_update(store, space, cur)
                if dev is not None:
                    return dev
        dflag = self._delta_flag()
        snap = self._build_fresh(store, space, dflag)
        self._check_hbm_budget(snap, space)
        # the device_put runs under the WRITE side of the dispatch
        # gate: in-flight dispatches drain first, new ones wait — the
        # jaxlib serve-while-repin race window is closed, and the
        # exclusive wait itself is telemetry (how long an epoch bump
        # waited on the serving plane)
        from ..utils.stats import stats
        wait_s = self._gate.acquire_write()
        try:
            # donate the replaced epoch's buffers BEFORE the new put so
            # peak HBM through a re-pin stays ~1x the snapshot, not 2x;
            # no dispatch can hold them (readers drained), and any
            # thread still carrying the old DeviceSnapshot object sees
            # `retired` under its next read gate and re-pins
            old = self.snapshots.get(space)
            if old is not None and not force and not old.retired \
                    and self._served_epoch(old) == sd.epoch \
                    and getattr(old, "space_uid", None) == getattr(
                        sd, "uid", None):
                # a concurrent first-touch pin of the same space won the
                # gate first — adopt its snapshot instead of retiring it
                # (retiring here would fail that thread's dispatch)
                return old
            if old is not None:
                old.delete_buffers()
            dev = pin_snapshot(snap, self.mesh)
            dev.space_uid = getattr(sd, "uid", None)
            self.snapshots[space] = dev
            # stale-epoch jitted fns are keyed by epoch; drop them
            self._fns = {k: v for k, v in self._fns.items()
                         if not (k[0] == space and k[1] != dev.epoch)}
            self._arm_delta(store, dev, snap, dflag)
        finally:
            self._gate.release_write()
        stats().observe("tpu_repin_wait_us", int(wait_s * 1e6))
        stats().inc("tpu_pins")
        self._emit_hbm_gauges()
        return dev

    def _build_fresh(self, store, space: str, dflag: int):
        """Build a CsrSnapshot for a full (re)pin.  When the delta plane
        is on, the store starts (or keeps) watching dirty keys BEFORE
        the export — a key noted between watch and export is merely
        re-read at apply time, so there is no lost-write window."""
        if dflag > 0 and hasattr(store, "delta_watch"):
            store.delta_watch(space)
        if hasattr(store, "build_csr_snapshot"):
            # cluster store: bulk per-part CSR export over RPC (the
            # north-star storage addition) instead of a local walk
            try:
                snap = store.build_csr_snapshot(space)
            except Exception as ex:  # noqa: BLE001 — RPC/meta errors
                # surface as device-unavailable so executors fall back
                # to the host path instead of failing the query
                raise TpuUnavailable(
                    f"cluster CSR export failed: {ex}") from ex
        else:
            snap = build_snapshot(
                store, space,
                vmax_extra=self._delta_slack() if dflag > 0 else 0)
        return self._maybe_degree_split(snap)

    def _arm_delta(self, store, dev, snap, dflag: int) -> None:
        """Allocate the EMPTY delta plane at pin time (gate held).
        Lazy allocation would change kernel input shapes on the first
        write and recompile every cached program; an empty plane costs
        one small put and compiles once.  Degree-split snapshots opt
        out: hub rows re-home edges, so delta row identity breaks."""
        if dflag <= 0 or getattr(snap, "hub_dense", None) is not None:
            return
        if not (hasattr(store, "delta_records")
                and hasattr(store, "delta_reader")):
            return
        put_delta_blocks(dev, HostDelta(snap, dflag))

    def _try_delta_update(self, store, space: str, cur):
        """Advance a delta-armed snapshot to the store's epoch without
        re-pinning.  Returns the snapshot on success, None to signal
        the full-rebuild path (log broken/overflow/unsupported key —
        the rebuild discards every partially-mutated mirror)."""
        rec = store.delta_records(space)
        if rec is None:
            return None
        _, _, floor = rec
        if floor > cur.delta.applied_epoch:
            return None                 # log gap: keys before floor lost
        from ..utils.stats import stats
        wait_s = self._gate.acquire_write()
        try:
            dev = self.snapshots.get(space)
            if dev is not cur or dev.retired or dev.delta is None:
                return None
            # re-read under the gate: writers that landed while we
            # waited are folded into this same apply
            rec = store.delta_records(space)
            if rec is None:
                return None
            keys, target, floor = rec
            if floor > dev.delta.applied_epoch:
                return None
            if target == dev.delta.applied_epoch:
                return dev               # a concurrent update got there
            try:
                changes = dev.delta.host.apply(
                    store.delta_reader(space), keys)
            except (DeltaOverflow, DeltaUnsupported):
                return None
            put_delta_blocks(dev, dev.delta.host, sorted(changes.blocks))
            host = dev.delta.host.snap
            putter = None
            if changes.num_vertices:
                from .device import make_putter
                putter = make_putter(dev.mesh, dev.num_parts)
                dev.num_vertices = putter(
                    np.asarray(host.num_vertices, np.int32))
            if changes.tag_cols:
                from .device import make_putter
                putter = putter or make_putter(dev.mesh, dev.num_parts)
                for tag, colname in sorted(changes.tag_cols):
                    dt = dev.tags.get(tag)
                    tt = host.tags.get(tag)
                    if dt is None or tt is None:
                        continue
                    if colname == "present":
                        dt.present = putter(tt.present)
                    else:
                        dt.props[colname] = putter(tt.props[colname])
            dev.delta.applied_epoch = target
            store.delta_trim(space, keys)
        finally:
            self._gate.release_write()
        st = stats()
        st.observe("tpu_repin_wait_us", int(wait_s * 1e6))
        st.inc("tpu_repin_avoided")
        self._emit_delta_gauges(dev)
        self._maybe_compact(store, space, dev)
        return dev

    @staticmethod
    def _delta_sig(dev):
        """STATIC delta shape identity for jit cache keys: caps only —
        putting the delta epoch here would recompile every program on
        every commit group and erase the perf win.  Compiled programs
        stay valid across applies because only array CONTENT changes
        (blocks_data is rebuilt per dispatch)."""
        if dev.delta is None:
            return None
        hd = dev.delta.host
        return ("delta", hd.dcap, hd.tcap)

    @staticmethod
    def _grab_delta(dev, block_keys, prop_names):
        """Grab ONE mutually-consistent delta view for a dispatch:
        (view, per-block kernel-leaf dicts).  `view` is the atomic
        (epoch, blocks) tuple — the materializers must decode this
        dispatch's capture against view[1]'s numpy mirrors, never
        against dev.delta's CURRENT state (an apply may land between
        launch and materialize; it replaces, never mutates, so the
        grabbed arrays stay coherent)."""
        if dev.delta is None:
            return None, [None] * len(block_keys)
        view = dev.delta.view
        extras = []
        for bk in block_keys:
            e = view[1].get(bk)
            if e is None:
                extras.append(None)
                continue
            d = {k: e[k] for k in ("d_src", "d_dst", "d_rank",
                                   "d_valid", "d_tomb")}
            d["d_props"] = {n: e["d_props"][n] for n in prop_names}
            extras.append(d)
        return view, extras

    def _emit_delta_gauges(self, dev) -> None:
        from ..utils.stats import stats
        if dev.delta is None:
            return
        hd = dev.delta.host
        st = stats()
        st.gauge("tpu_delta_edges",
                 float(hd.total_edges() + hd.total_tombs()))
        st.gauge("tpu_delta_bytes", float(hd.nbytes()))
        per = hd.edges_per_part()
        tpp = hd.tombs_per_part()
        for p in range(dev.num_parts):
            st.gauge_labeled("tpu_shard_delta_edges", {"shard": p},
                             float(per[p] + tpp[p]))

    def _maybe_compact(self, store, space: str, dev) -> None:
        """Watermark check after a delta apply: past the fill threshold,
        kick the background compaction (REPARTITION-style: build the new
        base off the gate, swap under a short exclusive hold)."""
        from ..utils.config import get_config
        try:
            wm = float(get_config().get("tpu_delta_compact_watermark"))
        except Exception:  # noqa: BLE001
            wm = 0.0
        if wm <= 0 or dev.delta is None or dev.retired:
            return
        if dev.delta.host.fill_ratio() < wm:
            return
        if getattr(dev, "_compacting", False):
            return
        dev._compacting = True
        t = threading.Thread(target=self._compact,
                             args=(store, space, dev), daemon=True,
                             name=f"tpu-compact-{space}")
        dev._compact_thread = t
        t.start()

    def _compact(self, store, space: str, dev) -> None:
        """Fold the delta back into a fresh base CSR: the whole build
        runs OFF the dispatch gate (reads keep flowing against the old
        base + delta); only the buffer swap takes the write side."""
        from ..utils import trace
        from ..utils.failpoints import FailpointError, fail
        from ..utils.stats import stats
        t0 = time.perf_counter()
        try:
            with trace.span("tpu:compaction", space=space):
                dflag = self._delta_flag()
                snap = self._build_fresh(store, space, dflag)
                fail.hit("tpu:compact_swap", key=space)
                self._gate.acquire_write()
                try:
                    if self.snapshots.get(space) is not dev \
                            or dev.retired:
                        return           # superseded while building
                    dev.delete_buffers()
                    new = pin_snapshot(snap, self.mesh)
                    new.space_uid = dev.space_uid
                    self.snapshots[space] = new
                    self._fns = {k: v for k, v in self._fns.items()
                                 if not (k[0] == space
                                         and k[1] != new.epoch)}
                    self._arm_delta(store, new, snap, dflag)
                finally:
                    self._gate.release_write()
                stats().inc("tpu_compactions")
                self._emit_delta_gauges(new)
                self._emit_hbm_gauges()
                trace.record_phase("tpu:compaction",
                                   time.perf_counter() - t0,
                                   space=space)
        except FailpointError:
            pass                         # KILL test hook: abort cleanly
        except Exception:  # noqa: BLE001 — background thread must not die
            pass
        finally:
            dev._compacting = False

    def _check_hbm_budget(self, snap, space: str) -> None:
        """HBM budget (SURVEY §2 row 5: device memory is the scarce
        resource): refuse to pin past the PER-DEVICE limit; the caller
        falls back to the host path instead of OOMing the chip.

        The limit is per device — that is the scale-out contract: a
        snapshot sharded P ways parks hbm_bytes/P on each chip, so an
        8-way mesh accepts a graph 8× the single-chip budget (ROADMAP
        item 1's "fills a pod, not a chip")."""
        from ..utils.memtracker import get_config as _gc  # flag defined there
        limit = int(_gc().get("tpu_hbm_limit_bytes"))
        if not limit:
            return
        P = self.mesh_size if (not self.local_mode
                               and snap.num_parts == self.mesh_size) else 1
        est = -(-snap.hbm_bytes() // P)
        others = 0
        for sp_, s in self.snapshots.items():
            if sp_ == space:
                continue
            others += max(s.shard_hbm_bytes().values(), default=0)
        if est + others > limit:
            raise TpuUnavailable(
                f"snapshot needs {est:,}B HBM per device "
                f"({P} shard(s)); {others:,}B already pinned per device, "
                f"limit {limit:,} (flag tpu_hbm_limit_bytes)")

    @staticmethod
    def _maybe_degree_split(snap):
        """Apply the supernode degree-split at pin time when the flag
        is set (SURVEY §7 hard-part #4): the pinned copy AND its host
        mirror share the split layout, so eidx decode is unchanged."""
        from ..utils.config import get_config
        try:
            thr = int(get_config().get("tpu_degree_split_threshold"))
        except Exception:  # noqa: BLE001 — config missing in odd embeds
            thr = 0
        if thr > 0 and getattr(snap, "hub_dense", None) is None:
            from ..graphstore.csr import degree_split
            snap = degree_split(snap, thr)
        return snap

    def pin_prebuilt(self, snap) -> DeviceSnapshot:
        """Pin an externally-built CsrSnapshot (bulk-ingest / bench path
        — no dict store behind it)."""
        snap = self._maybe_degree_split(snap)
        self._check_hbm_budget(snap, snap.space)
        wait_s = self._gate.acquire_write()
        try:
            old = self.snapshots.get(snap.space)
            if old is not None:
                old.delete_buffers()
            dev = pin_snapshot(snap, self.mesh)
            self.snapshots[snap.space] = dev
        finally:
            self._gate.release_write()
        from ..utils.stats import stats
        stats().observe("tpu_repin_wait_us", int(wait_s * 1e6))
        stats().inc("tpu_pins")
        self._emit_hbm_gauges()
        return dev

    def unpin(self, space: str):
        self._gate.acquire_write()
        try:
            old = self.snapshots.pop(space, None)
            if old is not None:
                old.delete_buffers()
            self._fns = {k: v for k, v in self._fns.items()
                         if k[0] != space}
            self._kmax = {k: v for k, v in self._kmax.items()
                          if k[0] != space}
            self._buckets = {k: v for k, v in self._buckets.items()
                             if k[0][0] != space}
        finally:
            self._gate.release_write()
        self._emit_hbm_gauges()

    def hbm_bytes(self) -> int:
        return sum(s.hbm_bytes() for s in self.snapshots.values())

    def _save_buckets(self):
        if not self._buckets_path:
            return
        try:
            import ast as _ast
            import json as _json
            import os as _os
            # MERGE with the on-disk contents: several runtimes (one per
            # engine) share the cache file, and a plain overwrite made
            # the last saver clobber every other program's converged
            # buckets (each process then re-climbed the recompile ladder
            # — ~100 s/rung on a tunneled chip)
            merged = {}
            try:
                with open(self._buckets_path) as f:
                    merged = {_ast.literal_eval(k): tuple(v)
                              for k, v in _json.load(f).items()}
            except Exception:  # noqa: BLE001 — absent/corrupt file
                merged = {}
            merged.update(self._buckets)
            tmp = self._buckets_path + ".tmp"
            with open(tmp, "w") as f:
                _json.dump({repr(k): list(v)
                            for k, v in merged.items()}, f)
            _os.replace(tmp, self._buckets_path)
        except Exception:  # noqa: BLE001 — cache is best-effort
            pass

    # -- traversal --------------------------------------------------------

    @staticmethod
    def _seed_sorted(dense_ids: Sequence[int], P: int,
                     vmax: int) -> List[int]:
        """Normalized seed list with the range check both preps share.
        The old host-side numpy build crashed loudly on an id from a
        stale/foreign snapshot; JAX scatter would DROP it."""
        d = sorted(set(int(x) for x in dense_ids if x >= 0))
        if d and d[-1] >= P * vmax:
            raise ValueError(
                f"dense seed id {d[-1]} out of range for snapshot "
                f"(P={P}, vmax={vmax})")
        return d

    def _seed_builder(self, target, P: int, vmax: int, lanes: bool):
        """The jitted seed-bitmap scatter builder, cached and bounded —
        ONE copy of the build closure, sharding resolution and eviction
        policy for the solo and lane-batched preps.  `lanes` vmaps the
        same build over a leading lane axis ((L, cap) ids →
        (L, P, vmax) bitmap stack).  Returns (cache key, fn)."""
        key = ("seedfr_lanes" if lanes else "seedfr", target, P, vmax)
        fn = self._seed_fns.get(key)
        if fn is not None:
            return key, fn
        import jax.numpy as jnp
        if not isinstance(target, jax.sharding.Sharding):
            sh = jax.sharding.SingleDeviceSharding(target)
        else:
            sh = target

        def build(dpad):
            valid = dpad >= 0
            rows = jnp.where(valid, dpad % P, 0)
            cols = jnp.where(valid, dpad // P, 0)
            fr = jnp.zeros((P, vmax), bool)
            return fr.at[rows, cols].max(valid)

        fn = jax.jit(jax.vmap(build) if lanes else build,
                     out_shardings=sh)
        self._seed_fns[key] = fn
        # bounded: the key embeds the sharding target and snapshot
        # vmax, so a long-lived server re-pinning growing snapshots
        # must not accumulate executables for the process lifetime
        while len(self._seed_fns) > 32:
            old = next(iter(self._seed_fns))
            self._seed_fns.pop(old)
            self._seed_warm = {w for w in self._seed_warm
                               if w[0] != old}
        return key, fn

    def _seed_frontier_prep(self, dev: DeviceSnapshot,
                            dense_ids: Sequence[int], target):
        """Prep for the on-device seed-bitmap build: pad the dense-id
        list to a pow2 bucket and return (pad, jitted builder) with the
        builder already COMPILED for this shape — first-bucket XLA
        trace/compile must not be charged to put_s (it would report a
        one-off compile as steady-state transfer cost).

        The builder scatter-ors the ids into a (P, vmax) bool bitmap on
        device (dense = local * P + p), so the per-query host→device
        transfer shrinks from the graph-sized zeros bitmap (8 MB at
        north-star scale) to the seed ids — on a tunneled chip that is
        the dominant fixed cost of a small query."""
        P, vmax = dev.num_parts, dev.vmax
        d = self._seed_sorted(dense_ids, P, vmax)
        cap = _pow2(max(len(d), 1))
        pad = np.full(cap, -1, np.int64)
        if d:
            pad[:len(d)] = d
        key, fn = self._seed_builder(target, P, vmax, lanes=False)
        wk = (key, cap)
        if wk not in self._seed_warm:
            with self._collective_launch():
                jax.block_until_ready(fn(pad))   # compile outside timer
            self._seed_warm.add(wk)
        return pad, fn

    def _blocks_for(self, dev: DeviceSnapshot, etypes: Sequence[str],
                    direction: str):
        keys = []
        for et in etypes:
            if direction in ("out", "both"):
                keys.append((et, "out"))
            if direction in ("in", "both"):
                keys.append((et, "in"))
        return keys

    def _escalate(self, dev: DeviceSnapshot, dense: Sequence[int],
                  key_fn, build_fn, inputs_fn, stats: "TraverseStats",
                  n_hops: int = 1, uniform: bool = False,
                  min_eb: Optional[int] = None,
                  fetch_keys: Optional[set] = None,
                  kernel: str = "traverse"):
        """Dispatch-queue wrapper around _escalate_locked (ISSUE 9).

        Every device program passes through here: the dispatch
        registers in the live DispatchTable (queued → running → done,
        feeding the tpu_dispatch_queue_depth gauge and the stall
        watchdog), waits on the READ side of the dispatch-vs-repin
        gate, and the wait lands in `tpu_dispatch_queue_us{kernel}`,
        the statement's cost sink (`queue_us`) and its live-registry
        row — the wait-vs-run decomposition the admission-control work
        (ROADMAP item 2) will be specified against.  The failpoint
        site `tpu:dispatch_gate` stalls a dispatch while it is still
        QUEUED (stall-watchdog and queue-accounting tests)."""
        with self._gated_dispatch(kernel) as wait_us:
            stats.queue_s = wait_us / 1e6
            return self._escalate_locked(
                dev, dense, key_fn, build_fn, inputs_fn, stats,
                n_hops=n_hops, uniform=uniform, min_eb=min_eb,
                fetch_keys=fetch_keys, kernel=kernel)

    @contextmanager
    def _gated_dispatch(self, kernel: str):
        """The dispatch-gate prologue/epilogue shared by EVERY device
        program (the escalation driver and the algo plane's
        single-shot iterations): register in the live DispatchTable
        (queued → running → done), hit the `tpu:dispatch_gate`
        failpoint, wait on the READ side of the dispatch-vs-repin
        gate, and land the wait in `tpu_dispatch_queue_us{kernel}`,
        the statement's cost sink and its live-registry row.  Yields
        the queue wait in µs.  Defined ONCE so a change to dispatch
        accounting cannot drift between the two paths."""
        from ..utils.failpoints import fail as _fail
        from ..utils.stats import current_cost
        from ..utils.stats import stats as _metrics
        from ..utils.workload import current_live, dispatch_table
        tok = dispatch_table().enter(kernel)
        acquired = False
        try:
            # inside the try: a `raise` action must still exit the
            # token, or GET /queries shows a phantom forever-queued
            # dispatch and the depth gauge sticks at 1
            _fail.hit("tpu:dispatch_gate", key=kernel)
            self._gate.acquire_read()
            acquired = True
            wait_us = dispatch_table().mark_running(tok)
            _metrics().observe("tpu_dispatch_queue_us", wait_us,
                               {"kernel": kernel})
            cc = current_cost()
            if cc is not None:
                cc.add("queue_us", wait_us)
            lv = current_live()
            if lv is not None:
                lv.add("queue_us", wait_us)
            yield wait_us
        finally:
            if acquired:
                self._gate.release_read()
            dispatch_table().exit(tok)

    @contextmanager
    def _collective_launch(self):
        """Serialize device programs that contain mesh collectives.
        On a multi-part mesh every launch (kernel run, seed warm-up,
        seed put) holds the mutex for the duration of the execution:
        concurrent collective programs on overlapping devices
        interleave their all_to_all rendezvous and deadlock.  A no-op
        in local mode — the vmapped single-chip programs have no
        collectives and dispatch concurrently as before."""
        if self.local_mode:
            yield
            return
        with self._launch_mutex:
            yield

    def algo_dispatch(self, kernel: str, fn, *args):
        """One gated single-shot device dispatch for the algo plane
        (ISSUE 13): a vertex-program ITERATION kernel has static
        full-graph shapes — no bucket escalation, no capture fetch —
        but it rides the same gate/accounting as every other device
        program (_gated_dispatch) and additionally lands its run time
        in `tpu_dispatch_us{kernel}`, `device_us` and the SHOW QUERIES
        decomposition.  Returns (result, dispatch_us)."""
        from ..utils.stats import current_cost, current_work
        from ..utils.stats import stats as _metrics
        from ..utils.workload import current_live
        with self._gated_dispatch(kernel):
            t0 = time.perf_counter()
            with self._collective_launch():
                res = fn(*args)
                jax.block_until_ready(res)
            us = int((time.perf_counter() - t0) * 1e6)
            _metrics().observe("tpu_dispatch_us", us, {"kernel": kernel})
            cc = current_cost()
            if cc is not None:
                cc.add("device_us", us)
                cc.add("device_dispatches", 1)
            lv = current_live()
            if lv is not None:
                lv.add("device_us", us)
                lv.add("dispatches", 1)
            wc = current_work()
            if wc is not None:
                wc.add("device_dispatches")
            return res, us

    # -- multi-lane batched dispatch (ISSUE 15 tentpole) -----------------

    def _seed_frontier_prep_lanes(self, dev: DeviceSnapshot,
                                  lane_dense: Sequence[Sequence[int]],
                                  target):
        """Lane-batched variant of _seed_frontier_prep: every lane's
        dense seed ids padded to one (L, cap) block, built into a
        (L, P, vmax) bool frontier stack by the vmapped on-device
        scatter (same builder closure — _seed_builder).  L is
        pow2-padded so the compile count stays logarithmic in batch
        size; padding lanes (all -1) scatter nothing and expand
        nothing."""
        P, vmax = dev.num_parts, dev.vmax
        lanes = [self._seed_sorted(dense_ids, P, vmax)
                 for dense_ids in lane_dense]
        cap = _pow2(max((len(d) for d in lanes), default=1) or 1)
        # on a (lanes, parts) mesh the global lane axis must divide
        # evenly over the lane-axis rows: pad to Lm × pow2 lanes (Lm=1
        # in local mode reduces to the plain pow2 bucket)
        Lm = max(self.mesh_lanes, 1)
        L = Lm * _pow2(max(-(-len(lanes) // Lm), 1))
        pad = np.full((L, cap), -1, np.int64)
        for i, d in enumerate(lanes):
            if d:
                pad[i, :len(d)] = d
        key, fn = self._seed_builder(target, P, vmax, lanes=True)
        wk = (key, L, cap)
        if wk not in self._seed_warm:
            with self._collective_launch():
                jax.block_until_ready(fn(pad))   # compile outside timer
            self._seed_warm.add(wk)
        return pad, fn, L

    def _escalate_lanes(self, dev: DeviceSnapshot,
                        lane_dense: Sequence[Sequence[int]],
                        key_fn, build_fn, inputs_fn,
                        n_hops: int = 1, uniform: bool = False,
                        fetch_keys: Optional[set] = None,
                        kernel: str = "traverse"):
        """The lane-batched escalation driver: ONE gated dispatch, ONE
        put, ONE fetch for every lane of a formed batch (the launcher
        member runs this on its own thread; batch.py fans the result
        out).  Returns (res, info): res carries lane-major arrays —
        hop_edges/frontier_sizes (L, P, steps), cap arrays with a
        leading L — and info the launch-level facts each lane's
        de-mux attribution needs (rungs, budgets, phase timings, gate
        wait).

        Per-statement TLS attribution (work/cost/live) is SUPPRESSED
        here — the lane-aware de-mux (_lane_attribution) charges each
        statement its own lane on its own thread, so rows,
        WorkCounters, cost sinks and flight entries stay exactly
        per-statement (the PR 7 concurrent-attribution contract).
        Launch-level truth still lands where it belongs: the kernel
        ledger, tpu_kernel_runs and the dispatch-table slot record ONE
        real launch, which is precisely how the ledger proves the
        sharing is real.  A batched launch consumes ONE
        `tpu_dispatch_queue_cap` slot (the single _gated_dispatch
        below), never K."""
        from ..utils.stats import stats as _metrics
        from ..utils.stats import use_cost, use_work
        from ..utils.workload import use_live
        if getattr(dev, "retired", False):
            raise TpuUnavailable(
                "device snapshot retired by a concurrent re-pin")
        base = self.init_eb
        EBs = [base] * n_hops
        L_real = len(lane_dense)
        # mesh identity in the bucket key: a 1-shard and an 8-shard run
        # of the same program have different overflow profiles (per-part
        # expansion vs whole-graph expansion)
        bkey = (key_fn(()) + ("lanes", self._mesh_key()),
                _pow2(max(L_real, 1)))
        prev = self._buckets.get(bkey)
        if prev is not None:
            pe = prev[-1]
            pe = [pe] * n_hops if isinstance(pe, int) else list(pe)
            if len(pe) == n_hops:
                EBs = [max(a, int(b)) for a, b in zip(EBs, pe)]
        if uniform:
            EBs = [max(EBs)] * n_hops
        if self.local_mode:
            target = self.mesh.devices.reshape(-1)[0]
        else:
            # lanes × shards grid: the frontier stack is sharded over
            # BOTH mesh axes — each device owns its lane rows of its
            # partition's bitmap.  On a legacy 1-D ('part',) mesh the
            # lane dimension stays unsharded (replicated lanes).
            lane_ax = "lane" if "lane" in self.mesh.axis_names else None
            target = NamedSharding(self.mesh,
                                   PartitionSpec(lane_ax, "part"))
        seed_pad, seed_fn, L = self._seed_frontier_prep_lanes(
            dev, lane_dense, target)
        info: Dict[str, Any] = {
            "lanes": L_real, "rungs": [], "compiles": 0, "retries": 0,
            "put_s": 0.0, "fetch_s": 0.0, "device_s": 0.0,
            "gate_wait_us": 0, "ebs": list(EBs), "hbm_bytes": 0,
            "shards": self.mesh_size, "exchange_bytes": 0}
        with use_work(None), use_cost(None), use_live(None), \
                self._gated_dispatch(kernel) as wait_us:
            info["gate_wait_us"] = wait_us
            tp = time.perf_counter()
            with self._collective_launch():
                frontier = seed_fn(seed_pad)
            info["put_s"] = time.perf_counter() - tp
            for attempt in range(max(self.max_retries, n_hops + 3)):
                ebs = tuple(EBs)
                # lane suffix (not prefix): pin/unpin prune _fns by
                # key[0]==space / key[1]==epoch — lane programs must
                # age out with their snapshot like solo programs do;
                # the mesh key separates per-grid compilations
                key = key_fn(ebs) + ("lanes", L, self._mesh_key())
                fn = self._fns.get(key)
                compiled = fn is None
                if compiled:
                    fn = self._fns[key] = build_fn(ebs)
                    info["compiles"] += 1
                t0 = time.perf_counter()
                from ..utils.config import get_config as _gc
                prof_dir = _gc().get("tpu_profiler_dir")
                if prof_dir:
                    # same xplane tracing contract as the solo path: a
                    # profiled deployment must capture the SHARED
                    # launches too — they are the ones worth profiling
                    self._prof_seq = getattr(self, "_prof_seq", 0) + 1
                    import os as _os
                    run_dir = _os.path.join(str(prof_dir),
                                            f"run{self._prof_seq:06d}")
                    with jax.profiler.trace(run_dir), \
                            self._collective_launch():
                        res = fn(*inputs_fn(ebs), frontier)
                        jax.block_until_ready(res)
                else:
                    with self._collective_launch():
                        res = fn(*inputs_fn(ebs), frontier)
                        jax.block_until_ready(res)
                t1 = time.perf_counter()
                info["rungs"].append((int((t1 - t0) * 1e6), compiled))
                info["device_s"] = t1 - t0
                cap_dev = res.pop("cap", None) if isinstance(res, dict) \
                    else None
                res = jax.device_get(res)
                info["fetch_s"] += time.perf_counter() - t1
                if res["ovf_expand"].any():
                    # per-hop true expansion max over (lane, part):
                    # jump every overflowed hop straight to its bucket
                    need = np.asarray(res["hop_edges"]).max(axis=(0, 1))
                    EBs = [e if need[h] <= e else
                           min(max(2 * e, _pow2(int(need[h]))),
                               self.max_cap)
                           for h, e in enumerate(EBs)]
                    if uniform:
                        EBs = [max(EBs)] * n_hops
                    continue
                info["ebs"] = list(EBs)
                info["retries"] = attempt
                if self._buckets.get(bkey) != (0, ebs):
                    self._buckets[bkey] = (0, ebs)
                    while len(self._buckets) > 512:
                        self._buckets.pop(next(iter(self._buckets)))
                    self._save_buckets()
                if cap_dev is not None:
                    tf = time.perf_counter()
                    kc = np.asarray(res["kcount"])
                    kmax = int(kc.max()) if kc.size else 0
                    # bound by the ACTUAL capture width, not max(EBs):
                    # a live delta plane widens capture to EB + Dcap,
                    # so kept counts can legitimately exceed EB
                    capw = next(iter(cap_dev.values())).shape[-1]
                    K = min(int(capw), _pow2(max(kmax, 1)))
                    res["cap"] = {k: np.asarray(
                        jax.device_get(v[..., :K]))
                        for k, v in cap_dev.items()
                        if fetch_keys is None or k in fetch_keys}
                    res["cap"]["kcount"] = kc
                    info["fetch_s"] += time.perf_counter() - tf
                # launch-level metrics/ledger: ONE real launch shared
                # by L_real statements — the sharing proof
                _metrics().inc("tpu_kernel_runs")
                _metrics().inc("tpu_edges_traversed",
                               int(np.asarray(res["hop_edges"]).sum()))
                _metrics().add_value("tpu_kernel_s", info["device_s"])
                for r_us, r_compiled in info["rungs"]:
                    _metrics().observe("tpu_dispatch_us", r_us,
                                       {"kernel": kernel})
                    if r_compiled:
                        _metrics().inc_labeled("tpu_kernel_compiles",
                                               {"kernel": kernel})
                    else:
                        _metrics().inc_labeled("tpu_kernel_cache_hits",
                                               {"kernel": kernel})
                hbm = self.hbm_bytes()
                info["hbm_bytes"] = hbm
                self._hbm_high_water = max(
                    getattr(self, "_hbm_high_water", 0), hbm)
                _metrics().gauge("tpu_hbm_high_water_bytes",
                                 float(self._hbm_high_water))
                # lanes × shards exchange accounting (PR 17): the
                # shared launch's single per-hop all_to_all carries the
                # whole L-lane payload
                xhops = n_hops if kernel == "bfs" else max(n_hops - 1, 0)
                xbytes = (0 if self.local_mode else
                          xhops * a2a_payload_bytes(
                              self.mesh_size, dev.vmax, lanes=L))
                info["shards"] = self.mesh_size
                info["exchange_bytes"] = xbytes
                _metrics().gauge("tpu_shards", float(self.mesh_size))
                from ..utils.flight import kernel_ledger
                kernel_ledger().record(
                    kernel=kernel, shape=[L] + list(EBs), steps=n_hops,
                    compiled=bool(info["compiles"]),
                    dispatch_us=int(info["device_s"] * 1e6),
                    hbm_bytes=hbm, retries=attempt,
                    shards=self.mesh_size, exchange_bytes=xbytes)
                from ..utils import trace as _t
                _t.record_phase("tpu:batch", info["device_s"],
                                lanes=L_real, kernel=kernel,
                                eb=list(EBs))
                if xbytes:
                    _metrics().inc("tpu_all_to_all_bytes", xbytes)
                    _t.record_phase("tpu:shard_exchange", 0.0,
                                    bytes=xbytes, hops=xhops,
                                    shards=self.mesh_size, lanes=L)
                return res, info
        raise TpuUnavailable(
            "lane-batched bucket escalation did not converge")

    def _lane_attribution(self, tk, stats: "TraverseStats"):
        """De-mux one lane of a shared launch: fill this statement's
        TraverseStats and charge ITS thread-local work/cost/live sinks
        with its own lane's deterministic counts (edges, frontier
        sizes) plus the shared launch's timings — exactly what a solo
        dispatch of the same statement would have recorded.  Returns
        the lane's slice of the capture arrays (the lane-aware epilogue
        of the gated dispatch)."""
        info, res, lane = tk.info, tk.res, tk.lane
        he = np.asarray(res["hop_edges"])[lane]          # (P, steps)
        stats.hop_edges = [int(x) for x in he.sum(axis=0)]
        if "frontier_sizes" in res:
            stats.frontier_sizes = [
                int(x) for x in
                np.asarray(res["frontier_sizes"])[lane].sum(axis=0)]
        stats.retries = info["retries"]
        stats.compiles = info["compiles"]
        stats.device_s = info["device_s"]
        stats.put_s = info["put_s"]
        stats.fetch_s = info["fetch_s"]
        stats.queue_s = (info["gate_wait_us"] + tk.form_wait_us) / 1e6
        stats.f_cap, stats.e_cap = 0, list(info["ebs"])
        stats.hbm_bytes = info["hbm_bytes"]
        stats.shards = info.get("shards", 1)
        stats.exchange_bytes = info.get("exchange_bytes", 0)
        n_rungs = len(info["rungs"])
        rung_us = sum(r for r, _ in info["rungs"])
        from ..utils.stats import current_cost, current_work
        from ..utils.workload import current_live
        wc = current_work()
        if wc is not None:
            wc.add("device_dispatches", n_rungs)
            wc.add("edges_traversed", stats.edges_traversed())
            wc.extend_frontier(stats.frontier_sizes)
        cc = current_cost()
        if cc is not None:
            cc.add("device_us", rung_us)
            cc.add("device_dispatches", n_rungs)
            cc.add("queue_us", int(stats.queue_s * 1e6))
            if info["compiles"]:
                cc.add("device_compiles", info["compiles"])
        lv = current_live()
        if lv is not None:
            lv.add("device_us", rung_us)
            lv.add("dispatches", n_rungs)
            lv.add("queue_us", int(stats.queue_s * 1e6))
        from ..utils import trace as _t
        _t.record_phase("device:put", stats.put_s)
        _t.record_phase("device:dispatch", stats.device_s,
                        eb=list(info["ebs"]), retries=stats.retries)
        _t.record_phase("device:fetch", stats.fetch_s)
        return {k: v[lane] for k, v in res["cap"].items()}

    def _lanes_builder(self, P: int, steps: int, n_blocks: int, **kw):
        """Grid-aware lanes program factory: the single-chip vmap
        program in local mode, the lanes × shards shard_map program on
        a multi-device mesh (CSR blocks mesh-resident, ONE all_to_all
        per hop carrying every lane)."""
        def build_lanes(ebs):
            if self.local_mode:
                return build_traverse_fn_lanes(
                    P, ebs, steps, n_blocks, **kw)
            return build_traverse_fn_lanes_sharded(
                self.mesh, P, ebs, steps, n_blocks, **kw)
        return build_lanes

    def _try_batched(self, dense: Sequence[int], dev: DeviceSnapshot,
                     key_fn, build_lanes, inputs_fn, n_hops: int,
                     uniform: bool, fetch_keys: Optional[set],
                     kernel: str, stats: "TraverseStats",
                     delta_epoch: Optional[int] = None):
        """Submit this dispatch to the batch former; returns the
        statement's solo-shaped {"cap": ...} after a shared launch, or
        None when the dispatch should run solo (batching off, no
        concurrent company, a mesh the snapshot is not sharded for, or
        the `tpu:batch_form` failpoint rejected enrollment).

        Sharded meshes batch too (PR 17): the lanes builder the caller
        hands us is grid-aware (lanes × shards shard_map when
        local_mode is off), and the compatibility key carries the mesh
        shape + epoch so a re-pin to a different shard count can never
        merge lanes compiled for different launch grids."""
        if not self.local_mode and dev.num_parts != self.mesh_size:
            return None
        from ..utils.failpoints import FailpointError
        from .batch import batch_former
        former = batch_former()
        if not former.enabled():
            return None
        # the delta device epoch the CALLER assembled against rides the
        # compatibility key (NOT the jit key): statements grouped into
        # one launch must share the exact same delta buffers, or a lane
        # could read another statement's pre-write view (read-your-
        # writes floor, PR 9)
        base_key = (kernel, key_fn(()),
                    frozenset(fetch_keys) if fetch_keys is not None
                    else None, ("mesh",) + self._mesh_key(),
                    ("delta", delta_epoch)
                    if delta_epoch is not None else None)

        def launch(lane_dense):
            return self._escalate_lanes(
                dev, lane_dense, key_fn=key_fn, build_fn=build_lanes,
                inputs_fn=inputs_fn, n_hops=n_hops, uniform=uniform,
                fetch_keys=fetch_keys, kernel=kernel)

        try:
            tk = former.submit(base_key, dense, launch, kernel=kernel,
                               gate_busy=self._gate.write_held)
        except FailpointError:
            return None          # batch forming rejected → solo dispatch
        if tk is None:
            return None
        return {"cap": self._lane_attribution(tk, stats)}

    def _escalate_locked(self, dev: DeviceSnapshot, dense: Sequence[int],
                         key_fn, build_fn, inputs_fn,
                         stats: "TraverseStats",
                         n_hops: int = 1, uniform: bool = False,
                         min_eb: Optional[int] = None,
                         fetch_keys: Optional[set] = None,
                         kernel: str = "traverse"):
        """Shared power-of-two bucket escalation driver for all device
        programs (traverse, bfs): seed bitmap layout, jit cache, one
        batched fetch, overflow-driven retry (SURVEY §7 hard-part #1).

        key_fn(ebs) → jit-cache key; build_fn(ebs) → jitted program
        fn(*inputs, frontier); inputs_fn(ebs) → tuple of extra inputs;
        ebs is the per-hop edge-budget tuple (len n_hops).

        With the bitmap frontier (round-4 redesign) the only dynamic
        budget is the per-block edge budget — the frontier and the
        routing buckets are structurally overflow-free.  Budgets are
        per-hop: hop h's bucket grows to pow2(its own measured
        expansion), so a 3-hop GO's first hop does not pay the final
        hop's padding.  `uniform=True` keeps all hops at one size
        (capture_hops stacks frames along a hop axis; BFS compiles one
        per-level body).
        """
        if getattr(dev, "retired", False):
            # a concurrent re-pin donated this snapshot's buffers while
            # we were queued at the gate; the caller re-pins / falls back
            raise TpuUnavailable(
                "device snapshot retired by a concurrent re-pin")
        base = self.init_eb
        if min_eb is not None:
            # caller knows a static bound (e.g. BFS: one hop's expansion
            # never exceeds the block's padded Emax) — start there and
            # never climb the recompile ladder
            base = min(max(base, min_eb), self.max_cap)
        EBs = [base] * n_hops
        # cache key includes the seed-count bucket: one supernode query
        # must not permanently inflate every later small query of the
        # same program to supernode-sized padded kernels
        bkey = (key_fn(()), _pow2(max(len(set(dense)), 1)))
        prev = self._buckets.get(bkey)
        if prev is not None:
            # value kept as (0, ebs) for cache-file compat (slot 0 was
            # the old frontier bucket F); an int ebs is a legacy uniform
            pe = prev[-1]
            pe = [pe] * n_hops if isinstance(pe, int) else list(pe)
            if len(pe) == n_hops:
                EBs = [max(a, int(b)) for a, b in zip(EBs, pe)]
        if uniform:
            EBs = [max(EBs)] * n_hops
        if self.local_mode:
            target = self.mesh.devices.reshape(-1)[0]
        else:
            target = NamedSharding(self.mesh, PartitionSpec("part"))

        seed_pad, seed_fn = self._seed_frontier_prep(dev, dense, target)
        tp = time.perf_counter()
        with self._collective_launch():
            frontier = seed_fn(seed_pad)
        stats.put_s = time.perf_counter() - tp

        # a post-overflow hop's reported count is a LOWER bound (its
        # frontier was truncated), so in the worst case each attempt
        # finalizes only one more hop's bucket — the retry budget must
        # scale with the hop count
        from ..utils.stats import current_work
        wc = current_work()
        rungs: List[Tuple[int, bool]] = []   # (dispatch_us, compiled)
        for attempt in range(max(self.max_retries, n_hops + 3)):
            stats.retries = attempt
            ebs = tuple(EBs)
            key = key_fn(ebs)
            fn = self._fns.get(key)
            compiled = fn is None
            if compiled:
                fn = self._fns[key] = build_fn(ebs)
                stats.compiles += 1
            # per-rung bookkeeping stays PLAIN-PYTHON here (ints and a
            # list append on locals): the dispatch neighborhood is
            # timing-sensitive under concurrent serve-while-repin (a
            # latent jaxlib CPU race); all metric/ledger emission for
            # the rungs happens once after convergence below
            if wc is not None:
                wc.add("device_dispatches")
            t0 = time.perf_counter()
            from ..utils.config import get_config
            prof_dir = get_config().get("tpu_profiler_dir")
            if prof_dir:
                # device-plane tracing (SURVEY §5): one xplane trace per
                # kernel run, viewable in TensorBoard/XProf.  Each run
                # gets its own subdir — jax names dumps by wall-clock
                # second, so two runs inside one second would otherwise
                # overwrite each other.
                self._prof_seq = getattr(self, "_prof_seq", 0) + 1
                import os as _os
                run_dir = _os.path.join(str(prof_dir),
                                        f"run{self._prof_seq:06d}")
                with jax.profiler.trace(run_dir), \
                        self._collective_launch():
                    res = fn(*inputs_fn(ebs), frontier)
                    jax.block_until_ready(res)
            else:
                with self._collective_launch():
                    res = fn(*inputs_fn(ebs), frontier)
                    jax.block_until_ready(res)
            t1 = time.perf_counter()
            stats.device_s = t1 - t0
            rungs.append((int((t1 - t0) * 1e6), compiled))
            # two-phase fetch: capture arrays stay on device while the
            # small meta (counters/overflow flags) comes back first; the
            # EB-padded capture rows are then fetched as [:kmax] slices —
            # kept entries are device-compacted to a prefix (hop.py
            # _compact_cap), so the transfer is kept-sized, not
            # bucket-sized (~2 GB → MBs on the north-star config).
            # SPECULATIVE single-phase: once this program shape has run
            # in-process, the previous kept-size bounds the slice and
            # both phases collapse into ONE device_get — on a tunneled
            # chip that is one fewer network round trip per query (the
            # dominant cost of small queries).  An undershoot (kept grew
            # past the speculation) falls back to the exact refetch.
            cap_dev = res.pop("cap", None) if isinstance(res, dict) \
                else None
            spec_k = self._kmax.get(key) if cap_dev is not None else None
            spec_cap = None
            if spec_k is not None:
                bundle = dict(res)
                for ck, cv in cap_dev.items():
                    if fetch_keys is None or ck in fetch_keys:
                        bundle["cap:" + ck] = cv[..., :spec_k]
                got = jax.device_get(bundle)
                res = {k: v for k, v in got.items()
                       if not k.startswith("cap:")}
                spec_cap = {k[4:]: v for k, v in got.items()
                            if k.startswith("cap:")}
            else:
                res = jax.device_get(res)
            stats.fetch_s = time.perf_counter() - t1

            if res["ovf_expand"].any():
                # hop_edges reports the true per-part pre-filter
                # expansion size PER HOP, so jump each overflowed hop
                # STRAIGHT to its needed bucket — blind doubling needs
                # ~20 rounds for a 1-seed BFS over a 30M-edge graph and
                # times out the retry budget.  (A pre-overflow hop's
                # count is exact; a post-overflow hop's is a lower bound
                # from the truncated frontier — the loop converges.)
                # Drop the failed rung's device capture buffers BEFORE
                # the larger rung runs — holding both nearly doubles
                # peak HBM and can fail a retry that would converge.
                need = np.asarray(res["hop_edges"]).max(axis=0)
                EBs = [e if need[h] <= e else
                       min(max(2 * e, _pow2(int(need[h]))), self.max_cap)
                       for h, e in enumerate(EBs)]
                if uniform:
                    EBs = [max(EBs)] * n_hops
                cap_dev = None
            else:
                stats.f_cap, stats.e_cap = 0, list(EBs)
                if self._buckets.get(bkey) != (0, ebs):
                    self._buckets[bkey] = (0, ebs)
                    # bound by evicting oldest entries — a wholesale
                    # clear() would also wipe the persistent cache file
                    # on the next save, re-exposing every converged
                    # query shape to the recompile ladder
                    while len(self._buckets) > 512:
                        self._buckets.pop(next(iter(self._buckets)))
                    self._save_buckets()
                stats.hop_edges = [int(x)
                                   for x in res["hop_edges"].sum(axis=0)]
                if "frontier_sizes" in res:
                    stats.frontier_sizes = [
                        int(x) for x in
                        np.asarray(res["frontier_sizes"]).sum(axis=0)]
                if cap_dev is not None:
                    tf = time.perf_counter()
                    kc = np.asarray(res["kcount"])
                    kmax = int(kc.max()) if kc.size else 0
                    # actual capture width, not max(EBs): a live delta
                    # plane widens capture to EB + Dcap per hop
                    capw = next(iter(cap_dev.values())).shape[-1]
                    K = min(int(capw), _pow2(max(kmax, 1)))
                    if spec_cap is not None and spec_k >= K:
                        res["cap"] = {k: np.asarray(v[..., :K])
                                      for k, v in spec_cap.items()}
                    else:
                        res["cap"] = {k: np.asarray(
                            jax.device_get(v[..., :K]))
                            for k, v in cap_dev.items()
                            if fetch_keys is None or k in fetch_keys}
                    res["cap"]["kcount"] = kc
                    self._kmax[key] = K
                    while len(self._kmax) > 512:
                        self._kmax.pop(next(iter(self._kmax)))
                    stats.fetch_s += time.perf_counter() - tf
                from ..utils.stats import stats as _metrics
                _metrics().inc("tpu_kernel_runs")
                _metrics().inc("tpu_edges_traversed",
                               stats.edges_traversed())
                _metrics().add_value("tpu_kernel_s", stats.device_s)
                if wc is not None:
                    wc.add("edges_traversed", stats.edges_traversed())
                    wc.extend_frontier(stats.frontier_sizes)
                # device kernel ledger (ISSUE 8 tentpole): per-RUNG
                # dispatch µs and compile-vs-cache dispositions were
                # accumulated as plain locals in the loop (every
                # escalation rung is a real dispatch — counting only
                # the converged run would skew the ratios under
                # retries); emit them to histograms/counters/cost HERE,
                # outside the timing-sensitive dispatch neighborhood
                from ..utils.stats import current_cost as _cc
                cc = _cc()
                for r_us, r_compiled in rungs:
                    _metrics().observe("tpu_dispatch_us", r_us,
                                       {"kernel": kernel})
                    if r_compiled:
                        _metrics().inc_labeled("tpu_kernel_compiles",
                                               {"kernel": kernel})
                    else:
                        _metrics().inc_labeled("tpu_kernel_cache_hits",
                                               {"kernel": kernel})
                if cc is not None:
                    cc.add("device_us", sum(r for r, _ in rungs))
                    cc.add("device_dispatches", len(rungs))
                    if stats.compiles:
                        cc.add("device_compiles", stats.compiles)
                # live workload row (ISSUE 9): SHOW QUERIES reports the
                # statement's device time while it is still running
                from ..utils.workload import current_live as _cl
                lv = _cl()
                if lv is not None:
                    lv.add("device_us", sum(r for r, _ in rungs))
                    lv.add("dispatches", len(rungs))
                dispatch_us = int(stats.device_s * 1e6)
                hbm = self.hbm_bytes()
                stats.hbm_bytes = hbm
                self._hbm_high_water = max(
                    getattr(self, "_hbm_high_water", 0), hbm)
                _metrics().gauge("tpu_hbm_high_water_bytes",
                                 float(self._hbm_high_water))
                # per-shard dispatch/exchange facts (PR 17): the
                # bit-packed frontier all_to_all payload this converged
                # run moved over ICI — BFS exchanges every level, the
                # traverse kernels skip the final hop's exchange
                stats.shards = self.mesh_size
                xhops = n_hops if kernel == "bfs" else max(n_hops - 1, 0)
                stats.exchange_bytes = (
                    0 if self.local_mode else
                    xhops * a2a_payload_bytes(self.mesh_size, dev.vmax))
                _metrics().gauge("tpu_shards", float(self.mesh_size))
                from ..utils.flight import kernel_ledger
                kernel_ledger().record(
                    kernel=kernel, shape=list(EBs), steps=n_hops,
                    compiled=bool(stats.compiles),
                    dispatch_us=dispatch_us, hbm_bytes=hbm,
                    retries=stats.retries, shards=self.mesh_size,
                    exchange_bytes=stats.exchange_bytes)
                # device-plane trace phases (ISSUE 1): the runtime
                # timed them itself — emit as leaf spans of whatever
                # executor span is driving this kernel
                from ..utils import trace as _t
                _t.record_phase("device:put", stats.put_s)
                _t.record_phase("device:dispatch", stats.device_s,
                                eb=list(EBs), retries=stats.retries)
                _t.record_phase("device:fetch", stats.fetch_s)
                if stats.exchange_bytes:
                    _metrics().inc("tpu_all_to_all_bytes",
                                   stats.exchange_bytes)
                    # the exchange runs inside the fused program — its
                    # span carries payload facts, not a separate timing
                    _t.record_phase("tpu:shard_exchange", 0.0,
                                    bytes=stats.exchange_bytes,
                                    hops=xhops, shards=self.mesh_size)
                return res
        raise TpuUnavailable("bucket escalation did not converge")

    def traverse(self, store: GraphStore, space: str, vids: Sequence[Any],
                 etypes: Sequence[str], direction: str, steps: int,
                 edge_filter: Optional[E.Expr] = None,
                 capture: bool = True,
                 yields: Optional[List[Tuple[Any, str]]] = None
                 ) -> Tuple[List[Any], TraverseStats]:
        """Run an N-step GO expansion fully on device.

        Returns (rows, stats).  Without `yields`, rows are
        (src_vid, Edge, dst_vid) triples for every final-hop edge passing
        the predicate.  With `yields` — a list of (Expr, name) pairs the
        fusion rule verified are columnar-computable — rows are a lazy
        ColumnarDataSet holding the FINAL output as numpy columns; no
        per-row Python objects exist unless the consumer crosses the row
        boundary (the E2E fast path).  Raises CannotCompile if the
        filter does not vectorize (caller falls back to the host path).
        """
        t_start = time.perf_counter()
        dev = self.pin(store, space)
        sd = store.space(space)
        stats = TraverseStats()
        stats.steps = steps
        stats.pin_s = time.perf_counter() - t_start

        block_keys = self._blocks_for(dev, etypes, direction)
        pred = None
        pred_cols: List[str] = []
        pred_key = None
        if edge_filter is not None:
            # single-etype constraint is enforced by the optimizer rule
            bl = dev.blocks[block_keys[0]]
            pred, pred_cols = compile_predicate(
                edge_filter, bl.prop_types, dev.pool,
                vid_to_dense=sd.dense_id)
            pred_key = E.to_text(edge_filter) if hasattr(E, "to_text") else repr(edge_filter)

        dense = [sd.dense_id(v) for v in vids]
        dense = [d for d in dense if d >= 0]
        if not dense:
            return [], stats

        P = dev.num_parts
        # edge props the yields read and EVERY block carries are
        # gathered on device at the compacted final-hop slots (the
        # fused-Project leg: the fetch then ships exactly the result
        # columns); props missing from some block fall back to the
        # host-side eidx gather
        yield_cols: tuple = ()
        if capture and yields is not None:
            wanted = {x.name for e, _ in yields for x in E.walk(e)
                      if x.kind == "edge_prop"
                      and not x.name.startswith("_")}
            yield_cols = tuple(sorted(
                n for n in wanted
                if all(n in dev.blocks[bk].props for bk in block_keys)))
            # each device-gathered col is one more EB-padded capture
            # buffer per block — cap the count so a wide YIELD can't
            # double peak HBM on the escalation ladder; the rest decode
            # on host via eidx as before
            if len(yield_cols) > 4:
                yield_cols = yield_cols[:4]
        prop_names = {n for n in pred_cols if not n.startswith("_")}
        prop_names |= set(yield_cols)
        dview, dextras = self._grab_delta(dev, block_keys, prop_names)
        blocks_data = tuple(
            {"indptr": dev.blocks[bk].indptr, "nbr": dev.blocks[bk].nbr,
             "rank": dev.blocks[bk].rank,
             "props": {n: dev.blocks[bk].props[n] for n in prop_names},
             **(dextras[i] or {})}
            for i, bk in enumerate(block_keys))

        # fetch only the capture arrays the yields actually read (each
        # is a kept-sized column — src+rank+eidx are most of the result
        # transfer on a dst+prop GO, the common shape)
        fetch_keys = (_cap_keys_for_yields(yields, yield_cols)
                      if capture else None)
        if fetch_keys is not None and fetch_keys & {"src", "dst"} \
                and any(d == "in" for _, d in block_keys):
            # reverse blocks serve src(edge) from the dst array and vice
            # versa (physical-edge orientation) — need both
            fetch_keys |= {"src", "dst"}
        if fetch_keys is not None and dview is not None:
            # delta rows interleave with base rows in canonical CSR
            # order at materialize time — the host re-sort needs every
            # identity column regardless of what the yields read
            fetch_keys |= {"src", "dst", "rank", "eidx"}

        hub_dense = getattr(dev.host, "hub_dense", None)
        hub_n = 0 if hub_dense is None else len(hub_dense)

        def build(ebs):
            if self.local_mode:
                return build_traverse_fn_local(
                    P, ebs, steps, len(block_keys), pred=pred,
                    pred_cols=pred_cols, capture=capture,
                    yield_cols=yield_cols, hub_dense=hub_dense)
            return build_traverse_fn(
                self.mesh, P, ebs, steps, len(block_keys),
                pred=pred, pred_cols=pred_cols, capture=capture,
                yield_cols=yield_cols, hub_dense=hub_dense)

        def key_fn(ebs):
            return (space, dev.epoch, tuple(block_keys), steps, ebs,
                    pred_key, capture, tuple(pred_cols), yield_cols,
                    hub_n, self._delta_sig(dev))

        # multi-lane batched dispatch (ISSUE 15): concurrent compatible
        # statements share ONE launch; None falls through to the solo
        # path (batching off / no company / capture-less program)
        res = None
        if capture:
            res = self._try_batched(
                dense, dev, key_fn,
                build_lanes=self._lanes_builder(
                    P, steps, len(block_keys), pred=pred,
                    pred_cols=pred_cols, capture=True,
                    yield_cols=yield_cols, hub_dense=hub_dense),
                inputs_fn=lambda ebs: (blocks_data,),
                n_hops=steps, uniform=False, fetch_keys=fetch_keys,
                kernel="traverse", stats=stats,
                delta_epoch=dview[0] if dview is not None else None)
        if res is None:
            res = self._escalate(
                dev, dense,
                key_fn=key_fn,
                build_fn=build,
                inputs_fn=lambda ebs: (blocks_data,),
                stats=stats, n_hops=steps, fetch_keys=fetch_keys,
                kernel="traverse")
        if not capture:
            stats.total_s = time.perf_counter() - t_start
            return [], stats

        t_mat = time.perf_counter()
        if yields is not None:
            rows = self._materialize_yields(store, space, dev, block_keys,
                                            res["cap"], yields,
                                            dview=dview)
        else:
            rows = self._materialize(store, space, dev, block_keys,
                                     res["cap"], dview=dview)
        stats.mat_s = time.perf_counter() - t_mat
        stats.result_edges = len(rows)
        stats.total_s = time.perf_counter() - t_start
        return rows, stats

    # -- MATCH device plane: layered hop frames --------------------------

    def traverse_hops(self, store: GraphStore, space: str,
                      vids: Sequence[Any], etypes: Sequence[str],
                      direction: str, max_hop: int,
                      edge_filter: Optional[E.Expr] = None
                      ) -> Tuple[List["HopFrame"], TraverseStats]:
        """Device expansion for MATCH Traverse (SURVEY §2 row 23).

        Runs max_hop frontier expansions on device with the compiled
        predicate applied at EVERY hop (MATCH edge filters are uniform
        over variable-length patterns) and captures the edge frame of
        each hop.  Returns one HopFrame per hop: the complete set of
        predicate-passing edges reachable at that depth, with Edge
        objects batch-decoded from the CSR columns.  The caller (the
        Traverse executor) assembles trail-semantics paths from the
        layered frames on host — every pred-passing edge out of any
        vertex reachable at depth d-1 is in frame d, so frame DFS with
        connectivity + distinct-edge checks enumerates exactly the paths
        the per-vertex host DFS would.

        Raises CannotCompile when the filter doesn't vectorize (caller
        may retry with edge_filter=None and re-check rows on host —
        frames are then a superset pruned during assembly).
        """
        t_start = time.perf_counter()
        dev = self.pin(store, space)
        sd = store.space(space)
        stats = TraverseStats()
        stats.steps = max_hop
        stats.pin_s = time.perf_counter() - t_start

        block_keys = self._blocks_for(dev, etypes, direction)
        pred = None
        pred_cols: List[str] = []
        pred_key = None
        if edge_filter is not None:
            bl = dev.blocks[block_keys[0]]
            pred, pred_cols = compile_predicate(
                edge_filter, bl.prop_types, dev.pool,
                vid_to_dense=sd.dense_id)
            pred_key = E.to_text(edge_filter) if hasattr(E, "to_text") \
                else repr(edge_filter)

        dense = [sd.dense_id(v) for v in vids]
        dense = [d for d in dense if d >= 0]
        if not dense:
            return [HopFrame.empty() for _ in range(max_hop)], stats

        P = dev.num_parts
        prop_names = {n for n in pred_cols if not n.startswith("_")}
        dview, dextras = self._grab_delta(dev, block_keys, prop_names)
        blocks_data = tuple(
            {"indptr": dev.blocks[bk].indptr, "nbr": dev.blocks[bk].nbr,
             "rank": dev.blocks[bk].rank,
             "props": {n: dev.blocks[bk].props[n] for n in prop_names},
             **(dextras[i] or {})}
            for i, bk in enumerate(block_keys))

        hub_dense = getattr(dev.host, "hub_dense", None)
        hub_n = 0 if hub_dense is None else len(hub_dense)

        def build(ebs):
            if self.local_mode:
                return build_traverse_fn_local(
                    P, ebs, max_hop, len(block_keys), pred=pred,
                    pred_cols=pred_cols, capture=True, capture_hops=True,
                    hub_dense=hub_dense)
            return build_traverse_fn(
                self.mesh, P, ebs, max_hop, len(block_keys),
                pred=pred, pred_cols=pred_cols, capture=True,
                capture_hops=True, hub_dense=hub_dense)

        def key_fn(ebs):
            return (space, dev.epoch, "hops", tuple(block_keys),
                    max_hop, ebs, pred_key, tuple(pred_cols), hub_n,
                    self._delta_sig(dev))

        # multi-lane batched dispatch (ISSUE 15): concurrent MATCH
        # expansions of the same program share ONE launch
        res = self._try_batched(
            dense, dev, key_fn,
            build_lanes=self._lanes_builder(
                P, max_hop, len(block_keys), pred=pred,
                pred_cols=pred_cols, capture=True, capture_hops=True,
                hub_dense=hub_dense),
            inputs_fn=lambda ebs: (blocks_data,),
            n_hops=max_hop, uniform=True, fetch_keys=None,
            kernel="hops", stats=stats,
            delta_epoch=dview[0] if dview is not None else None)
        if res is None:
            res = self._escalate(
                dev, dense,
                key_fn=key_fn,
                build_fn=build,
                inputs_fn=lambda ebs: (blocks_data,),
                stats=stats, n_hops=max_hop, uniform=True,
                kernel="hops")

        t_mat = time.perf_counter()
        frames = self._build_frames(store, space, dev, block_keys,
                                    res["cap"], max_hop, dview=dview)
        stats.mat_s = time.perf_counter() - t_mat
        stats.result_edges = sum(f.n for f in frames)
        stats.total_s = time.perf_counter() - t_start
        return frames, stats

    def _build_frames(self, store: GraphStore, space: str,
                      dev: DeviceSnapshot, block_keys, cap, steps: int,
                      dview=None) -> List["HopFrame"]:
        """cap arrays are (P, steps, nb, EB); one columnar HopFrame per
        hop.  NO Edge objects are built here — frames carry dense-id and
        canonical-key columns, plus a per-segment decode closure that
        materializes Edge objects only for the entries the assembly
        actually emits (VERDICT r2 item 4)."""
        host = dev.host
        d2v_arr = _d2v(host)
        d2v_id = host._d2v_identity
        etype_ids = {et: store.catalog.get_edge(space, et).edge_type
                     for et, _ in block_keys}
        def make_decode(et, dirn, sgn):
            hb = host.blocks[(et, dirn)]
            de = None if dview is None else dview[1].get((et, dirn))
            ext_cache: Dict[str, np.ndarray] = {}

            def _ecol(n):
                # delta rows gather at virtual eidx = Emax + slot: the
                # base column extends with the view's numpy mirror
                if de is None:
                    return hb.props[n]
                c = ext_cache.get(n)
                if c is None:
                    c = ext_cache[n] = np.concatenate(
                        [hb.props[n], de["np"]["d_props"][n]], axis=1)
                return c

            def decode_seg(payload, offs):
                ss, dd, rr, ee, sel_p = payload
                ss, dd = ss[offs], dd[offs]
                rr, ee, sp = rr[offs], ee[offs], sel_p[offs]
                props = {n: decode_prop_column(
                    hb.prop_types[n], _ecol(n)[sp, ee], host.pool)
                    for n in hb.props}
                sv = ss if d2v_id else d2v_arr[ss]
                dvv = dd if d2v_id else d2v_arr[dd]
                names = list(props)
                cols = [props[n] for n in names]
                rrl = rr.tolist()
                return [Edge(s, d, et, rrl[i],
                             {n: c[i] for n, c in zip(names, cols)},
                             etype=sgn)
                        for i, (s, d) in enumerate(zip(sv.tolist(),
                                                       dvv.tolist()))]
            return decode_seg

        def decode_seg(payload_dec, offs):
            payload, dec = payload_dec
            return dec(payload, offs)

        frames = []
        P = cap["kcount"].shape[0]
        for h in range(steps):
            srcs, dsts, rks = [], [], []
            ket, ks, kd = [], [], []
            segs = []
            pos = 0
            for bi, (et, dirn) in enumerate(block_keys):
                kc = cap["kcount"][:, h, bi]        # (P,)
                # kept entries are a device-compacted prefix per part
                # row: per-part slice concat preserves the (part, slot)
                # order nonzero gave — per (part, src) the kept slots
                # stay contiguous ascending eidx, so the concat below is
                # already (src-stable) CSR order
                pids = [p for p in range(kc.shape[0]) if kc[p] > 0]
                if not pids:
                    continue
                perms = None
                if dview is not None \
                        and dview[1].get((et, dirn)) is not None:
                    perms = self._delta_perms(
                        cap["src"][:, h], cap["dst"][:, h],
                        cap["rank"][:, h], bi, pids, kc, P,
                        d2v_arr, d2v_id)

                def catp(name, dtype=None):
                    parts = [cap[name][p, h, bi, :kc[p]] for p in pids]
                    if perms is not None:
                        parts = [a[pm] for a, pm in zip(parts, perms)]
                    return _cat_parts(parts, dtype)

                ss = catp("src", np.int64)
                dd = catp("dst", np.int64)
                rr = catp("rank", np.int64)
                ee = catp("eidx")
                sel_p = np.repeat(np.asarray(pids, np.int64),
                                  [int(kc[p]) for p in pids])
                eid = etype_ids[et]
                sgn = eid if dirn == "out" else -eid
                srcs.append(ss)
                dsts.append(dd)
                rks.append(rr)
                # canonical physical-edge key: out/in copies of one
                # logical edge compare equal (trail dedup currency)
                ket.append(np.full(ss.size, eid, np.int64))
                ks.append(ss if dirn == "out" else dd)
                kd.append(dd if dirn == "out" else ss)
                segs.append((pos, pos + ss.size,
                             ((ss, dd, rr, ee, sel_p),
                              make_decode(et, dirn, sgn))))
                pos += ss.size
            if not srcs:
                frames.append(HopFrame.empty())
                continue
            frames.append(HopFrame.build(
                np.concatenate(srcs), np.concatenate(dsts),
                np.concatenate(rks), np.concatenate(ket),
                np.concatenate(ks), np.concatenate(kd),
                segs, decode_seg))
        return frames

    # -- BFS (FIND SHORTEST PATH device plane) ---------------------------

    def bfs(self, store: GraphStore, space: str, srcs: Sequence[Any],
            etypes: Sequence[str], direction: str, max_steps: int,
            edge_filter: Optional[E.Expr] = None
            ) -> Tuple[np.ndarray, "TraverseStats"]:
        """Level-synchronous device BFS from `srcs`.

        Returns (dist, stats): dist is (P, Vmax) int32 of BFS depths
        (-1 unreached); the caller reconstructs paths on host (parity
        with the host oracle's multi-parent BFS).  With `edge_filter`
        (compilable predicates only — raises CannotCompile otherwise)
        the BFS only traverses mask-passing edges, matching the host
        oracle's filtered expansion.
        """
        from .bfs import build_bfs_fn, build_bfs_fn_local
        dev = self.pin(store, space)
        sd = store.space(space)
        stats = TraverseStats()
        stats.steps = max_steps

        block_keys = self._blocks_for(dev, etypes, direction)
        pred = None
        pred_cols: List[str] = []
        pred_key = None
        if edge_filter is not None:
            bl = dev.blocks[block_keys[0]]
            pred, pred_cols = compile_predicate(
                edge_filter, bl.prop_types, dev.pool,
                vid_to_dense=sd.dense_id)
            pred_key = E.to_text(edge_filter)
        dense = [sd.dense_id(v) for v in srcs]
        dense = [d for d in dense if d >= 0]
        if not dense:
            return np.full((dev.num_parts, dev.vmax), -1, np.int32), stats

        P = dev.num_parts
        # direction-optimizing leg (single chip): each block's REVERSE
        # twin rides along so dense levels can go bottom-up (a vertex
        # scans its in-neighbors against the resident frontier bitmap).
        # 'both' already traverses both planes — no distinct reverse.
        rev_of = {"out": "in", "in": "out"}
        rev_keys = [(et, rev_of[d]) for et, d in block_keys
                    if d in rev_of]
        # direction-optimizing is OFF while a delta plane is armed:
        # bottom-up scans the reverse adjacency with swapped endpoint
        # semantics the delta merge doesn't model — forcing top-down
        # keeps every level's expansion delta-correct (have_rev is in
        # the jit key, and delta-armed is stable per pin, so this never
        # flip-flops compilations)
        have_rev = (self.local_mode and dev.delta is None
                    and len(rev_keys) == len(block_keys)
                    and all(rk in dev.blocks for rk in rev_keys))
        pnames = [n for n in pred_cols if not n.startswith("_")]
        dview, dextras = self._grab_delta(dev, block_keys, set(pnames))

        def _bd(bk):
            out = {"indptr": dev.blocks[bk].indptr,
                   "nbr": dev.blocks[bk].nbr,
                   "rank": dev.blocks[bk].rank}
            if pred is not None:
                out["props"] = {n: dev.blocks[bk].props[n] for n in pnames}
            return out

        blocks_data = []
        for i, bk in enumerate(block_keys):
            d = _bd(bk)
            if dextras[i] is not None:
                d.update(dextras[i])
            if have_rev:
                rb = dev.blocks[rev_keys[i]]
                d["rev_indptr"] = rb.indptr
                d["rev_nbr"] = rb.nbr
                d["rev_rank"] = rb.rank
                if pred is not None:
                    d["rev_props"] = {n: rb.props[n] for n in pnames}
                else:
                    d["rev_props"] = {}
            if pred is None:
                d.setdefault("props", {})
            blocks_data.append(d)
        blocks_data = tuple(blocks_data)

        n_phantom = int(P * dev.vmax
                        - np.asarray(dev.num_vertices).sum())
        hub_dense = getattr(dev.host, "hub_dense", None)
        hub_n = 0 if hub_dense is None else len(hub_dense)

        def build(ebs):
            if self.local_mode:
                return build_bfs_fn_local(P, ebs, max_steps,
                                          len(block_keys), dev.vmax,
                                          pred=pred, pred_cols=pred_cols,
                                          have_rev=have_rev,
                                          n_phantom=n_phantom,
                                          hub_dense=hub_dense)
            return build_bfs_fn(self.mesh, P, ebs, max_steps,
                                len(block_keys), dev.vmax,
                                pred=pred, pred_cols=pred_cols,
                                hub_dense=hub_dense)

        # Per-LEVEL edge budgets (like the traverse kernel's per-hop
        # buckets): a BFS's first and last levels examine orders of
        # magnitude fewer edges than its middle, so one uniform bucket
        # made every level pay the widest level's padding.  The kernel
        # reports exact per-level counts, so the ladder jumps straight
        # to each level's bucket; the persistent bucket cache remembers
        # the converged shape across runs.
        res = self._escalate(
            dev, dense,
            key_fn=lambda ebs: (space, dev.epoch, "bfs",
                                tuple(block_keys), max_steps, ebs,
                                pred_key, tuple(pred_cols), have_rev,
                                hub_n, self._delta_sig(dev)),
            build_fn=build,
            inputs_fn=lambda ebs: (blocks_data,),
            stats=stats, n_hops=max_steps, kernel="bfs")
        return res["dist"], stats

    # -- host materialization --------------------------------------------

    @staticmethod
    def _delta_perms(cap_src, cap_dst, cap_rank, bi, pids, kc, P,
                     d2v_arr, d2v_id):
        """Per-part permutations restoring canonical CSR slot order over
        the merged base+delta capture: within a part, base rows sit in
        (local_src, rank, dst_key) order and delta rows are appended —
        the union must interleave exactly where a full rebuild would
        have placed the new rows.  dst_key matches native.kernels.
        dst_sort_key: the vid itself for int vids, code-point string
        order otherwise (np.unique ordinals preserve it).  Keys are
        unique per live edge, so the sort is deterministic."""
        perms = []
        for p in pids:
            k = int(kc[p])
            s_ = np.asarray(cap_src[p, bi, :k]).astype(np.int64)
            d_ = np.asarray(cap_dst[p, bi, :k]).astype(np.int64)
            r_ = np.asarray(cap_rank[p, bi, :k])
            if d2v_id:
                dk = d_
            else:
                dk = d2v_arr[d_]
                if dk.dtype == object:
                    dk = dk.astype("U")
            perms.append(np.lexsort((dk, r_, s_ // P)))
        return perms

    def _block_columns(self, store: GraphStore, space: str,
                       dev: DeviceSnapshot, block_keys, cap,
                       prop_names: Optional[Sequence[str]] = None,
                       as_np: bool = False, dview=None):
        """Vectorized gather of the captured final-hop edge set.

        Yields per-block dicts of flat numpy/object arrays: sv/dv (vids),
        rr (ranks), decoded prop columns — no per-edge Python loop; vid
        decode is one fancy-index into the dense→vid array and prop
        decode is batched per column (VERDICT r1 'weak #3' fix).

        With a live delta view (`dview`, grabbed at dispatch assembly)
        the merged rows are re-sorted per part into canonical CSR order
        and delta-row props decode from the view's numpy mirror at
        virtual eidx = Emax + slot.
        """
        host = dev.host
        d2v_arr = _d2v(host)
        d2v_id = host._d2v_identity
        etype_ids = {et: store.catalog.get_edge(space, et).edge_type
                     for et, _ in block_keys}
        kcount = cap["kcount"]              # (P, nb); arrays (P, nb, K)
        P = kcount.shape[0]
        for bi, (et, dirn) in enumerate(block_keys):
            hb = host.blocks[(et, dirn)]
            de = None if dview is None else dview[1].get((et, dirn))
            # kept entries are a device-compacted PREFIX per part row —
            # selection is contiguous slices, not a 2D fancy gather
            # (nonzero + fancy indexing cost ~60% of materialization at
            # north-star scale)
            kc = kcount[:, bi]
            pids = [p for p in range(P) if kc[p] > 0]
            if not pids:
                continue
            n_rows = int(sum(int(kc[p]) for p in pids))
            perms = None
            if de is not None:
                perms = self._delta_perms(
                    cap["src"], cap["dst"], cap["rank"], bi, pids, kc,
                    P, d2v_arr, d2v_id)

            def catp(name, dtype=None):
                parts = [cap[name][p, bi, :kc[p]] for p in pids]
                if perms is not None:
                    parts = [a[pm] for a, pm in zip(parts, perms)]
                return _cat_parts(parts, dtype)

            # arrays the caller's yields never read were not fetched
            # (fetch_keys) — and are not decoded here either
            ss = catp("src", np.int64) if "src" in cap else None
            dd = catp("dst", np.int64) if "dst" in cap else None
            rr = catp("rank") if "rank" in cap else None
            props = {}
            dec = decode_prop_column_np if as_np else decode_prop_column
            ee_parts = None
            for n in (hb.props if prop_names is None else
                      [x for x in prop_names if x in hb.props]):
                if ("prop:" + n) in cap:
                    # device-gathered yield column: fetched ready-made
                    raw = catp("prop:" + n)
                elif "eidx" in cap:
                    if ee_parts is None:
                        ee_parts = [cap["eidx"][p, bi, :kc[p]]
                                    for p in pids]
                        if perms is not None:
                            ee_parts = [a[pm] for a, pm in
                                        zip(ee_parts, perms)]
                    col = hb.props[n]
                    if de is not None:
                        # extend with the delta mirror: delta rows carry
                        # virtual eidx = Emax + slot
                        col = np.concatenate(
                            [col, de["np"]["d_props"][n]], axis=1)
                    raw = [col[p][e] for p, e in zip(pids, ee_parts)]
                    raw = np.concatenate(raw) if len(raw) > 1 else raw[0]
                else:
                    continue
                props[n] = dec(hb.prop_types[n], raw, host.pool)
            eid = etype_ids[et]
            yield {"et": et, "dirn": dirn, "etype": eid if dirn == "out"
                   else -eid, "n": n_rows,
                   "sv": (ss if d2v_id else d2v_arr[ss])
                   if ss is not None else None,
                   "dv": (dd if d2v_id else d2v_arr[dd])
                   if dd is not None else None,
                   "rr": rr, "props": props,
                   "prop_types": hb.prop_types}

    def _materialize(self, store: GraphStore, space: str,
                     dev: DeviceSnapshot, block_keys, cap, dview=None
                     ) -> List[Tuple[Any, Optional[Edge], Any]]:
        """(src_vid, Edge, dst_vid) triples — Edge objects built in one
        tight zip loop over pre-decoded columns."""
        rows: List[Tuple[Any, Optional[Edge], Any]] = []
        for b in self._block_columns(store, space, dev, block_keys, cap,
                                     dview=dview):
            et, etype = b["et"], b["etype"]
            names = list(b["props"])
            cols = [b["props"][n] for n in names]
            rr = b["rr"].tolist()
            for i, (sv, dv) in enumerate(zip(b["sv"].tolist(),
                                             b["dv"].tolist())):
                props = {n: c[i] for n, c in zip(names, cols)}
                rows.append((sv, Edge(sv, dv, et, rr[i], props,
                                      etype=etype), dv))
        return rows

    def _materialize_yields(self, store: GraphStore, space: str,
                            dev: DeviceSnapshot, block_keys, cap,
                            yields, dview=None) -> ColumnarDataSet:
        """Final output as a lazy columnar DataSet (fused Project).

        Columns are numpy arrays straight from the capture buffers; no
        per-row Python objects are built here — the ColumnarDataSet
        materializes rows only if the consumer crosses the row boundary
        (VERDICT r2 item 3: device results stay columnar end-to-end)."""
        needed = [x.name for e, _ in yields for x in E.walk(e)
                  if x.kind == "edge_prop"]
        per_block: List[List[np.ndarray]] = []
        for b in self._block_columns(store, space, dev, block_keys, cap,
                                     prop_names=needed, as_np=True,
                                     dview=dview):
            per_block.append([eval_yield_column_np(e, b)
                              for e, _ in yields])
        names = [alias for _, alias in yields]
        if not per_block:
            return ColumnarDataSet(
                names, [np.empty(0, object) for _ in yields])
        if len(per_block) == 1:
            return ColumnarDataSet(names, per_block[0])

        def _cat(j):
            # ADVICE r3: int+float blocks (multi-etype GO) must not
            # upcast to float64 — that silently turns 5 into 5.0 and
            # diverges from the host path's exact per-element types.
            # Mixed numeric kinds concatenate as object instead.
            blks = [blk[j] for blk in per_block]
            kinds = {b.dtype.kind for b in blks}
            if len(kinds) > 1 and "O" not in kinds:
                blks = [b.astype(object) for b in blks]
            return np.concatenate(blks)

        return ColumnarDataSet(names, [_cat(j)
                                       for j in range(len(yields))])
