"""Device BFS — the FIND SHORTEST PATH kernel.

Level-synchronous BFS over the sharded CSR: each chip expands its shard
of the frontier, routes candidates to their owning chips
(`lax.all_to_all` over ICI), and keeps only first-visits recorded in a
per-chip dist array (the visited bitmap of SURVEY §5, sharded by vid
ownership).  The kernel returns the dist array; the host reconstructs
ALL shortest paths by walking predecessors (dist[u] == dist[v]-1)
backwards — identical path sets to the host oracle's multi-parent BFS
(exec/algorithms.py), which is the parity contract.

Reference analog: BFSShortestPathExecutor's per-hop storage fan-out +
host hash-set frontiers (src/graph/executor/algo [UNVERIFIED — empty
mount, SURVEY §0]), replaced by on-device expansion.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from .hop import MAXI, _expand_block, _merge_frontier, _route, _sorted_unique


def _visit_new(dist, fr, level: int, P: int):
    """Mark frontier vertices (dense ids, -1 pad) with `level` where
    unvisited; return (dist, filtered frontier of first-visits)."""
    valid = fr >= 0
    loc = jnp.where(valid, fr // P, 0)
    seen = dist[loc] >= 0
    first = valid & ~seen
    dist = dist.at[jnp.where(first, loc, dist.shape[0])].set(
        level, mode="drop")
    nf = jnp.where(first, fr, -1)
    # compact: sort pushes -1-as-MAXI to the tail
    key = jnp.where(nf >= 0, nf, MAXI)
    nf = jnp.sort(key)
    nf = jnp.where(nf != MAXI, nf, -1)
    return dist, nf


def build_bfs_fn(mesh, P: int, F: int, EB: int, max_steps: int,
                 n_blocks: int, vmax: int, pred=None, pred_cols=()):
    """Sharded BFS program: (blocks_data, frontier) →
    {dist (P, Vmax), ovf_* flags, hop_edges (P, steps)}.

    pred/pred_cols: optional compiled edge predicate (exprjit) — a
    filtered FIND SHORTEST PATH only traverses mask-passing edges,
    matching the host oracle's per-expansion filter."""

    def kernel(blocks_data, frontier):
        fr = frontier[0]
        dist = jnp.full((vmax,), -1, jnp.int32)
        ovf_e = jnp.zeros((), bool)
        ovf_r = jnp.zeros((), bool)
        ovf_f = jnp.zeros((), bool)
        hop_edges = []

        # level 0: sources are visited at distance 0
        dist, fr = _visit_new(dist, fr, 0, P)

        for level in range(1, max_steps + 1):
            cands = []
            edges = jnp.zeros((), jnp.int32)
            for bi in range(n_blocks):
                b = blocks_data[bi]
                src, dst, rk, eidx, ve, total, ovf = _expand_block(
                    b["indptr"][0], b["nbr"][0], b["rank"][0], fr, F, EB, P)
                ovf_e = ovf_e | ovf
                edges = edges + total
                if pred is not None:
                    cols = {"_rank": rk}
                    for name in pred_cols:
                        if name != "_rank":
                            cols[name] = b["props"][name][0][eidx]
                    keep = pred(cols) & ve
                else:
                    keep = ve
                cands.append(jnp.where(keep, dst, -1))
            hop_edges.append(edges)
            cand = jnp.concatenate(cands) if len(cands) > 1 else cands[0]
            u, _ = _sorted_unique(cand)
            out, sendc, ovf = _route(u, P, F)
            ovf_r = ovf_r | ovf
            recv = jax.lax.all_to_all(out, "part", 0, 0, tiled=False)
            recv = recv.reshape(P, F)
            fr, fcount, ovf2 = _merge_frontier(recv, F)
            ovf_f = ovf_f | ovf2
            dist, fr = _visit_new(dist, fr, level, P)

        return {"dist": dist[None], "hop_edges": jnp.stack(hop_edges)[None],
                "ovf_expand": ovf_e[None], "ovf_route": ovf_r[None],
                "ovf_frontier": ovf_f[None]}

    spec = PartitionSpec("part")
    smapped = jax.shard_map(kernel, mesh=mesh,
                            in_specs=(spec, spec), out_specs=spec)
    return jax.jit(smapped)


def build_bfs_fn_local(P: int, F: int, EB: int, max_steps: int,
                       n_blocks: int, vmax: int, pred=None, pred_cols=()):
    """Single-chip variant (vmap over parts, transpose as all_to_all)."""

    def one_part(block, f):
        src, dst, rk, eidx, ve, total, ovf = _expand_block(
            block["indptr"], block["nbr"], block["rank"], f, F, EB, P)
        if pred is not None:
            cols = {"_rank": rk}
            for name in pred_cols:
                if name != "_rank":
                    cols[name] = block["props"][name][eidx]
            keep = pred(cols) & ve
        else:
            keep = ve
        return keep, dst, total, ovf

    def fn(blocks_data, frontier):
        fr = frontier                  # (P, F)
        dist = jnp.full((P, vmax), -1, jnp.int32)
        ovf_e = jnp.zeros((P,), bool)
        ovf_r = jnp.zeros((P,), bool)
        ovf_f = jnp.zeros((P,), bool)
        hop_edges = []

        dist, fr = jax.vmap(
            lambda d, f: _visit_new(d, f, 0, P))(dist, fr)

        for level in range(1, max_steps + 1):
            cands = []
            edges = jnp.zeros((P,), jnp.int32)
            for bi in range(n_blocks):
                b = blocks_data[bi]
                keep, dst, total, ovf = jax.vmap(
                    lambda ip, nb, rkk, prp, f: one_part(
                        {"indptr": ip, "nbr": nb, "rank": rkk,
                         "props": prp}, f)
                )(b["indptr"], b["nbr"], b["rank"],
                  b.get("props", {}), fr)
                ovf_e = ovf_e | ovf
                edges = edges + total
                cands.append(jnp.where(keep, dst, -1))
            hop_edges.append(edges)
            cand = (jnp.concatenate(cands, axis=1)
                    if len(cands) > 1 else cands[0])

            def route_one(c):
                u, _ = _sorted_unique(c)
                return _route(u, P, F)
            outs, sendc, ovr = jax.vmap(route_one)(cand)
            ovf_r = ovf_r | ovr
            recv = outs.transpose(1, 0, 2)
            fr, fcount, ovr2 = jax.vmap(
                lambda r: _merge_frontier(r, F))(recv)
            ovf_f = ovf_f | ovr2
            dist, fr = jax.vmap(
                lambda d, f, lv=level: _visit_new(d, f, lv, P))(dist, fr)

        return {"dist": dist, "hop_edges": jnp.stack(hop_edges, axis=1),
                "ovf_expand": ovf_e, "ovf_route": ovf_r,
                "ovf_frontier": ovf_f}

    return jax.jit(fn)
