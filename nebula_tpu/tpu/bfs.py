"""Device BFS — the FIND SHORTEST PATH kernel (bitmap design).

Level-synchronous BFS over the sharded CSR: each chip expands its shard
of the frontier, marks candidate destinations in a per-owner bitmap,
and exchanges the bitmaps with ONE bool `lax.all_to_all` over ICI; the
receiving chip's first-visit filter is two elementwise ops against its
dist array (the visited bitmap of SURVEY §5, sharded by vid ownership).
The kernel returns the dist array; the host reconstructs ALL shortest
paths by walking predecessors (dist[u] == dist[v]-1) backwards —
identical path sets to the host oracle's multi-parent BFS
(exec/algorithms.py), which is the parity contract.

Round-4 redesign (VERDICT r3 item 3): the previous BFS shared the
sorted-frontier machinery (sort-unique, argsort routing, merge sort,
plus a scatter-based visit pass) — all gone; the frontier bitmap IS the
visited-set currency, so BFS is now expand → mark → exchange →
`new = cand & (dist < 0)` with no sorts and no frontier/route overflow.

ISSUE 13 refactor: the per-level expansion bodies (top-down expand +
mark, bottom-up reverse scan, the sharded expand + mark) moved to
nebula_tpu/algo/frontier.py — ONE frontier-iteration code path shared
with the graph-analytics vertex-program plane.  This module now only
composes those steps with the BFS-specific state update (dist/level
bookkeeping and the direction-optimizing switch).

Reference analog: BFSShortestPathExecutor's per-hop storage fan-out +
host hash-set frontiers (src/graph/executor/algo [UNVERIFIED — empty
mount, SURVEY §0]), replaced by on-device expansion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..algo.frontier import (bottom_up_step, sharded_level_step,
                             top_down_step)
from .hop import (_exchange_marks, _extend_fbm_local,
                  _extend_fbm_sharded, _hub_consts, _norm_ebs,
                  a2a_payload_bytes)


def bfs_exchange_bytes(P: int, vmax: int, max_steps: int,
                       lanes: int = 1) -> int:
    """Total bit-packed all_to_all payload of one sharded BFS run: BFS
    exchanges EVERY level (the final level's received candidates still
    update dist), unlike the traverse kernels which skip the last hop's
    exchange.  This is the number `tpu_all_to_all_bytes` grows by per
    run — the runtime accounts it analytically because the exchange is
    fused inside the jitted program (no host-visible boundary to
    measure).  Zero on a 1-part mesh."""
    return max_steps * a2a_payload_bytes(P, vmax, lanes)


def build_bfs_fn(mesh, P: int, EB, max_steps: int,
                 n_blocks: int, vmax: int, pred=None, pred_cols=(),
                 hub_dense=None):
    """Sharded BFS program: (blocks_data, frontier) →
    {dist (P, vmax), ovf_expand, hop_edges (P, steps)}.

    frontier: (P, vmax) bool seed bitmap.  pred/pred_cols: optional
    compiled edge predicate (exprjit) — a filtered FIND SHORTEST PATH
    only traverses mask-passing edges, matching the host oracle's
    per-expansion filter.

    Mesh contract (PR 17): in_specs name only the 'part' axis, so the
    same program runs on the legacy 1-D ('part',) mesh and on the
    2-axis ('lane', 'part') grid (CSR + dist replicated over the lane
    rows); the per-level exchange payload is bfs_exchange_bytes."""

    ebs = _norm_ebs(EB, max_steps, False)
    hubs_c, hub_owner, hub_local = _hub_consts(hub_dense, P)

    def kernel(blocks_data, frontier):
        fbm = frontier[0]                       # (vmax,) bool seeds
        pid = jax.lax.axis_index("part").astype(jnp.int32)
        dist = jnp.where(fbm, 0, -1).astype(jnp.int32)
        ovf_e = jnp.zeros((), bool)
        hop_edges = []

        for level in range(1, max_steps + 1):
            EBl = ebs[level - 1]
            efbm = fbm if hubs_c is None else _extend_fbm_sharded(
                fbm, pid, hub_owner, hub_local)
            marks, edges, ovf = sharded_level_step(
                blocks_data, efbm, EBl, P, pid, vmax,
                pred=pred, pred_cols=pred_cols, hub_dense=hubs_c)
            ovf_e = ovf_e | ovf
            hop_edges.append(edges)
            cand = _exchange_marks(marks, P, vmax)
            new = cand & (dist < 0)
            dist = jnp.where(new, level, dist)
            fbm = new

        return {"dist": dist[None],
                "hop_edges": jnp.stack(hop_edges)[None],
                "ovf_expand": ovf_e[None]}

    from jax.sharding import PartitionSpec

    from .device import shard_map as _shard_map
    spec = PartitionSpec("part")
    smapped = _shard_map(kernel, mesh=mesh,
                         in_specs=(spec, spec), out_specs=spec)
    return jax.jit(smapped)


def build_bfs_fn_local(P: int, EB, max_steps: int,
                       n_blocks: int, vmax: int, pred=None, pred_cols=(),
                       have_rev: bool = False, n_phantom: int = 0,
                       hub_dense=None):
    """Single-chip variant (vmap over parts, OR-reduce as all_to_all).

    With `have_rev` (blocks_data carries each block's REVERSE-direction
    twin under "rev_*" keys) the kernel is DIRECTION-OPTIMIZING: on
    dense levels it switches bottom-up — every still-unvisited vertex
    scans its reverse-adjacency and joins the next frontier if any
    in-neighbor's bit is set in the (single-chip-resident) frontier
    bitmap.  Bottom-up needs NO routing exchange at all: each owner
    decides its own vertices from the global bitmap, which is exactly
    what the bitmap-frontier currency makes cheap.  Both branches share
    the level body via lax.cond; the classic switch heuristic
    (frontier edges vs unvisited edges, Beamer-style) degrades to a
    frontier-population threshold since degrees are already summed by
    the expansion itself."""
    pids = jnp.arange(P, dtype=jnp.int32)
    ebs = _norm_ebs(EB, max_steps, False)
    hubs_c, hub_owner, hub_local = _hub_consts(hub_dense, P)

    def ext(x):
        if hubs_c is None:
            return x
        return _extend_fbm_local(x, hub_owner, hub_local, P)

    def fn(blocks_data, frontier):
        fbm = frontier                          # (P, vmax) bool seeds
        dist = jnp.where(fbm, 0, -1).astype(jnp.int32)   # (P, vmax)
        ovf_e = jnp.zeros((P,), bool)
        hop_edges = []

        def top_down(blocks, f, EBl):
            return top_down_step(blocks, ext(f), EBl, P, vmax, pids,
                                 pred=pred, pred_cols=pred_cols,
                                 hub_dense=hubs_c)

        def bottom_up(blocks, f, unvis, EBl):
            return bottom_up_step(blocks, f, ext(unvis), EBl, P, vmax,
                                  pids, pred=pred, pred_cols=pred_cols,
                                  hub_dense=hubs_c)

        for level in range(1, max_steps + 1):
            EBl = ebs[level - 1]
            if have_rev:
                unvis = dist < 0
                # dense-level switch: frontier holds a meaningful share
                # of the unvisited set → scanning unvisited in-edges
                # beats expanding frontier out-edges.  Padding slots of
                # smaller partitions sit forever in `unvis`; subtract
                # them so skewed layouts don't suppress the switch.
                use_bu = fbm.sum() * 8 > unvis.sum() - n_phantom
                cand, edges, ovf = jax.lax.cond(
                    use_bu,
                    lambda args: bottom_up(blocks_data, args[0], args[1],
                                           EBl),
                    lambda args: top_down(blocks_data, args[0], EBl),
                    (fbm, unvis))
            else:
                cand, edges, ovf = top_down(blocks_data, fbm, EBl)
            ovf_e = ovf_e | ovf
            hop_edges.append(edges)
            new = cand & (dist < 0)
            dist = jnp.where(new, level, dist)
            fbm = new

        return {"dist": dist, "hop_edges": jnp.stack(hop_edges, axis=1),
                "ovf_expand": ovf_e}

    return jax.jit(fn)
