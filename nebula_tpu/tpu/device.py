"""Mesh construction + pinning a CsrSnapshot into device HBM.

The partition axis of every snapshot array (axis 0, length P) is sharded
over the `'part'` mesh axis; each device holds exactly its partition's
adjacency + property columns — the device analog of the reference's
one-RocksDB-engine-per-data-path partition ownership (reference:
src/kvstore/NebulaStore [UNVERIFIED — empty mount, SURVEY §0]).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..graphstore.csr import CsrSnapshot, StringPool
from ..graphstore.schema import PropType

# jax moved shard_map out of experimental at ~0.6; export the resolved
# callable so every kernel module (hop, bfs, future ones) shares ONE
# version shim instead of re-probing
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:
    from jax.experimental.shard_map import shard_map


class TpuUnavailable(Exception):
    """The device plane cannot serve this space/config; callers fall back
    to the host execution path."""


def init_multihost():
    """Join a multi-host jax runtime (ICI within a slice, DCN across
    hosts) when the standard coordination env is present — after this,
    `jax.devices()` is GLOBAL and make_mesh lays partitions across every
    host's chips; `shard_map` collectives then ride ICI/DCN exactly as
    on one host (SURVEY §5 distributed-comm: data plane = XLA
    collectives, never RPC).

    Controlled by NEBULA_COORDINATOR (host:port of process 0) plus
    NEBULA_NUM_PROCESSES / NEBULA_PROCESS_ID; no-op when unset,
    idempotent when called twice."""
    import os
    coord = os.environ.get("NEBULA_COORDINATOR")
    if not coord:
        return False
    missing = [k for k in ("NEBULA_NUM_PROCESSES", "NEBULA_PROCESS_ID")
               if k not in os.environ]
    if missing:
        # a plain config error, NOT TpuUnavailable: the executors treat
        # TpuUnavailable as the routine host-fallback signal, which
        # would silently mask a half-configured multi-host deployment
        raise ValueError(
            f"NEBULA_COORDINATOR is set but {missing} are not — "
            f"multi-host init needs all three")
    if getattr(init_multihost, "_done", False):
        return True
    try:
        n_proc = int(os.environ["NEBULA_NUM_PROCESSES"])
        proc_id = int(os.environ["NEBULA_PROCESS_ID"])
    except ValueError as ex:
        raise ValueError(
            f"NEBULA_NUM_PROCESSES / NEBULA_PROCESS_ID must be "
            f"integers: {ex}") from None
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=n_proc,
            process_id=proc_id)
    except RuntimeError as ex:
        # already initialized (by the embedding app or a racing thread):
        # the runtime is up, which is all we need
        if "already" not in str(ex).lower():
            raise
    init_multihost._done = True
    return True


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D 'part' mesh: one graph partition per device slot."""
    explicit = devices is not None
    if devices is None:
        init_multihost()
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices) and not explicit:
        # 1-chip host asked for an N-way mesh: the CPU platform may carry
        # virtual devices (--xla_force_host_platform_device_count)
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
        else:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(and {len(cpu)} cpu)")
    elif n_devices > len(devices):
        raise ValueError(f"need {n_devices} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n_devices]), ("part",))


def make_mesh2(lanes: int = 1, parts: Optional[int] = None,
               devices=None) -> Mesh:
    """A 2-axis ``("lane", "part")`` mesh: the part axis owns one graph
    partition per column of devices, the lane axis spreads concurrent
    query lanes over rows (CSR blocks are replicated along it).

    Degrades gracefully instead of refusing: if ``lanes × parts`` devices
    are not available the lane axis collapses first (lanes → 1, the
    batched program still runs with every lane on the part row), then the
    part axis (parts → 1, single-chip local mode). A host with one device
    always yields the (1, 1) mesh.
    """
    explicit = devices is not None
    if devices is None:
        init_multihost()
        devices = jax.devices()
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) > len(devices):
            devices = cpu
    devices = list(devices)
    if parts is None:
        parts = max(len(devices) // max(lanes, 1), 1)
    lanes = max(int(lanes), 1)
    parts = max(int(parts), 1)
    if lanes * parts > len(devices):
        if explicit:
            raise ValueError(
                f"need {lanes}x{parts} devices, have {len(devices)}")
        # degrade: lane axis first, then part axis
        lanes = max(len(devices) // parts, 1)
        if lanes * parts > len(devices):
            lanes, parts = 1, max(len(devices), 1)
        if parts > len(devices):
            parts = 1
    grid = np.asarray(devices[:lanes * parts]).reshape(lanes, parts)
    return Mesh(grid, ("lane", "part"))


def mesh_lanes(mesh: Mesh) -> int:
    """Lane-axis size of a mesh; 1 for legacy 1-D 'part' meshes."""
    return int(dict(mesh.shape).get("lane", 1))


def mesh_parts(mesh: Mesh) -> int:
    return int(dict(mesh.shape).get("part", 1))


@dataclass
class DeviceBlock:
    """One (edge type, direction) CSR block resident on the mesh."""
    etype: str
    direction: str
    indptr: Any                       # (P, Vmax+1) i32, sharded on axis 0
    nbr: Any                          # (P, Emax)   i32
    rank: Any                         # (P, Emax)   i32
    props: Dict[str, Any] = field(default_factory=dict)   # (P, Emax)
    prop_types: Dict[str, PropType] = field(default_factory=dict)


@dataclass
class DeviceTag:
    tag: str
    present: Any                      # (P, Vmax) bool
    props: Dict[str, Any] = field(default_factory=dict)   # (P, Vmax)
    prop_types: Dict[str, PropType] = field(default_factory=dict)


@dataclass
class DeviceDelta:
    """Device-resident delta-CSR buffers for one pinned snapshot
    (ISSUE 19): per (etype, direction) block, padded insert rows +
    sorted tombstoned base edge indices, re-put whole per commit group
    (small: (P, Dcap)/(P, Tcap)).  `host` is the numpy mirror
    (graphstore.delta.HostDelta) the arrays are rebuilt from."""
    host: Any
    # bk → {"d_src","d_dst","d_rank","d_valid","d_tomb": device arrays,
    #        "d_props": {name: device array},
    #        "np": the numpy block_arrays dict these were put from}
    blocks: Dict[Tuple[str, str], Dict[str, Any]] = field(
        default_factory=dict)
    applied_epoch: int = 0            # store epoch the delta covers
    epoch: int = 0                    # bumped per device apply (jit/batch
    #                                   compatibility keys carry it; the
    #                                   BASE epoch stays fixed, so XLA
    #                                   programs and caches survive)
    # (epoch, blocks) published as ONE tuple: dispatch assembly runs
    # outside the gate, so it must grab a mutually-consistent pair —
    # an apply REPLACES the blocks dict (copy-on-write) and then swaps
    # this tuple in one atomic attribute write
    view: Tuple[int, Dict[Tuple[str, str], Dict[str, Any]]] = (0, None)

    def _leaves(self):
        for arrs in self.blocks.values():
            for k, v in arrs.items():
                if k == "d_props":
                    yield from v.values()
                elif k != "np":
                    yield v


@dataclass
class DeviceSnapshot:
    """Epoch-tagged device-resident copy of one space."""
    space: str
    epoch: int
    num_parts: int
    vmax: int
    mesh: Mesh
    num_vertices: Any                 # (P,) i32
    blocks: Dict[Tuple[str, str], DeviceBlock] = field(default_factory=dict)
    tags: Dict[str, DeviceTag] = field(default_factory=dict)
    pool: StringPool = field(default_factory=StringPool)
    host: Optional[CsrSnapshot] = None   # kept for vid decode / oracle
    # uid of the SpaceData this snapshot was pinned from (None when the
    # accessor has no uid — cluster views, prebuilt bench snapshots);
    # guards the runtime's per-space cache across distinct stores
    space_uid: Optional[int] = None

    # device delta-CSR (ISSUE 19); None = delta plane off for this pin
    delta: Optional[DeviceDelta] = None

    # set by runtime.pin when a newer epoch replaced this snapshot and its
    # device buffers were donated (deleted); dispatch paths check it under
    # the read gate and fall back instead of touching dead buffers
    retired: bool = False

    def block(self, etype: str, direction: str = "out") -> DeviceBlock:
        return self.blocks[(etype, direction)]

    def _leaves(self):
        yield self.num_vertices
        for b in self.blocks.values():
            yield b.indptr
            yield b.nbr
            yield b.rank
            yield from b.props.values()
        for t in self.tags.values():
            yield t.present
            yield from t.props.values()
        if self.delta is not None:
            yield from self.delta._leaves()

    def hbm_bytes(self) -> int:
        return sum(a.nbytes for a in self._leaves())

    def shard_hbm_bytes(self) -> Dict[int, int]:
        """Per-shard HBM ledger: bytes resident on each part-axis shard.

        Every snapshot leaf is (P, ...) with axis 0 sharded (or, in
        single-chip mode, wholly resident on the one device), so each
        part's share is exactly nbytes / P per leaf — lane-axis replicas
        are not double counted (they are copies of the same partition).
        """
        P = max(int(self.num_parts), 1)
        if mesh_parts(self.mesh) == 1:
            return {0: self.hbm_bytes()}
        per = {p: 0 for p in range(P)}
        for a in self._leaves():
            share = a.nbytes // P
            for p in range(P):
                per[p] += share
        return per

    def delete_buffers(self) -> None:
        """Donate this snapshot's device buffers back to the allocator
        (re-pin path: the old epoch is freed BEFORE the new epoch is
        placed, so peak HBM stays ~1x instead of 2x). Idempotent."""
        self.retired = True
        for a in self._leaves():
            try:
                a.delete()
            except Exception:
                pass


def make_putter(mesh: Mesh, num_parts: int):
    """The placement closure shared by full pins and delta applies:
    single-chip mode puts whole arrays on the one device; multi-part
    mode puts partition p's row directly onto column-p device(s) and
    assembles with make_array_from_single_device_arrays (no host-side
    concat, no all-device broadcast copy), replicated down the lane
    axis."""
    P = mesh_parts(mesh)
    L = mesh_lanes(mesh)
    if P == 1:
        # single-chip mode: every partition resident on the one device;
        # the local (vmap) kernel runs the same program without ICI
        dev0 = mesh.devices.reshape(-1)[0]

        def put(a: np.ndarray):
            return jax.device_put(a, dev0)
        return put
    if num_parts == P:
        part0 = NamedSharding(mesh, PartitionSpec("part"))
        grid = mesh.devices.reshape(L, P)

        def put(a: np.ndarray):
            shards = []
            for row in grid:                     # lane replicas
                for p, d in enumerate(row):      # one partition per column
                    shards.append(jax.device_put(a[p:p + 1], d))
            return jax.make_array_from_single_device_arrays(
                a.shape, part0, shards)
        return put
    raise TpuUnavailable(
        f"snapshot has {num_parts} parts but mesh has {P} devices; "
        f"create the space with partition_num == mesh size to pin it")


def put_delta_blocks(dev: DeviceSnapshot, host_delta,
                     block_keys=None) -> int:
    """(Re-)place delta buffers for `block_keys` (None = all blocks) of
    a pinned snapshot; returns bytes transferred.  Replaced buffers are
    NOT force-deleted: a batch group formed just before this apply may
    still hold references to them in its launch closure (there is no
    `retired` divert for an in-place delta apply, unlike a full
    re-pin), so the old copies are released by refcount instead —
    they are commit-group-sized, not graph-sized."""
    put = make_putter(dev.mesh, dev.num_parts)
    if dev.delta is None:
        dev.delta = DeviceDelta(host=host_delta,
                                applied_epoch=dev.epoch)
    dd = dev.delta
    keys = list(dev.blocks if block_keys is None else block_keys)
    new_blocks = dict(dd.blocks)       # copy-on-write: see DeviceDelta.view
    moved = 0
    for bk in keys:
        arrs = host_delta.block_arrays(bk)
        placed: Dict[str, Any] = {"np": arrs}
        for k, v in arrs.items():
            if k == "d_props":
                placed[k] = {n: put(a) for n, a in v.items()}
                moved += sum(a.nbytes for a in v.values())
            else:
                placed[k] = put(v)
                moved += v.nbytes
        new_blocks[bk] = placed
    dd.epoch += 1
    dd.blocks = new_blocks
    dd.view = (dd.epoch, new_blocks)
    return moved


def pin_snapshot(snap: CsrSnapshot, mesh: Mesh) -> DeviceSnapshot:
    """device_put every snapshot array, sharded over the 'part' axis.

    The snapshot's partition count must equal the mesh part-axis size —
    the 1:1 partition↔chip contract (SURVEY §2b, partition parallelism
    row). Multi-part placement is per-device: partition p's row is put
    directly onto the column-p device(s) and assembled with
    `make_array_from_single_device_arrays`, so no host-side concat and
    no all-device broadcast copy ever materialises. On a 2-axis
    ("lane", "part") mesh the CSR rows are replicated down each lane-axis
    column (each lane row sees its own resident copy of partition p).
    """
    put = make_putter(mesh, snap.num_parts)
    dev = DeviceSnapshot(space=snap.space, epoch=snap.epoch,
                         num_parts=snap.num_parts, vmax=snap.vmax, mesh=mesh,
                         num_vertices=put(snap.num_vertices),
                         pool=snap.pool, host=snap)
    for key, b in snap.blocks.items():
        dev.blocks[key] = DeviceBlock(
            etype=b.etype, direction=b.direction,
            indptr=put(b.indptr), nbr=put(b.nbr), rank=put(b.rank),
            props={k: put(v) for k, v in b.props.items()},
            prop_types=dict(b.prop_types))
    for name, t in snap.tags.items():
        dev.tags[name] = DeviceTag(
            tag=name, present=put(t.present),
            props={k: put(v) for k, v in t.props.items()},
            prop_types=dict(t.prop_types))
    return dev
