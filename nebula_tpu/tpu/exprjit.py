"""Compile nGQL predicate subtrees to vectorized jnp mask functions.

The reference evaluates pushed-down edge filters row-at-a-time inside
storaged's scan loop (StorageExpressionContext; reference:
src/storage/exec [UNVERIFIED — empty mount, SURVEY §0]).  Here the same
predicate becomes ONE jnp expression over whole property columns — the
north-star "vectorized property-predicate mask" — with the host
interpreter's exact semantics:

  * three-valued logic: every compiled term is a (value, is_null) pair;
    Kleene AND/OR, null-propagating arithmetic & comparisons;
  * division / modulo by zero → null (NullKind collapses to "drop row"
    under a WHERE, which is all a mask needs);
  * strings are dict codes (int64): ==, !=, IN compile; ordering /
    CONTAINS / regex on strings do NOT (structural `compilable()` check
    refuses fusion, the row stays on the host path);
  * NULL sentinels: INT64_MIN in int/string columns, NaN in floats.

`compilable(expr, etypes)` is the static gate the optimizer rule uses;
`compile_predicate(expr, block, pool)` produces the mask fn used inside
the hop kernel.  Columns arrive as a dict: reserved keys `_rank`, `_src`, `_dst` (endpoint DENSE ids for id($^)/id($$)) plus one
key per edge property name.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from ..core import expr as E
from ..core.value import NullValue
from ..graphstore.csr import INT_NULL, StringPool
from ..graphstore.schema import PropType


class CannotCompile(Exception):
    pass


_NUMERIC = ("int", "float")
_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
_ARITH_OPS = ("+", "-", "*", "/", "%")
_BIT_OPS = ("&", "|", "^")          # int-only; others refuse
_LOGIC_OPS = ("AND", "OR", "XOR")


def _is_string_type(pt: PropType) -> bool:
    return pt in (PropType.STRING, PropType.FIXED_STRING)


def _kind_of(pt: PropType) -> str:
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        return "float"
    if _is_string_type(pt):
        return "str"
    if pt == PropType.BOOL:
        return "bool"
    # Temporal kinds stay distinct: the host engine returns BAD_TYPE for
    # e.g. DateTime < int, so the device must not compare their raw int
    # encodings against numeric literals (same-kind compares are fine —
    # the encodings are order-isomorphic).
    if pt == PropType.DATE:
        return "date"
    if pt == PropType.TIME:
        return "time"
    if pt == PropType.DATETIME:
        return "datetime"
    if pt == PropType.DURATION:
        return "duration"
    if pt == PropType.GEOGRAPHY:
        return "geo"    # distinct kind: no device op compares geographies
    return "int"        # ints + TIMESTAMP (host value is a plain int)


# ---------------------------------------------------------------------------
# Static compilability gate (no pool / schema values needed)
# ---------------------------------------------------------------------------


def compilable(e: E.Expr, etypes: Sequence[str]) -> bool:
    """True iff `compile_predicate` will succeed for this expr against a
    single-block hop over one of `etypes`.  Conservative."""
    try:
        _check(e, set(etypes))
        return True
    except CannotCompile:
        return False


def _edge_prop_ref(e: E.Expr):
    """Normalize the three spellings of an edge-property reference:
    EdgeProp (validator-canonical), AttributeExpr(LabelExpr) (raw parse of
    `knows.w`), rank(edge).  Returns (edge_name_or_None, prop) or None."""
    if isinstance(e, E.EdgeProp):
        return (e.edge, e.name)
    if isinstance(e, E.AttributeExpr) and isinstance(e.obj, E.LabelExpr):
        return (e.obj.name, e.attr)
    if (isinstance(e, E.FunctionCall) and e.name.lower() == "rank"
            and len(e.args) == 1 and isinstance(e.args[0], E.EdgeExpr)):
        return (None, "_rank")
    return None


def _vid_ref(e: E.Expr):
    """id($$) / id($^) → the capture column holding that endpoint's
    DENSE id ("_dst" / "_src").  Compilable only in direct comparisons
    against literal vids (the literal translates to a dense id at
    compile time; arbitrary arithmetic over vids cannot)."""
    if (isinstance(e, E.FunctionCall) and e.name.lower() == "id"
            and len(e.args) == 1 and getattr(e.args[0], "kind", "")
            == "vertex"):
        which = getattr(e.args[0], "which", "")
        if which == "$$":
            return "_dst"
        if which == "$^":
            return "_src"
    return None


def _nonnull_lit(x: E.Expr) -> bool:
    """Literal usable in a dense-id compare.  NULL is out (comparison
    answers NULL on the host — see _id_pred_shape_ok) and so is bool:
    hash(True)==hash(1) would resolve a dense id for `id(v) == true`
    while host v_eq answers False for int-vs-bool."""
    return (isinstance(x, E.Literal) and x.value is not None
            and not isinstance(x.value, (NullValue, bool)))


def _id_pred_shape_ok(e: "E.Binary", l_ref: bool, r_ref: bool) -> bool:
    """Shared id-vs-literal shape gate for the edge plane (id($$)/id($^))
    and the vertex plane (id(alias)).  NULL literals are rejected: the
    host's comparison-with-NULL answers NULL (row dropped), which a
    dense-id compare cannot express for the negated ops ('!=' /
    'NOT IN' would mask every row back IN)."""
    if e.op in ("==", "!=") and ((l_ref and _nonnull_lit(e.rhs))
                                 or (r_ref and _nonnull_lit(e.lhs))):
        return True
    if e.op in ("IN", "NOT IN") and l_ref \
            and isinstance(e.rhs, (E.ListExpr, E.SetExpr)) \
            and all(_nonnull_lit(i) for i in e.rhs.items):
        return True
    return False


def _check(e: E.Expr, etypes: Set[str]):
    if isinstance(e, E.Literal):
        v = e.value
        if v is None or isinstance(v, (bool, int, float, str, NullValue)):
            return
        raise CannotCompile(f"literal {type(v)}")
    ref = _edge_prop_ref(e)
    if ref is not None:
        edge, name = ref
        if name in ("_src", "_dst", "_type"):
            raise CannotCompile("edge reserved prop beyond _rank")
        if name != "_rank" and len(etypes) != 1:
            raise CannotCompile("prop predicate over multiple edge types")
        # "__edge__" is the planner's alias for the edge being traversed
        # (MATCH inline props, _edge_pred) — always the single etype here
        if name != "_rank" and edge != "__edge__" and edge not in etypes:
            raise CannotCompile(f"predicate on non-traversed edge {edge}")
        return
    if isinstance(e, E.Unary):
        if e.op in ("NOT", "-", "+", "IS_NULL", "IS_NOT_NULL"):
            _check(e.operand, etypes)
            return
        raise CannotCompile(f"unary {e.op}")
    if isinstance(e, E.Binary):
        # endpoint-id predicate: id($$)/id($^) vs literal vid(s) only
        lv, rv = _vid_ref(e.lhs), _vid_ref(e.rhs)
        if lv or rv:
            if _id_pred_shape_ok(e, bool(lv), bool(rv)):
                return
            raise CannotCompile(
                "id($$)/id($^) only compiles vs non-null literal vids")
        if e.op in _LOGIC_OPS + _CMP_OPS + _ARITH_OPS + _BIT_OPS:
            _check(e.lhs, etypes)
            _check(e.rhs, etypes)
            return
        if e.op in ("IN", "NOT IN"):
            _check(e.lhs, etypes)
            if not isinstance(e.rhs, (E.ListExpr, E.SetExpr)):
                raise CannotCompile("IN rhs must be a literal list")
            for item in e.rhs.items:
                if not isinstance(item, E.Literal):
                    raise CannotCompile("IN item not literal")
            return
        raise CannotCompile(f"binary {e.op}")
    raise CannotCompile(f"expr kind {e.kind}")


# ---------------------------------------------------------------------------
# Compilation — terms are (value_array, null_mask, kind)
# ---------------------------------------------------------------------------

Term = Tuple[Any, Any, str]             # (val, isnull, kind)
MaskFn = Callable[[Dict[str, Any]], Any]


def compile_predicate(e: E.Expr, prop_types: Dict[str, PropType],
                      pool: StringPool,
                      vid_to_dense=None) -> Tuple[MaskFn, List[str]]:
    """Returns (mask_fn, needed_columns).  mask_fn(cols) -> bool array:
    True where the predicate evaluates to (non-null) true.

    vid_to_dense: vid → dense id (-1 unknown), required to compile
    id($$)/id($^) comparisons — the literal vid translates to the dense
    currency the kernel's src/dst columns carry."""
    needed: Set[str] = set()

    def dense_of(v):
        if vid_to_dense is None:
            raise CannotCompile("no vid→dense mapping for id() predicate")
        d = vid_to_dense(v)
        return int(d) if d is not None else -1

    def vid_cmp(col, op, values):
        """id(endpoint) ==/!=/IN literal vid(s) → dense comparison;
        unknown vids map to -1, which no real dense id equals."""
        needed.add(col)
        dv = [dense_of(v.value) for v in values]

        def g(c):
            ep = c[col]
            m = jnp.zeros(jnp.shape(ep), bool)
            for d in dv:
                m = m | (ep == d)
            if op in ("!=", "NOT IN"):
                m = jnp.logical_not(m)
            return (m, jnp.zeros(jnp.shape(ep), bool), "bool")
        return g

    def build(x: E.Expr) -> Callable[[Dict[str, Any]], Term]:
        if isinstance(x, E.Binary):
            lv, rv = _vid_ref(x.lhs), _vid_ref(x.rhs)
            if lv or rv:
                if x.op in ("==", "!="):
                    col = lv or rv
                    lit = x.rhs if lv else x.lhs
                    if not isinstance(lit, E.Literal):
                        raise CannotCompile("id() vs non-literal")
                    return vid_cmp(col, x.op, [lit])
                if x.op in ("IN", "NOT IN") and lv:
                    return vid_cmp(lv, x.op, list(x.rhs.items))
                raise CannotCompile("id() predicate shape")
        if isinstance(x, E.Literal):
            return _lit(x.value, pool)
        ref = _edge_prop_ref(x)
        if ref is not None:
            _, pname = ref
            if pname == "_rank":
                needed.add("_rank")
                return lambda c: (c["_rank"],
                                  jnp.zeros(c["_rank"].shape, bool), "int")
            pt = prop_types.get(pname)
            if pt is None:
                raise CannotCompile(f"unknown edge prop {pname}")
            kind = _kind_of(pt)
            name = pname
            needed.add(name)
            if kind == "float":
                return lambda c: (c[name], jnp.isnan(c[name]), "float")
            if kind == "bool":
                return lambda c: (c[name] != 0, c[name] == INT_NULL, "bool")
            return lambda c: (c[name], c[name] == INT_NULL, kind)
        if isinstance(x, E.Unary):
            return _unary(x.op, build(x.operand))
        if isinstance(x, E.Binary):
            if x.op in ("IN", "NOT IN"):
                return _in_list(build(x.lhs),
                                [it.value for it in x.rhs.items],
                                pool, negate=x.op == "NOT IN")
            return _binary(x.op, build(x.lhs), build(x.rhs))
        raise CannotCompile(f"expr kind {x.kind}")

    term = build(e)

    def mask_fn(cols: Dict[str, Any]):
        val, isnull, kind = term(cols)
        if kind != "bool":
            # non-bool WHERE result: host to_bool3 yields null → drop row
            return jnp.zeros(val.shape, bool)
        return jnp.logical_and(val, jnp.logical_not(isnull))

    return mask_fn, sorted(needed)


def _term_alg(xp):
    """Build the (value, is_null, kind) term algebra over one array
    namespace.  The SAME code compiles the in-kernel jnp mask functions
    (hop predicate pushdown) and the host-side numpy vertex-predicate
    masks (fused MATCH tail, match_agg.py) — jnp and np agree on every
    op used here, so the two planes cannot drift semantically."""

    def _lit(v: Any, pool: StringPool) -> Callable[[Dict[str, Any]], Term]:
        if v is None or isinstance(v, NullValue):
            return lambda c: (xp.zeros((), xp.int64), xp.ones((), bool),
                              "int")
        if isinstance(v, bool):
            return lambda c: (xp.asarray(v), xp.zeros((), bool), "bool")
        if isinstance(v, int):
            if not (-(1 << 63) <= v < (1 << 63)):
                # host compares arbitrary-precision ints; fall back
                raise CannotCompile("int literal outside int64")
            return lambda c: (xp.asarray(v, xp.int64), xp.zeros((), bool),
                              "int")
        if isinstance(v, float):
            return lambda c: (xp.asarray(v, xp.float64),
                              xp.zeros((), bool), "float")
        if isinstance(v, str):
            code = pool.lookup(v)   # -2 when absent: equals nothing non-null
            return lambda c: (xp.asarray(code, xp.int64),
                              xp.zeros((), bool), "str")
        raise CannotCompile(f"literal {type(v)}")

    def _unary(op: str, f) -> Callable[[Dict[str, Any]], Term]:
        def g(c):
            v, n, k = f(c)
            if op == "IS_NULL":
                return (n, xp.zeros(xp.shape(n), bool), "bool")
            if op == "IS_NOT_NULL":
                return (xp.logical_not(n), xp.zeros(xp.shape(n), bool),
                        "bool")
            if op == "NOT":
                if k != "bool":
                    raise CannotCompile("NOT on non-bool")
                return (xp.logical_not(v), n, "bool")
            if op == "-":
                if k not in _NUMERIC:
                    raise CannotCompile("negate non-numeric")
                return (-v, n, k)
            if op == "+":
                if k not in _NUMERIC:
                    raise CannotCompile("+x non-numeric")
                return (v, n, k)
            raise CannotCompile(f"unary {op}")
        return g

    def _coerce_pair(av, ak, bv, bk):
        """Numeric promotion for mixed int/float operands."""
        if ak == bk:
            return av, bv, ak
        if set((ak, bk)) == {"int", "float"}:
            return (av.astype(xp.float64) if ak == "int" else av,
                    bv.astype(xp.float64) if bk == "int" else bv, "float")
        raise CannotCompile(f"type mix {ak}/{bk}")

    def _binary(op: str, fa, fb) -> Callable[[Dict[str, Any]], Term]:
        def g(c):
            av, an, ak = fa(c)
            bv, bn, bk = fb(c)
            if op in _LOGIC_OPS:
                if ak != "bool" or bk != "bool":
                    raise CannotCompile("logic on non-bool")
                if op == "AND":
                    is_false = (~an & ~av) | (~bn & ~bv)
                    val = ~is_false
                    null = ~is_false & (an | bn)
                    return (val & ~null, null, "bool")
                if op == "OR":
                    is_true = (~an & av) | (~bn & bv)
                    null = ~is_true & (an | bn)
                    return (is_true, null, "bool")
                # XOR
                return (xp.logical_xor(av, bv), an | bn, "bool")
            if op in _CMP_OPS:
                null = an | bn
                if "str" in (ak, bk) or "bool" in (ak, bk) or "geo" in (ak, bk):
                    if ak != bk:
                        raise CannotCompile(f"compare {ak} vs {bk}")
                    if op not in ("==", "!="):
                        # dict codes are insertion-ordered, not value-ordered
                        raise CannotCompile(f"ordering on {ak}")
                    val = (av == bv) if op == "==" else (av != bv)
                    return (val, null, "bool")
                a2, b2, _ = _coerce_pair(av, ak, bv, bk)
                val = {"==": a2 == b2, "!=": a2 != b2, "<": a2 < b2,
                       "<=": a2 <= b2, ">": a2 > b2, ">=": a2 >= b2}[op]
                return (val, null, "bool")
            if op in _ARITH_OPS:
                if ak not in _NUMERIC or bk not in _NUMERIC:
                    raise CannotCompile(f"arith on {ak}/{bk}")
                a2, b2, k = _coerce_pair(av, ak, bv, bk)
                null = an | bn
                if op == "+":
                    return (a2 + b2, null, k)
                if op == "-":
                    return (a2 - b2, null, k)
                if op == "*":
                    return (a2 * b2, null, k)
                if op == "/":
                    null = null | (b2 == 0)
                    safe = xp.where(b2 == 0, xp.ones((), b2.dtype), b2)
                    if k == "int":
                        # host semantics: truncation toward zero
                        q = xp.abs(a2) // xp.abs(safe)
                        sign = xp.where((a2 >= 0) == (safe >= 0), 1, -1)
                        return (q * sign, null, "int")
                    return (a2 / safe, null, "float")
                # %
                null = null | (b2 == 0)
                safe = xp.where(b2 == 0, xp.ones((), b2.dtype), b2)
                if k == "int":
                    # host v_mod: sign follows the dividend (C fmod style)
                    r = xp.abs(a2) % xp.abs(safe)
                    return (xp.where(a2 >= 0, r, -r), null, "int")
                return (xp.where(xp.signbit(a2),
                                 -(xp.abs(a2) % xp.abs(safe)),
                                 xp.abs(a2) % xp.abs(safe)), null, "float")
            if op in _BIT_OPS:
                # host gives BAD_TYPE (row-dropping) for non-int
                # operands incl. bools/floats — only the int/int shape
                # compiles; everything else falls back
                if ak != "int" or bk != "int":
                    raise CannotCompile(f"bitwise on {ak}/{bk}")
                null = an | bn
                val = {"&": av & bv, "|": av | bv, "^": av ^ bv}[op]
                return (val, null, "int")
            raise CannotCompile(f"binary {op}")
        return g

    def _in_list(fa, items: List[Any], pool: StringPool,
                 negate: bool) -> Callable[[Dict[str, Any]], Term]:
        def g(c):
            av, an, ak = fa(c)
            any_true = xp.zeros(xp.shape(av), bool)
            any_null = xp.zeros(xp.shape(av), bool)
            for it in items:
                if it is None or isinstance(it, NullValue):
                    any_null = xp.ones(xp.shape(av), bool)
                    continue
                # type-mismatched items yield NULL from v_eq on the host
                # (not False), so anything not exactly comparable must
                # fall back
                if isinstance(it, bool):
                    if ak != "bool":
                        raise CannotCompile("IN bool item vs non-bool")
                    any_true = any_true | (av == it)
                elif isinstance(it, int):
                    if ak not in _NUMERIC \
                            or not (-(1 << 63) <= it < (1 << 63)):
                        raise CannotCompile("IN int item vs non-numeric")
                    if ak == "int":
                        any_true = any_true | (av == it)
                    else:
                        any_true = any_true | (av == float(it))
                elif isinstance(it, float):
                    if ak not in _NUMERIC:
                        raise CannotCompile("IN float item vs non-numeric")
                    any_true = any_true | (av.astype(xp.float64) == it)
                elif isinstance(it, str):
                    if ak != "str":
                        raise CannotCompile("IN str item vs non-string")
                    any_true = any_true | (av == pool.lookup(it))
                else:
                    raise CannotCompile(f"IN item {type(it)}")
            val = any_true
            null = an | (~any_true & any_null)
            if negate:
                return (~val & ~null, null, "bool")
            return (val & ~null, null, "bool")
        return g

    return _lit, _unary, _coerce_pair, _binary, _in_list


_lit, _unary, _coerce_pair, _binary, _in_list = _term_alg(jnp)
_np_lit, _np_unary, _np_coerce_pair, _np_binary, _np_in_list = _term_alg(np)


# ---------------------------------------------------------------------------
# Vertex-predicate compiler (numpy, host plane)
# ---------------------------------------------------------------------------
#
# The fused MATCH pipeline (tpu/match_agg.py) evaluates AppendVertices
# filters — `_hastag(v, "Tag")`, `v.Tag.prop > x`, compositions — as ONE
# numpy mask over the snapshot's TagTable columns instead of per-row
# Python `Expr.eval` over built Vertex objects.  Same Term algebra as
# the in-kernel predicate compiler (shared `_term_alg`), numpy-bound so
# a host-side mask never dispatches to the device.


def _vertex_ref(x: "E.Expr", alias: str):
    """Classify a vertex-alias reference.  Returns ("prop", tag, prop) |
    ("attr", prop) | ("hastag", tag) | None; raises CannotCompile on a
    reference to a DIFFERENT alias (the caller's filter must be
    single-alias)."""
    if isinstance(x, E.LabelTagProp):
        if x.var != alias:
            raise CannotCompile(f"prop of other alias {x.var}")
        return ("prop", x.tag, x.prop)
    if isinstance(x, E.AttributeExpr) and isinstance(x.obj, E.LabelExpr):
        # tag-less `v.prop`: get_attribute over the MERGED tag props
        # (later tag in sorted order wins on a name collision)
        if x.obj.name != alias:
            raise CannotCompile(f"attr of other alias {x.obj.name}")
        return ("attr", x.attr)
    if (isinstance(x, E.FunctionCall) and x.name == "_hastag"
            and len(x.args) == 2 and isinstance(x.args[0], E.LabelExpr)
            and isinstance(x.args[1], E.Literal)
            and isinstance(x.args[1].value, str)):
        if x.args[0].name != alias:
            raise CannotCompile(f"_hastag of other alias {x.args[0].name}")
        return ("hastag", x.args[1].value)
    return None


def _vertex_id_ref(x: "E.Expr", alias: str) -> bool:
    """True iff x is id(<alias>)."""
    return (isinstance(x, E.FunctionCall) and x.name == "id"
            and len(x.args) == 1 and isinstance(x.args[0], E.LabelExpr)
            and x.args[0].name == alias)


def vertex_compilable(e: "E.Expr", alias: str) -> bool:
    """Static gate: will compile_vertex_predicate_np succeed (given the
    snapshot has the referenced tags)?  Conservative, schema-free."""
    try:
        _vertex_check(e, alias)
        return True
    except CannotCompile:
        return False


def _vertex_check(e: "E.Expr", alias: str):
    if isinstance(e, E.Literal):
        v = e.value
        if v is None or isinstance(v, (bool, int, float, str, NullValue)):
            return
        raise CannotCompile(f"literal {type(v)}")
    if _vertex_ref(e, alias) is not None:
        return
    if isinstance(e, E.Unary):
        if e.op in ("NOT", "-", "+", "IS_NULL", "IS_NOT_NULL"):
            _vertex_check(e.operand, alias)
            return
        raise CannotCompile(f"unary {e.op}")
    if isinstance(e, E.Binary):
        li, ri = _vertex_id_ref(e.lhs, alias), _vertex_id_ref(e.rhs, alias)
        if li or ri:
            if _id_pred_shape_ok(e, li, ri):
                return
            raise CannotCompile("id(v) only compiles vs non-null "
                                "literal vids")
        if e.op in _LOGIC_OPS + _CMP_OPS + _ARITH_OPS + _BIT_OPS:
            _vertex_check(e.lhs, alias)
            _vertex_check(e.rhs, alias)
            return
        if e.op in ("IN", "NOT IN"):
            _vertex_check(e.lhs, alias)
            if not isinstance(e.rhs, (E.ListExpr, E.SetExpr)):
                raise CannotCompile("IN rhs must be a literal list")
            for item in e.rhs.items:
                if not isinstance(item, E.Literal):
                    raise CannotCompile("IN item not literal")
            return
        raise CannotCompile(f"binary {e.op}")
    raise CannotCompile(f"expr kind {e.kind}")


def compile_vertex_predicate_np(e: "E.Expr", alias: str, snap,
                                sd) -> Callable[["np.ndarray"], "np.ndarray"]:
    """Compile a single-alias vertex predicate against CsrSnapshot tag
    tables.  Returns mask_fn(dense_ids) -> bool array: True where the
    predicate is (non-null) true for the vertex with that dense id.

    Tag-table null currency matches the edge plane: INT_NULL sentinel in
    int-coded columns, NaN in floats — absent-tag rows carry the fill,
    so `v.Tag.prop` on a vertex without Tag is NULL exactly like the
    host's per-row lookup (core/expr.py LabelTagProp)."""
    P = snap.num_parts
    pool = snap.pool

    def dense_of(v):
        d = sd.dense_id(v)
        return int(d) if d is not None else -1

    def vid_cmp(op, values):
        dv = [dense_of(x.value) for x in values]

        def g(c):
            ep = c["_dense"]
            m = np.zeros(np.shape(ep), bool)
            for d in dv:
                m = m | (ep == d)
            if op in ("!=", "NOT IN"):
                m = np.logical_not(m)
            return (m, np.zeros(np.shape(ep), bool), "bool")
        return g

    def build(x: "E.Expr"):
        if isinstance(x, E.Binary):
            li, ri = _vertex_id_ref(x.lhs, alias), _vertex_id_ref(x.rhs, alias)
            if li or ri:
                if x.op in ("==", "!="):
                    lit = x.rhs if li else x.lhs
                    if not isinstance(lit, E.Literal):
                        raise CannotCompile("id(v) vs non-literal")
                    return vid_cmp(x.op, [lit])
                if x.op in ("IN", "NOT IN") and li:
                    return vid_cmp(x.op, list(x.rhs.items))
                raise CannotCompile("id(v) predicate shape")
        if isinstance(x, E.Literal):
            return _np_lit(x.value, pool)
        ref = _vertex_ref(x, alias)
        if ref is not None:
            if ref[0] == "attr":
                return _attr_term(snap, P, ref[1])
            if ref[0] == "hastag":
                tt = snap.tags.get(ref[1])
                if tt is None:
                    return lambda c: (np.zeros(np.shape(c["_dense"]), bool),
                                      np.zeros(np.shape(c["_dense"]), bool),
                                      "bool")
                pres = tt.present

                def g(c, pres=pres):
                    d = c["_dense"]
                    m = pres[d % P, d // P]
                    return (m, np.zeros(m.shape, bool), "bool")
                return g
            _, tag, pname = ref
            tt = snap.tags.get(tag)
            if tt is None or pname not in tt.props:
                # unknown tag/prop → NULL (host LabelTagProp: absent)
                return lambda c: (np.zeros(np.shape(c["_dense"]), np.int64),
                                  np.ones(np.shape(c["_dense"]), bool),
                                  "int")
            kind = _kind_of(tt.prop_types[pname])
            col = tt.props[pname]

            def g(c, col=col, kind=kind):
                d = c["_dense"]
                raw = col[d % P, d // P]
                if kind == "float":
                    return (raw, np.isnan(raw), "float")
                if kind == "bool":
                    return (raw != 0, raw == INT_NULL, "bool")
                return (raw, raw == INT_NULL, kind)
            return g
        if isinstance(x, E.Unary):
            return _np_unary(x.op, build(x.operand))
        if isinstance(x, E.Binary):
            if x.op in ("IN", "NOT IN"):
                return _np_in_list(build(x.lhs),
                                   [it.value for it in x.rhs.items],
                                   pool, negate=x.op == "NOT IN")
            return _np_binary(x.op, build(x.lhs), build(x.rhs))
        raise CannotCompile(f"expr kind {x.kind}")

    term = build(e)

    def mask_fn(dense):
        val, isnull, kind = term({"_dense": dense})
        if kind != "bool":
            return np.zeros(np.shape(dense), bool)
        val = np.broadcast_to(val, np.shape(dense))
        isnull = np.broadcast_to(isnull, np.shape(dense))
        return np.logical_and(val, np.logical_not(isnull))

    return mask_fn


def merged_attr_columns(snap, prop: str):
    """(present, raw, kind) per tag whose schema carries `prop`, in the
    snapshot's sorted-tag order — the columnar mirror of
    Vertex.properties()'s dict merge (later tag wins).  Raises when the
    participating columns disagree on the value kind (a per-row merge
    of mixed encodings has no single columnar type)."""
    parts = []
    for tt in snap.tags.values():          # insertion = sorted tag order
        if prop in tt.props:
            parts.append((tt.present, tt.props[prop],
                          _kind_of(tt.prop_types[prop]),
                          tt.prop_types[prop]))
    kinds = {k for _, _, k, _ in parts}
    if len(kinds) > 1:
        raise CannotCompile(f"attr {prop} mixes value kinds across tags")
    return parts


def merged_attr_raw(snap, parts, dense: "np.ndarray"):
    """Merged raw column for `parts` at `dense` (sentinel nulls)."""
    P = snap.num_parts
    kind = parts[0][2]
    if kind == "float":
        val = np.full(np.shape(dense), np.nan)
    else:
        val = np.full(np.shape(dense), INT_NULL, np.int64)
    p_, li = dense % P, dense // P
    for pres, col, _, _ in parts:
        pm = pres[p_, li]
        val = np.where(pm, col[p_, li], val)
    return val


def _attr_term(snap, P, prop: str):
    parts = merged_attr_columns(snap, prop)
    if not parts:
        return lambda c: (np.zeros(np.shape(c["_dense"]), np.int64),
                          np.ones(np.shape(c["_dense"]), bool), "int")
    kind = parts[0][2]

    def g(c):
        raw = merged_attr_raw(snap, parts, c["_dense"])
        if kind == "float":
            return (raw, np.isnan(raw), "float")
        if kind == "bool":
            return (raw != 0, raw == INT_NULL, "bool")
        return (raw, raw == INT_NULL, kind)
    return g


# ---------------------------------------------------------------------------
# Columnar YIELD compiler — the fused-Project output path
# ---------------------------------------------------------------------------
#
# The fusion rule absorbs a GO plan's final Project(go_row) into
# TpuTraverse when every yield column is computable straight from the
# materialized edge columns (sv, dv, rr, props) with NO per-row Python
# evaluation.  Semantics mirror RowContext/get_edge_prop and the
# src/dst/rank/type/typeid builtins exactly (core/functions.py) —
# including the etype-sign swap for reverse-direction blocks.

_YIELD_FNS = frozenset({"src", "dst", "rank", "type", "typeid"})


def yieldable(e: "E.Expr") -> bool:
    """Can this YIELD column be evaluated columnar-side?"""
    if e.kind == "literal":
        return True
    if e.kind == "edge_prop":
        return True
    if e.kind == "function" and e.name in _YIELD_FNS and len(e.args) == 1 \
            and e.args[0].kind == "edge":
        return True
    return False


def eval_yield_column_np(e: "E.Expr", b: Dict[str, Any]) -> "np.ndarray":
    """eval_yield_column, columnar: returns numpy arrays (object dtype
    for vids/strings, native dtype for numeric prop columns) with no
    per-element tolist — the ColumnarDataSet fast path.  `b["props"]`
    must hold numpy arrays (decode_prop_column_np)."""
    import numpy as np

    from ..core.value import NULL_UNKNOWN_PROP
    n = b["n"]
    fwd = b["etype"] >= 0

    def _const(v, dtype=object):
        a = np.empty(n, dtype=dtype)
        a.fill(v)
        return a

    if e.kind == "literal":
        return _const(e.value)
    if e.kind == "function":
        name = e.name
        if name == "src":
            return b["sv"] if fwd else b["dv"]
        if name == "dst":
            return b["dv"] if fwd else b["sv"]
        if name == "rank":
            return np.asarray(b["rr"], dtype=np.int64)
        if name == "type":
            return _const(b["et"])
        if name == "typeid":
            return _const(int(b["etype"]), dtype=np.int64)
    if e.kind == "edge_prop":
        pname = e.name
        if pname == "_src":
            return b["sv"] if fwd else b["dv"]
        if pname == "_dst":
            return b["dv"] if fwd else b["sv"]
        if pname == "_rank":
            return np.asarray(b["rr"], dtype=np.int64)
        if pname == "_type":
            return _const(b["et"])
        col = b["props"].get(pname)
        if col is None:
            return _const(NULL_UNKNOWN_PROP)
        return col
    raise CannotCompile(f"yield not columnar: {e.kind}")


def eval_yield_column(e: "E.Expr", b: Dict[str, Any]) -> List[Any]:
    """Evaluate one absorbed YIELD column over a materialized block.

    b: {"et", "etype" (signed), "n", "sv", "dv", "rr", "props"} from
    TpuRuntime._block_columns.  For reverse ("in") blocks etype < 0 and
    sv is the frontier vertex — the PHYSICAL edge is dv→sv, matching
    Edge(sv, dv, etype=-id) built by the row materializer.
    """
    from ..core.value import NULL_UNKNOWN_PROP
    n = b["n"]
    fwd = b["etype"] >= 0
    if e.kind == "literal":
        return [e.value] * n
    if e.kind == "function":
        name = e.name
        if name == "src":       # physical source
            return (b["sv"] if fwd else b["dv"]).tolist()
        if name == "dst":
            return (b["dv"] if fwd else b["sv"]).tolist()
        if name == "rank":
            return b["rr"].tolist()
        if name == "type":
            return [b["et"]] * n
        if name == "typeid":
            return [b["etype"]] * n
    if e.kind == "edge_prop":
        pname = e.name
        if pname == "_src":
            return (b["sv"] if fwd else b["dv"]).tolist()
        if pname == "_dst":
            return (b["dv"] if fwd else b["sv"]).tolist()
        if pname == "_rank":
            return b["rr"].tolist()
        if pname == "_type":
            return [b["et"]] * n
        col = b["props"].get(pname)
        if col is None:
            return [NULL_UNKNOWN_PROP] * n
        return col
    raise CannotCompile(f"yield not columnar: {e.kind}")
