"""TpuTraverse: the fused plan node, its executor, and the fusion rule.

The optimizer rule is the north-star plugin (SURVEY §2 row 22): when a
GO plan's frontier chain —

    ExpandAll ← [Dedup ← Project(_dst→_vid) ← ExpandAll]×(n-1) ← Start(vids)

— has no carried input columns, no per-src limits, and a final-hop edge
filter that the predicate compiler can vectorize (or none), the whole
chain collapses into ONE TpuTraverse node.  Its executor runs the entire
multi-hop expansion on the device mesh (frontier never leaves HBM
between hops; see hop.py) and materializes only the final edge set.

The reference's equivalent seam is a new OptRule producing a fused plan
node in src/graph/optimizer + an Executor in src/graph/executor
[UNVERIFIED — empty mount, SURVEY §0].
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.value import DataSet, Edge, is_null
from ..exec.executors import executor
from ..query import optimizer as opt
from ..query.plan import PlanNode, walk_plan
from .device import TpuUnavailable
from .exprjit import CannotCompile, compilable, yieldable

try:
    import jax
    _JAX_RT_ERRORS = (jax.errors.JaxRuntimeError,)
except (ImportError, AttributeError):
    _JAX_RT_ERRORS = ()

# ---------------------------------------------------------------------------
# Fusion rule
# ---------------------------------------------------------------------------


def _match_frontier_chain(final: PlanNode, uses: Dict[int, int]
                          ) -> Optional[Tuple[List[Any], int]]:
    """If `final` (an ExpandAll) terminates a pure literal-vid frontier
    chain, return (vids, steps); else None.  Every mid-chain node must be
    single-use (m<n GO plans branch off mid chain — those stay host)."""
    a = final.args
    steps = 1
    cur = final
    while True:
        ca = cur.args
        if (ca.get("carry") or ca.get("limit") is not None
                or ca.get("sample") is not None):
            return None
        if ca.get("space") != a.get("space"):
            return None
        if ca.get("edge_types") != a.get("edge_types"):
            return None
        if ca.get("direction") != a.get("direction"):
            return None
        if cur is not final and ca.get("edge_filter") is not None:
            return None
        if ca.get("src_col") is None:
            # chain head: literal vids
            vids = ca.get("vids") or []
            dep = cur.deps[0] if cur.deps else None
            if dep is not None and dep.kind != "Start":
                return None
            return (vids, steps)
        # walk down: ExpandAll ← Dedup ← Project ← ExpandAll
        if ca.get("src_col") != "_vid" or len(cur.deps) != 1:
            return None
        ddp = cur.deps[0]
        if ddp.kind != "Dedup" or uses.get(ddp.id, 2) != 1 or len(ddp.deps) != 1:
            return None
        prj = ddp.deps[0]
        if (prj.kind != "Project" or uses.get(prj.id, 2) != 1
                or prj.col_names != ["_vid"] or len(prj.deps) != 1):
            return None
        nxt = prj.deps[0]
        if nxt.kind != "ExpandAll" or uses.get(nxt.id, 2) != 1:
            return None
        steps += 1
        cur = nxt


def make_tpu_rule(uses: Dict[int, int], root=None):
    """Rule closure for one optimize() pass; `uses` maps node id → number
    of parents in the plan DAG (`root` is unused here — the pipeline
    fusion needs it for by-name Argument references)."""

    def rule(node: PlanNode) -> Optional[PlanNode]:
        # Preferred match: Project(go_row) over the chain — the YIELD
        # columns are absorbed too, so materialization emits the FINAL
        # output rows from numpy columns (no per-edge Edge objects, no
        # per-row expression eval: the E2E fast path).
        yields = None
        expand = node
        if node.kind == "Project" and node.args.get("go_row") \
                and len(node.deps) == 1 and node.dep().kind == "ExpandAll" \
                and uses.get(node.dep().id, 2) == 1:
            cols = node.args.get("columns") or []
            if cols and all(yieldable(e) for e, _ in cols):
                yields = cols
                expand = node.dep()
        if expand.kind != "ExpandAll":
            return None
        a = expand.args
        ef = a.get("edge_filter")
        etypes = a.get("edge_types") or []
        if ef is not None and not compilable(ef, etypes):
            return None
        m = _match_frontier_chain(expand, uses)
        if m is None:
            return None
        vids, steps = m
        if steps == 1:
            # duplicate literal FROM vids produce duplicate rows on host;
            # the device frontier dedups — refuse that edge case
            from ..core.expr import Expr
            from ..core.expr import DictContext
            vals = [v.eval(DictContext()) if isinstance(v, Expr) else v
                    for v in vids]
            keys = [repr(v) for v in vals]
            if len(set(keys)) != len(keys):
                return None
        return PlanNode(
            "TpuTraverse", deps=[],
            args={"space": a["space"], "edge_types": list(etypes),
                  "direction": a["direction"], "vids": list(vids),
                  "steps": steps, "edge_filter": ef, "yields": yields},
            col_names=(list(node.col_names) if yields is not None
                       else ["_src", "_edge", "_dst"]))

    return rule


opt.TPU_RULES.append(make_tpu_rule)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


@executor("TpuTraverse")
def _tpu_traverse(node, qctx, ectx, space):
    from ..core.expr import DictContext, Expr
    a = node.args
    sp = a["space"]
    vids = [v.eval(DictContext()) if isinstance(v, Expr) else v
            for v in a.get("vids") or []]
    vids = [v for v in vids if not is_null(v)]
    rt = getattr(qctx, "tpu_runtime", None)
    yields = a.get("yields")
    if rt is not None:
        try:
            rows, stats = rt.traverse(
                qctx.store, sp, vids, a["edge_types"], a["direction"],
                a["steps"], edge_filter=a.get("edge_filter"),
                yields=yields)
            qctx.last_tpu_stats = stats
            if yields is not None:
                if isinstance(rows, DataSet):
                    # ColumnarDataSet: rows stay numpy columns until a
                    # consumer crosses the row boundary (lazy handle)
                    rows.column_names = list(node.col_names)
                    return rows
                return DataSet(list(node.col_names), rows)
            return DataSet(["_src", "_edge", "_dst"],
                           [[s, e, d] for (s, e, d) in rows])
        except (CannotCompile, TpuUnavailable) + _JAX_RT_ERRORS as ex:
            # JaxRuntimeError covers device-capacity failures (e.g. HBM
            # RESOURCE_EXHAUSTED on pin); escalation non-convergence
            # raises TpuUnavailable.  The host path below has identical
            # semantics; the fallback cause is recorded for PROFILE/debug
            # rather than silently swallowed.
            qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
    return _host_traverse(node, qctx, sp, vids)


def _host_traverse(node, qctx, space, vids):
    """CPU fallback with identical semantics (frontier chain with per-hop
    dedup; filter on the final hop)."""
    from ..core.expr import to_bool3
    from ..exec.context import RowContext
    from ..exec.executors import _make_edge

    a = node.args
    store = qctx.store
    etypes = a["edge_types"]
    etype_ids = {e: store.catalog.get_edge(space, e).edge_type
                 for e in etypes}
    direction = a["direction"]
    ef = a.get("edge_filter")
    steps = a["steps"]

    frontier = []
    seen = set()
    for v in vids:
        if repr(v) not in seen:
            seen.add(repr(v))
            frontier.append(v)
    for _ in range(steps - 1):
        nxt, seen2 = [], set()
        for (s, et, rank, other, props, sd) in store.get_neighbors(
                space, frontier, etypes, direction):
            k = repr(other)
            if k not in seen2:
                seen2.add(k)
                nxt.append(other)
        frontier = nxt
    yields = a.get("yields")
    rows = []
    for (s, et, rank, other, props, sd) in store.get_neighbors(
            space, frontier, etypes, direction):
        e = _make_edge(s, other, et, rank, props, sd, etype_ids[et])
        rc = None
        if ef is not None or yields is not None:
            rc = RowContext(qctx, space,
                            {"_src": s, "_edge": e, "_dst": other})
        if ef is not None and to_bool3(ef.eval(rc)) is not True:
            continue
        if yields is not None:
            rows.append([ye.eval(rc) for ye, _ in yields])
        else:
            rows.append([s, e, other])
    if yields is not None:
        return DataSet(list(node.col_names), rows)
    return DataSet(["_src", "_edge", "_dst"], rows)
