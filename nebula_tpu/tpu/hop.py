"""The sharded multi-hop traversal kernel.

One `shard_map` program runs the WHOLE N-step GO expansion on device:
per hop, each chip expands its shard of the frontier through its local
CSR block(s) (a vectorized segment gather — the MXU/VPU replacement for
the reference's per-vid RocksDB prefix loops in GetNeighborsProcessor),
applies the compiled predicate mask, dedups via sort-unique, hash-routes
destinations to their owning chips, and re-shards the frontier with ONE
`lax.all_to_all` over ICI — replacing the reference's per-hop
storage.thrift fan-out (StorageClient::getNeighbors; reference:
src/clients/storage, src/storage/query [UNVERIFIED — empty mount,
SURVEY §0]).

Static-shape policy (SURVEY §7 hard-part #1): frontier capacity F and
per-block edge budget EB are power-of-two buckets chosen by the runtime;
every kernel output carries per-part overflow flags, and the runtime
re-runs with doubled buckets on overflow (inputs are never consumed, so
the retry is exact).

Frontier representation between hops: (P, F) int32 dense vertex ids,
-1 padded, each row owned by (and resident on) its chip; dense id
encodes ownership as dense % P — the vid-hash partition map.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

MAXI = np.iinfo(np.int32).max


def _sorted_unique(vals):
    """vals: (N,) int32 with -1 invalid → (u, count): u has the unique
    valid values somewhere (others MAXI), count = #unique."""
    key = jnp.where(vals >= 0, vals, MAXI).astype(jnp.int32)
    s = jnp.sort(key)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    first = first & (s != MAXI)
    u = jnp.where(first, s, MAXI)
    return u, jnp.sum(first, dtype=jnp.int32)


def _route(u, P: int, cap: int):
    """Bucket unique candidates by owner part (owner = v % P).

    u: (N,) int32 values or MAXI.  Returns:
      out   (P, cap) int32  — row d = candidates destined for part d
      sendc (P,)     int32  — valid count per destination
      ovf   ()       bool   — some destination bucket overflowed
    """
    ok = u != MAXI
    owner = jnp.where(ok, u % P, P).astype(jnp.int32)
    perm = jnp.argsort(owner, stable=True)
    so = owner[perm]
    sv = u[perm]
    counts = jnp.zeros((P + 1,), jnp.int32).at[so].add(1)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts[:-1])])
    pos = jnp.arange(so.shape[0], dtype=jnp.int32) - starts[so]
    out = jnp.full((P, cap), -1, jnp.int32)
    out = out.at[so, pos].set(sv, mode="drop")
    sendc = jnp.minimum(counts[:P], cap)
    ovf = jnp.any(counts[:P] > cap)
    return out, sendc, ovf


def _merge_frontier(recv, F: int):
    """recv: (P, cap) candidates received from every chip → next frontier
    (F,) sorted ascending, -1 padded, + count + overflow."""
    u, cnt = _sorted_unique(recv.reshape(-1))
    nf = jnp.sort(u)[:F]
    nf = jnp.where(nf != MAXI, nf, -1)
    return nf, jnp.minimum(cnt, F), cnt > F


def _compact_cap(src, dst, rk, eidx, keep, EB: int):
    """Stable-partition the kept edge slots to the FRONT of each capture
    row (cumsum scatter, O(EB)) and return the kept count.

    Why: capture arrays are EB-padded and EB is sized for the worst hop
    (millions of slots); fetching them wholesale ships mostly padding —
    ~2 GB/query over a tunneled chip.  With kept entries compacted to a
    prefix the host fetches only [:kmax] slices (runtime._escalate).
    The scatter is order-preserving, so the (part, src)-contiguous
    ascending-eidx invariant the host materializers rely on survives."""
    pos = jnp.where(keep, jnp.cumsum(keep, dtype=jnp.int32) - 1,
                    EB).astype(jnp.int32)

    def put(a, fill):
        return jnp.full((EB,), fill, a.dtype).at[pos].set(a, mode="drop")

    return (put(src, -1), put(jnp.where(keep, dst, -1), -1), put(rk, 0),
            put(eidx, 0), jnp.sum(keep, dtype=jnp.int32))


def _expand_block(indptr, nbr, rank, fr, F: int, EB: int, P: int):
    """Vectorized CSR expansion of one block for one shard's frontier.

    Returns per-edge-slot arrays of length EB:
      src (frontier dense id), dst, rk, eidx (index into the block's edge
      arrays — the host uses it to decode properties), ve (slot valid),
    plus (total, ovf): true expansion size and overflow flag.
    """
    valid = fr >= 0
    lf = jnp.where(valid, fr // P, 0)
    deg = jnp.where(valid, indptr[lf + 1] - indptr[lf], 0)
    ends = jnp.cumsum(deg)
    total = ends[-1]
    j = jnp.arange(EB, dtype=jnp.int32)
    row = jnp.searchsorted(ends, j, side="right").astype(jnp.int32)
    row = jnp.minimum(row, F - 1)
    starts = ends - deg
    eidx = indptr[lf[row]] + (j - starts[row])
    ve = j < jnp.minimum(total, EB)
    eidx = jnp.where(ve, eidx, 0).astype(jnp.int32)
    dst = jnp.where(ve, nbr[eidx], -1)
    src = jnp.where(ve, fr[row], -1)
    rk = jnp.where(ve, rank[eidx], 0)
    return src, dst, rk, eidx, ve, total, total > EB


def build_traverse_fn(mesh, P: int, F: int, EB: int, steps: int,
                      n_blocks: int,
                      pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                      pred_cols: Sequence[str] = (),
                      capture: bool = True,
                      capture_hops: bool = False):
    """Compile the N-step traversal program for one bucket configuration.

    blocks_data (runtime arg): tuple of n_blocks dicts with keys
      indptr (P, V+1), nbr (P, E), rank (P, E), props {name: (P, E)}
    where props holds ONLY the columns the predicate needs (property
    decode for result rows happens on host via captured eidx).

    Returns jitted fn(blocks_data, frontier) -> dict with:
      frontier (P, F), fcount (P,): next frontier after the LAST hop
        (mid-hop frontiers never leave the device)
      hop_edges (P, steps): pre-filter expansion size per hop per part
      ovf_expand / ovf_route / ovf_frontier (P,) bool
      cap (if capture): dict of (P, n_blocks, EB) arrays
        src, dst, rank, eidx, keep — the final hop's edge set

    capture_hops=True is the MATCH mode (SURVEY §2 row 23 Traverse):
    the predicate is applied at EVERY hop (a MATCH edge pattern's filter
    is uniform over a variable-length expansion, unlike GO's final-step
    WHERE) and the edge frame of every hop is captured — cap arrays gain
    a leading hop axis, (P, steps, n_blocks, EB).  The host assembles
    trail-semantics paths from the layered frames (runtime.py).
    """

    def kernel(blocks_data, frontier):
        fr = frontier[0]                       # (F,)
        hop_edges: List[Any] = []
        ovf_e = jnp.zeros((), bool)
        ovf_r = jnp.zeros((), bool)
        ovf_f = jnp.zeros((), bool)
        cap_out = None
        hop_caps: List[Dict[str, Any]] = []
        fcount = jnp.zeros((), jnp.int32)

        for hop in range(steps):
            last = hop == steps - 1
            cands = []
            edges_this_hop = jnp.zeros((), jnp.int32)
            caps = {"src": [], "dst": [], "rank": [], "eidx": [],
                    "kcount": []}
            for bi in range(n_blocks):
                b = blocks_data[bi]
                src, dst, rk, eidx, ve, total, ovf = _expand_block(
                    b["indptr"][0], b["nbr"][0], b["rank"][0], fr, F, EB, P)
                ovf_e = ovf_e | ovf
                edges_this_hop = edges_this_hop + total
                if pred is not None and (last or capture_hops):
                    cols = {"_rank": rk}
                    for name in pred_cols:
                        if name != "_rank":
                            cols[name] = b["props"][name][0][eidx]
                    keep = pred(cols) & ve
                else:
                    keep = ve
                if capture and (last or capture_hops):
                    cs, cd, cr, ce, kc = _compact_cap(src, dst, rk, eidx,
                                                      keep, EB)
                    caps["src"].append(cs)
                    caps["dst"].append(cd)
                    caps["rank"].append(cr)
                    caps["eidx"].append(ce)
                    caps["kcount"].append(kc)
                if not last:
                    cands.append(jnp.where(keep, dst, -1))
            hop_edges.append(edges_this_hop)
            if capture and (last or capture_hops):
                hop_caps.append({k: jnp.stack(v) for k, v in caps.items()})

            if last:
                if capture:
                    arr_keys = ("src", "dst", "rank", "eidx")
                    if capture_hops:
                        cap_out = {k: jnp.stack([hc[k] for hc in hop_caps]
                                                )[None]
                                   for k in arr_keys}
                        kcount_out = jnp.stack(
                            [hc["kcount"] for hc in hop_caps])[None]
                    else:
                        cap_out = {k: hop_caps[-1][k][None]
                                   for k in arr_keys}
                        kcount_out = hop_caps[-1]["kcount"][None]
                # the post-final frontier is not needed for GO; report empty
                fr = jnp.full((F,), -1, jnp.int32)
                fcount = jnp.zeros((), jnp.int32)
            else:
                cand = jnp.concatenate(cands) if len(cands) > 1 else cands[0]
                u, _ = _sorted_unique(cand)
                out, sendc, ovf = _route(u, P, F)
                ovf_r = ovf_r | ovf
                recv = jax.lax.all_to_all(out, "part", 0, 0, tiled=False)
                recv = recv.reshape(P, F)
                fr, fcount, ovf = _merge_frontier(recv, F)
                ovf_f = ovf_f | ovf

        res = {
            "frontier": fr[None],
            "fcount": fcount[None],
            "hop_edges": jnp.stack(hop_edges)[None],
            "ovf_expand": ovf_e[None],
            "ovf_route": ovf_r[None],
            "ovf_frontier": ovf_f[None],
        }
        if capture:
            res["cap"] = cap_out
            res["kcount"] = kcount_out   # small: fetched with the meta
        return res

    spec = PartitionSpec("part")
    smapped = jax.shard_map(kernel, mesh=mesh,
                            in_specs=(spec, spec), out_specs=spec)
    return jax.jit(smapped)


def build_traverse_fn_local(P: int, F: int, EB: int, steps: int,
                            n_blocks: int,
                            pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                            pred_cols: Sequence[str] = (),
                            capture: bool = True,
                            capture_hops: bool = False):
    """Single-chip variant: all P partitions resident on one device, the
    per-part kernel vmapped over the part axis, and the frontier exchange
    a plain transpose (the degenerate all_to_all).  This is the program
    that runs on one real chip (the bench config) — identical semantics
    to the sharded build, no ICI.  capture_hops follows the sharded
    contract (MATCH mode: per-hop pred + per-hop frames, cap arrays
    (P, steps, n_blocks, EB)).
    """

    def one_part_expand(block, fr, want_pred):
        src, dst, rk, eidx, ve, total, ovf = _expand_block(
            block["indptr"], block["nbr"], block["rank"], fr, F, EB, P)
        if want_pred:
            cols = {"_rank": rk}
            for name in pred_cols:
                if name != "_rank":
                    cols[name] = block["props"][name][eidx]
            keep = pred(cols) & ve
        else:
            keep = ve
        return src, dst, rk, eidx, ve, keep, total, ovf

    def fn(blocks_data, frontier):
        fr = frontier                      # (P, F)
        hop_edges = []
        ovf_e = jnp.zeros((P,), bool)
        ovf_r = jnp.zeros((P,), bool)
        ovf_f = jnp.zeros((P,), bool)
        cap_out = None
        hop_caps = []
        fcount = jnp.zeros((P,), jnp.int32)

        for hop in range(steps):
            last = hop == steps - 1
            cands = []
            edges = jnp.zeros((P,), jnp.int32)
            caps = {"src": [], "dst": [], "rank": [], "eidx": [],
                    "kcount": []}
            for bi in range(n_blocks):
                b = blocks_data[bi]
                want_pred = pred is not None and (last or capture_hops)
                src, dst, rk, eidx, ve, keep, total, ovf = jax.vmap(
                    lambda ip, nb, rkk, prp, f: one_part_expand(
                        {"indptr": ip, "nbr": nb, "rank": rkk, "props": prp},
                        f, want_pred)
                )(b["indptr"], b["nbr"], b["rank"], b["props"], fr)
                ovf_e = ovf_e | ovf
                edges = edges + total
                if capture and (last or capture_hops):
                    cs, cd, cr, ce, kc = jax.vmap(
                        lambda s, d, r, e, k: _compact_cap(s, d, r, e, k,
                                                           EB)
                    )(src, dst, rk, eidx, keep)
                    caps["src"].append(cs)
                    caps["dst"].append(cd)
                    caps["rank"].append(cr)
                    caps["eidx"].append(ce)
                    caps["kcount"].append(kc)
                if not last:
                    cands.append(jnp.where(keep, dst, -1))
            hop_edges.append(edges)
            if capture and (last or capture_hops):
                # arrays (P, nb, EB); kcount (P, nb)
                hop_caps.append({k: jnp.stack(v, axis=1)
                                 for k, v in caps.items()})

            if last:
                if capture:
                    arr_keys = ("src", "dst", "rank", "eidx")
                    if capture_hops:
                        # (P, steps, nb, EB); kcount (P, steps, nb)
                        cap_out = {k: jnp.stack([hc[k] for hc in hop_caps],
                                                axis=1)
                                   for k in arr_keys}
                        kcount_out = jnp.stack(
                            [hc["kcount"] for hc in hop_caps], axis=1)
                    else:
                        cap_out = {k: hop_caps[-1][k] for k in arr_keys}
                        kcount_out = hop_caps[-1]["kcount"]
                fr = jnp.full((P, F), -1, jnp.int32)
                fcount = jnp.zeros((P,), jnp.int32)
            else:
                cand = (jnp.concatenate(cands, axis=1)
                        if len(cands) > 1 else cands[0])    # (P, nb*EB)

                def route_one(c):
                    u, _ = _sorted_unique(c)
                    return _route(u, P, F)
                outs, sendc, ovr = jax.vmap(route_one)(cand)
                ovf_r = ovf_r | ovr
                recv = outs.transpose(1, 0, 2)              # dest-major
                fr, fcount, ovr2 = jax.vmap(
                    lambda r: _merge_frontier(r, F))(recv)
                ovf_f = ovf_f | ovr2

        res = {
            "frontier": fr,
            "fcount": fcount,
            "hop_edges": jnp.stack(hop_edges, axis=1),      # (P, steps)
            "ovf_expand": ovf_e,
            "ovf_route": ovf_r,
            "ovf_frontier": ovf_f,
        }
        if capture:
            res["cap"] = cap_out
            res["kcount"] = kcount_out   # small: fetched with the meta
        return res

    return jax.jit(fn)
