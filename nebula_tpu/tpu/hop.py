"""The sharded multi-hop traversal kernel — bitmap-frontier design.

One `shard_map` program runs the WHOLE N-step GO expansion on device:
per hop, each chip expands its shard of the frontier through its local
CSR block(s) (a vectorized segment gather — the MXU/VPU replacement for
the reference's per-vid RocksDB prefix loops in GetNeighborsProcessor),
applies the compiled predicate mask, and marks destination vertices in a
per-owner **bitmap** that is exchanged with ONE bool `lax.all_to_all`
over ICI — replacing the reference's per-hop storage.thrift fan-out
(StorageClient::getNeighbors; reference: src/clients/storage,
src/storage/query [UNVERIFIED — empty mount, SURVEY §0]).

Why a bitmap (round-4 redesign, VERDICT r3 item 3): the previous design
kept the frontier as a padded (P, F) sorted id list, which cost three
O(EB log EB) sorts per hop (sort-unique dedup, stable argsort routing,
merge sort) — sort-heavy work on sort-weak hardware for an expansion
whose useful work is an int32 gather.  The frontier is now a
(P, vmax) bool membership bitmap sharded by vid ownership
(dense % P — the vid-hash partition map), which makes all three sorts
disappear structurally:

  * dedup      = the scatter-max mark itself (duplicate dsts set the
                 same bit);
  * routing    = the bitmap's layout (row d of the mark matrix IS the
                 bucket for part d — no argsort, no bucket overflow);
  * merge      = a bool OR-reduce over the received rows;
  * the F bucket, its escalation rung, and the ovf_route/ovf_frontier
    flags cease to exist — the only dynamic budget left is EB.

Per hop the work is O(EB) gathers/scatters + an O(vmax) cumsum, versus
O(EB log EB) before; the exchange payload is P*vmax bools versus
P*F int32 words (at north-star shape: 1 MB versus 64 MB).

Static-shape policy (SURVEY §7 hard-part #1): the per-block edge budget
EB is a power-of-two bucket chosen by the runtime; every kernel output
carries overflow flags, and the runtime re-runs with doubled buckets on
overflow (inputs are never consumed, so the retry is exact).

Frontier representation between hops: (P, vmax) bool, row p = the
membership bitmap of part p's local ids (dense id = local * P + p).
Expansion enumerates set bits in ascending local-id order, so captured
edge slots stay (part, src)-contiguous ascending-eidx — the invariant
the host materializers rely on.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .device import shard_map as _shard_map

MAXI = np.iinfo(np.int32).max


def _expand_block(indptr, nbr, rank, fbm, EB: int, P: int, pid,
                  vmax_local: int = 0, hub_dense=None):
    """Vectorized CSR expansion of one block from one part's frontier
    bitmap.

    indptr: (vmax+1,) local CSR row pointers; nbr/rank: (E,) edge
    arrays; fbm: (vmax,) bool frontier membership; pid: this part's id
    (dense id = local * P + pid).

    With a degree-split snapshot (graphstore.csr.degree_split) the
    block carries H extra HUB rows after the vmax_local local rows, and
    fbm arrives EXTENDED to vmax_local+H (hub-active bits appended by
    the caller); a hub row's source dense id comes from `hub_dense`
    instead of the local-row arithmetic.

    Slot→source-row assignment is a cumsum-scatter, not a binary
    search: bump +1 at each frontier vertex's first slot, prefix-sum
    over the EB slots, then map the compact row number back to a local
    id through a scattered lookup table — O(vmax + EB) total, versus
    O(EB log vmax) for searchsorted (the log factor dominated the old
    kernel's per-slot cost on both the VPU and the CPU-emulated mesh).

    Returns per-edge-slot arrays of length EB:
      src (frontier dense id), dst, rk, eidx (index into the block's
      edge arrays — the host uses it to decode properties), ve (slot
      valid), plus (total, ovf): true expansion size and overflow flag.
    """
    vmax = fbm.shape[0]
    deg = jnp.where(fbm, indptr[1:] - indptr[:-1], 0).astype(jnp.int32)
    ends = jnp.cumsum(deg)
    total = ends[-1]
    starts = ends - deg                       # (vmax,)
    has = deg > 0
    # compact index of each expanding vertex, and its inverse table
    cidx = jnp.cumsum(has.astype(jnp.int32)) - 1
    vid_of = jnp.zeros((vmax,), jnp.int32).at[
        jnp.where(has, cidx, vmax)].set(
        jnp.arange(vmax, dtype=jnp.int32), mode="drop")
    # +1 at each expanding vertex's first slot; prefix-sum = compact row
    bump = jnp.zeros((EB,), jnp.int32).at[
        jnp.where(has, starts, EB)].add(1, mode="drop")
    crow = jnp.cumsum(bump) - 1               # (EB,)
    row = vid_of[jnp.maximum(crow, 0)]
    j = jnp.arange(EB, dtype=jnp.int32)
    eidx = indptr[row] + (j - starts[row])
    ve = j < jnp.minimum(total, EB)
    eidx = jnp.where(ve, eidx, 0).astype(jnp.int32)
    dst = jnp.where(ve, nbr[eidx], -1)
    if hub_dense is None:
        src_id = row * P + pid
    else:
        src_id = jnp.where(
            row < vmax_local, row * P + pid,
            hub_dense[jnp.clip(row - vmax_local, 0,
                               hub_dense.shape[0] - 1)])
    src = jnp.where(ve, src_id, -1)
    rk = jnp.where(ve, rank[eidx], 0)
    return src, dst, rk, eidx, ve, total, total > EB


def _merge_delta(dl, fbm, src, dst, rk, eidx, ve, total, P: int, pid,
                 emax: int):
    """Merge the device-resident delta plane into one block's expansion
    (ISSUE 19).

    dl: dict with the block's delta leaves for THIS part —
      d_src (Dcap,) int32 LOCAL source index, d_dst (Dcap,) dense dst,
      d_rank (Dcap,), d_valid (Dcap,) bool slot-live,
      d_tomb (Tcap,) SORTED int32 base-edge indices masked out
      (MAXI-padded).
    fbm: (vmax,) bool — this part's frontier bitmap (delta snapshots are
    never degree-split, so no hub extension applies).

    Two halves, in order:
      1. tombstones: a searchsorted membership test drops base slots
         whose eidx was deleted/overwritten since the pin;
      2. inserts: delta rows whose source vertex is on the frontier are
         APPENDED to the capture arrays — delta row j takes the virtual
         edge index emax + j, so downstream prop gathers read from
         columns extended with the delta prop columns and the host can
         split captured rows back into base (< emax) and delta halves.

    The appended slots keep the ascending-eidx tail position, so the
    (part, src)-contiguous prefix invariant of the BASE slots survives;
    the host re-sorts the merged union per part into canonical CSR
    order (runtime._block_columns) before materializing rows.
    """
    tomb = dl["d_tomb"]
    if tomb.shape[0]:
        pos = jnp.clip(jnp.searchsorted(tomb, eidx), 0, tomb.shape[0] - 1)
        ve = ve & ~(tomb[pos] == eidx)
    dsrc = dl["d_src"]
    Dcap = dsrc.shape[0]
    if Dcap:
        active = dl["d_valid"] & fbm[jnp.clip(dsrc, 0, fbm.shape[0] - 1)]
        src = jnp.concatenate([src, jnp.where(active, dsrc * P + pid, -1)])
        dst = jnp.concatenate([dst, jnp.where(active, dl["d_dst"], -1)])
        rk = jnp.concatenate([rk, jnp.where(active, dl["d_rank"], 0)])
        eidx = jnp.concatenate(
            [eidx, emax + jnp.arange(Dcap, dtype=jnp.int32)])
        ve = jnp.concatenate([ve, active])
        total = total + jnp.sum(active, dtype=jnp.int32)
    return src, dst, rk, eidx, ve, total


def _delta_cap(b) -> int:
    """Extra capture width a block's delta plane adds (0 = no delta)."""
    return int(b["d_src"].shape[-1]) if "d_src" in b else 0


def _mark(dst, keep, P: int, vmax: int, acc=None):
    """Scatter keep-passing dense dst ids into a (P, vmax) ownership
    bitmap: row d = the candidate set destined for part d.  This is the
    sort-free dedup + route: duplicates set the same bit, and the row
    index IS the routing bucket (no argsort, no bucket overflow)."""
    owner = jnp.where(keep, dst % P, 0).astype(jnp.int32)
    loc = jnp.where(keep, dst // P, 0).astype(jnp.int32)
    m = jnp.zeros((P, vmax), bool) if acc is None else acc
    return m.at[owner, loc].max(keep)


def _pack_bits(m):
    """(P, vmax) bool → (P, W) uint32 words (W = ceil(vmax/32)): the
    mark matrix is bit-packed BEFORE the inter-chip exchange, cutting
    the all_to_all payload 8× vs bool (at SF300 scale: ~35 MB/chip/hop
    instead of ~280 MB).  Packing is a shift-weighted sum over disjoint
    bits (sum of distinct powers of two == OR — no overflow)."""
    P, vmax = m.shape
    W = -(-vmax // 32)
    pad = W * 32 - vmax
    mb = jnp.pad(m, ((0, 0), (0, pad)))
    bits = mb.reshape(P, W, 32).astype(jnp.uint32)
    weights = jnp.left_shift(jnp.uint32(1),
                             jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def _unpack_or(recv, vmax: int):
    """(P, W) received words → (vmax,) bool: OR the P rows on PACKED
    words, then unpack once."""
    ored = recv[0]
    for i in range(1, recv.shape[0]):
        ored = ored | recv[i]
    bits = (ored[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    return bits.reshape(-1)[:vmax].astype(bool)


def _exchange_marks(marks, P: int, vmax: int):
    """The per-hop frontier exchange: row d of `marks` is part d's
    candidate bitmap; ship it there (ONE all_to_all over ICI, packed)
    and OR what this part received."""
    packed = _pack_bits(marks)
    recv = jax.lax.all_to_all(packed, "part", 0, 0, tiled=False)
    return _unpack_or(recv.reshape(P, -1), vmax)


def _exchange_marks_lanes(marks, P: int, vmax: int):
    """Lane-batched frontier exchange: `marks` is (Ll, P, vmax) — one
    mark matrix per resident query lane.  Still ONE `all_to_all` per hop:
    the packed payload carries the lanes × parts grid in a single
    (Ll, P, W) tensor split/concatenated over the part axis (axis 1), so
    L compatible queries share the ICI transfer instead of paying one
    collective each.  Returns (Ll, vmax) bool — this part's next
    frontier per lane."""
    packed = jax.vmap(_pack_bits)(marks)              # (Ll, P, W)
    recv = jax.lax.all_to_all(packed, "part", 1, 1, tiled=False)
    return jax.vmap(lambda r: _unpack_or(r, vmax))(recv)


def a2a_payload_bytes(P: int, vmax: int, lanes: int = 1) -> int:
    """Total bytes moved through ONE bit-packed frontier all_to_all
    across the whole mesh (sum of every device's send payload): each of
    the P parts ships P rows of ceil(vmax/32) uint32 words per lane.
    Zero when P == 1 — local mode has no exchange."""
    if P <= 1:
        return 0
    W = -(-vmax // 32)
    return int(lanes) * P * P * W * 4


def _compact_cap(src, dst, rk, eidx, keep, EB: int):
    """Stable-partition the kept edge slots to the FRONT of each capture
    row (cumsum scatter, O(EB)) and return the kept count.

    Why: capture arrays are EB-padded and EB is sized for the worst hop
    (millions of slots); fetching them wholesale ships mostly padding —
    ~2 GB/query over a tunneled chip.  With kept entries compacted to a
    prefix the host fetches only [:kmax] slices (runtime._escalate).
    The scatter is order-preserving, so the (part, src)-contiguous
    ascending-eidx invariant the host materializers rely on survives."""
    pos = jnp.where(keep, jnp.cumsum(keep, dtype=jnp.int32) - 1,
                    EB).astype(jnp.int32)

    def put(a, fill):
        return jnp.full((EB,), fill, a.dtype).at[pos].set(a, mode="drop")

    return (put(src, -1), put(jnp.where(keep, dst, -1), -1), put(rk, 0),
            put(eidx, 0), jnp.sum(keep, dtype=jnp.int32))


def _norm_ebs(EB, steps: int, capture_hops: bool):
    """Per-hop edge budgets: an int is uniform; a sequence gives each
    hop its own bucket (a 3-hop GO's first hop expands a few hundred
    edges while the last expands millions — one uniform bucket made
    every hop pay the final hop's padding).  capture_hops mode stacks
    per-hop capture arrays along a hop axis, which requires equal EB."""
    ebs = tuple([EB] * steps) if isinstance(EB, int) else tuple(EB)
    assert len(ebs) == steps, (ebs, steps)
    if capture_hops:
        assert len(set(ebs)) == 1, "capture_hops requires uniform EB"
    return ebs


def _hub_consts(hub_dense, P: int):
    """Static per-snapshot hub tables for the degree-split expansion:
    (dense ids, owner part, owner-local index) as jnp constants, or
    (None, None, None) for an unsplit snapshot."""
    if hub_dense is None or len(hub_dense) == 0:
        return None, None, None
    hd = jnp.asarray(np.asarray(hub_dense), jnp.int32)
    return hd, hd % P, hd // P


def _extend_fbm_sharded(fbm, pid, hub_owner, hub_local):
    """Append hub-active bits to one shard's expansion bitmap: each
    hub's frontier bit lives in its OWNER's shard — OR the per-part
    contributions over the mesh so every part expands its chunk of
    each active hub."""
    mine = hub_owner == pid
    vals = jnp.where(mine, fbm[hub_local], False)
    bits = jax.lax.psum(vals.astype(jnp.int32), "part") > 0
    return jnp.concatenate([fbm, bits])


def _extend_fbm_sharded_lanes(fbm, pid, hub_owner, hub_local):
    """Lane-batched hub extension: fbm is (Ll, vmax) — gather each
    lane's owned hub bits and psum over the part axis in ONE collective
    for all resident lanes (the collective sits OUTSIDE any vmap: the
    lane axis is just a leading data axis of the psum operand)."""
    mine = hub_owner == pid                               # (H,)
    vals = jnp.where(mine[None, :], fbm[:, hub_local], False)
    bits = jax.lax.psum(vals.astype(jnp.int32), "part") > 0
    return jnp.concatenate([fbm, bits], axis=1)           # (Ll, vmax+H)


def _extend_fbm_local(fbm, hub_owner, hub_local, P: int):
    """Single-chip variant: the full (P, vmax) ownership bitmap is
    resident — gather each hub's bit straight from its owner row and
    replicate across the part axis."""
    bits = fbm[hub_owner, hub_local]                       # (H,)
    return jnp.concatenate(
        [fbm, jnp.broadcast_to(bits, (P, bits.shape[0]))], axis=1)


def build_traverse_fn(mesh, P: int, EB, steps: int,
                      n_blocks: int,
                      pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                      pred_cols: Sequence[str] = (),
                      capture: bool = True,
                      capture_hops: bool = False,
                      yield_cols: Sequence[str] = (),
                      hub_dense=None):
    """Compile the N-step traversal program for one bucket configuration.
    EB: per-block edge budget — an int (uniform) or a per-hop sequence.

    yield_cols: edge-prop names the caller's YIELD list reads — their
    values are gathered ON DEVICE from the pinned prop columns at the
    compacted final-hop slots and captured as `prop:<name>` arrays, so
    the host fetches exactly the result columns instead of eidx + a
    host-side gather (GO capture mode only; x64 is enabled, so device
    gathers are bit-exact with the host decode).

    blocks_data (runtime arg): tuple of n_blocks dicts with keys
      indptr (P, vmax+1), nbr (P, E), rank (P, E), props {name: (P, E)}
    where props holds the columns the predicate needs PLUS yield_cols
    (any other result prop decodes on host via the captured eidx).

    Returns jitted fn(blocks_data, frontier) -> dict with:
      frontier (P, vmax) bool, fcount (P,): next frontier after the LAST
        hop (mid-hop frontiers never leave the device)
      hop_edges (P, steps): pre-filter expansion size per hop per part
      ovf_expand (P,) bool: some hop's expansion exceeded EB
      cap (if capture): dict of (P, n_blocks, EB) arrays
        src, dst, rank, eidx, prop:<name> per yield_col — the final
        hop's edge set (kept entries compacted to a prefix;
        kcount (P, n_blocks) gives the counts)

    capture_hops=True is the MATCH mode (SURVEY §2 row 23 Traverse):
    the predicate is applied at EVERY hop (a MATCH edge pattern's filter
    is uniform over a variable-length expansion, unlike GO's final-step
    WHERE) and the edge frame of every hop is captured — cap arrays gain
    a leading hop axis, (P, steps, n_blocks, EB).  The host assembles
    trail-semantics paths from the layered frames (runtime.py).
    """

    ebs = _norm_ebs(EB, steps, capture_hops)
    hubs_c, hub_owner, hub_local = _hub_consts(hub_dense, P)

    def kernel(blocks_data, frontier):
        fbm = frontier[0]                      # (vmax,) bool
        vmax = fbm.shape[0]
        pid = jax.lax.axis_index("part").astype(jnp.int32)
        hop_edges: List[Any] = []
        frontier_sizes: List[Any] = []         # popcount entering each hop
        ovf_e = jnp.zeros((), bool)
        cap_out = None
        hop_caps: List[Dict[str, Any]] = []

        for hop in range(steps):
            frontier_sizes.append(jnp.sum(fbm, dtype=jnp.int32))
            last = hop == steps - 1
            EBh = ebs[hop]
            marks = None
            edges_this_hop = jnp.zeros((), jnp.int32)
            caps = {"src": [], "dst": [], "rank": [], "eidx": [],
                    "kcount": []}
            efbm = fbm if hubs_c is None else _extend_fbm_sharded(
                fbm, pid, hub_owner, hub_local)
            for bi in range(n_blocks):
                b = blocks_data[bi]
                src, dst, rk, eidx, ve, total, ovf = _expand_block(
                    b["indptr"][0], b["nbr"][0], b["rank"][0], efbm, EBh,
                    P, pid, vmax_local=vmax, hub_dense=hubs_c)
                ovf_e = ovf_e | ovf
                dcap = _delta_cap(b)
                if dcap:
                    dl = {k: b[k][0] for k in
                          ("d_src", "d_dst", "d_rank", "d_valid", "d_tomb")}
                    src, dst, rk, eidx, ve, total = _merge_delta(
                        dl, fbm, src, dst, rk, eidx, ve, total, P, pid,
                        b["nbr"].shape[-1])
                edges_this_hop = edges_this_hop + total

                def _col(name):
                    c = b["props"][name][0]
                    if dcap:
                        c = jnp.concatenate([c, b["d_props"][name][0]])
                    return c

                if pred is not None and (last or capture_hops):
                    cols = {"_rank": rk, "_src": src, "_dst": dst}
                    for name in pred_cols:
                        if not name.startswith("_"):
                            cols[name] = _col(name)[eidx]
                    keep = pred(cols) & ve
                else:
                    keep = ve
                if capture and (last or capture_hops):
                    cs, cd, cr, ce, kc = _compact_cap(src, dst, rk, eidx,
                                                      keep, EBh + dcap)
                    caps["src"].append(cs)
                    caps["dst"].append(cd)
                    caps["rank"].append(cr)
                    caps["eidx"].append(ce)
                    caps["kcount"].append(kc)
                    if last and not capture_hops:
                        for name in yield_cols:
                            caps.setdefault("prop:" + name, []).append(
                                _col(name)[ce])
                if not last:
                    marks = _mark(dst, keep, P, vmax, marks)
            hop_edges.append(edges_this_hop)
            if capture and (last or capture_hops):
                hop_caps.append({k: jnp.stack(v) for k, v in caps.items()})

            if last:
                if capture:
                    if capture_hops:
                        arr_keys = ("src", "dst", "rank", "eidx")
                        cap_out = {k: jnp.stack([hc[k] for hc in hop_caps]
                                                )[None]
                                   for k in arr_keys}
                        kcount_out = jnp.stack(
                            [hc["kcount"] for hc in hop_caps])[None]
                    else:
                        cap_out = {k: v[None]
                                   for k, v in hop_caps[-1].items()
                                   if k != "kcount"}
                        kcount_out = hop_caps[-1]["kcount"][None]
                # the post-final frontier is not needed for GO; report empty
                fbm = jnp.zeros((vmax,), bool)
            else:
                fbm = _exchange_marks(marks, P, vmax)

        res = {
            "frontier": fbm[None],
            "fcount": jnp.sum(fbm, dtype=jnp.int32)[None],
            "hop_edges": jnp.stack(hop_edges)[None],
            # deterministic work counter (ISSUE 1): per-hop frontier
            # size, this shard's members only — host sums over parts
            "frontier_sizes": jnp.stack(frontier_sizes)[None],
            "ovf_expand": ovf_e[None],
        }
        if capture:
            res["cap"] = cap_out
            res["kcount"] = kcount_out   # small: fetched with the meta
        return res

    from jax.sharding import PartitionSpec
    spec = PartitionSpec("part")
    smapped = _shard_map(kernel, mesh=mesh,
                         in_specs=(spec, spec), out_specs=spec)
    return jax.jit(smapped)


def _build_local_fn(P: int, EB, steps: int,
                    n_blocks: int,
                    pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                    pred_cols: Sequence[str] = (),
                    capture: bool = True,
                    capture_hops: bool = False,
                    yield_cols: Sequence[str] = (),
                    hub_dense=None):
    """The UNJITTED single-chip traversal program — shared by
    build_traverse_fn_local (jit) and build_traverse_fn_lanes (jit of a
    vmap over a leading query-lane axis; ISSUE 15)."""
    pids = jnp.arange(P, dtype=jnp.int32)
    ebs = _norm_ebs(EB, steps, capture_hops)
    hubs_c, hub_owner, hub_local = _hub_consts(hub_dense, P)

    def one_part_expand(block, fbm, pid, want_pred, EBh, vmax_local):
        src, dst, rk, eidx, ve, total, ovf = _expand_block(
            block["indptr"], block["nbr"], block["rank"], fbm, EBh, P,
            pid, vmax_local=vmax_local, hub_dense=hubs_c)
        if "d_src" in block:
            # delta snapshots are never hub-extended, so fbm here is the
            # plain (vmax,) membership row
            src, dst, rk, eidx, ve, total = _merge_delta(
                block, fbm, src, dst, rk, eidx, ve, total, P, pid,
                block["nbr"].shape[-1])
        if want_pred:
            cols = {"_rank": rk, "_src": src, "_dst": dst}
            for name in pred_cols:
                if not name.startswith("_"):
                    c = block["props"][name]
                    if "d_src" in block:
                        c = jnp.concatenate([c, block["d_props"][name]])
                    cols[name] = c[eidx]
            keep = pred(cols) & ve
        else:
            keep = ve
        return src, dst, rk, eidx, ve, keep, total, ovf

    def fn(blocks_data, frontier):
        fbm = frontier                     # (P, vmax) bool
        vmax = fbm.shape[1]
        hop_edges = []
        frontier_sizes = []                # popcount entering each hop
        ovf_e = jnp.zeros((P,), bool)
        cap_out = None
        hop_caps = []

        for hop in range(steps):
            frontier_sizes.append(jnp.sum(fbm, axis=1, dtype=jnp.int32))
            last = hop == steps - 1
            EBh = ebs[hop]
            marks = None                   # (P_src, P_dst, vmax) bool
            edges = jnp.zeros((P,), jnp.int32)
            caps = {"src": [], "dst": [], "rank": [], "eidx": [],
                    "kcount": []}
            efbm = fbm if hubs_c is None else _extend_fbm_local(
                fbm, hub_owner, hub_local, P)
            for bi in range(n_blocks):
                b = blocks_data[bi]
                want_pred = pred is not None and (last or capture_hops)
                dcap = _delta_cap(b)
                # the whole block dict is the vmap operand: every leaf
                # (indptr/nbr/rank/props AND the d_* delta plane) carries
                # a leading part axis
                src, dst, rk, eidx, ve, keep, total, ovf = jax.vmap(
                    lambda blk, f, pd: one_part_expand(
                        blk, f, pd, want_pred, EBh, vmax)
                )(b, efbm, pids)
                ovf_e = ovf_e | ovf
                edges = edges + total
                if capture and (last or capture_hops):
                    cs, cd, cr, ce, kc = jax.vmap(
                        lambda s, d, r, e, k: _compact_cap(s, d, r, e, k,
                                                           EBh + dcap)
                    )(src, dst, rk, eidx, keep)
                    caps["src"].append(cs)
                    caps["dst"].append(cd)
                    caps["rank"].append(cr)
                    caps["eidx"].append(ce)
                    caps["kcount"].append(kc)
                    if last and not capture_hops:
                        for name in yield_cols:
                            col = b["props"][name]
                            if dcap:
                                col = jnp.concatenate(
                                    [col, b["d_props"][name]], axis=1)
                            caps.setdefault("prop:" + name, []).append(
                                jax.vmap(lambda c, e: c[e])(col, ce))
                if not last:
                    blk_marks = jax.vmap(
                        lambda d, k: _mark(d, k, P, vmax))(dst, keep)
                    marks = blk_marks if marks is None \
                        else marks | blk_marks
            hop_edges.append(edges)
            if capture and (last or capture_hops):
                # arrays (P, nb, EB); kcount (P, nb)
                hop_caps.append({k: jnp.stack(v, axis=1)
                                 for k, v in caps.items()})

            if last:
                if capture:
                    if capture_hops:
                        arr_keys = ("src", "dst", "rank", "eidx")
                        # (P, steps, nb, EB); kcount (P, steps, nb)
                        cap_out = {k: jnp.stack([hc[k] for hc in hop_caps],
                                                axis=1)
                                   for k in arr_keys}
                        kcount_out = jnp.stack(
                            [hc["kcount"] for hc in hop_caps], axis=1)
                    else:
                        cap_out = {k: v for k, v in hop_caps[-1].items()
                                   if k != "kcount"}
                        kcount_out = hop_caps[-1]["kcount"]
                fbm = jnp.zeros((P, vmax), bool)
            else:
                # marks[s, d] = part s's candidate bitmap for part d;
                # OR over sources = the exchange + merge in one reduce
                fbm = marks.any(axis=0)

        res = {
            "frontier": fbm,
            "fcount": jnp.sum(fbm, axis=1, dtype=jnp.int32),
            "hop_edges": jnp.stack(hop_edges, axis=1),      # (P, steps)
            "frontier_sizes": jnp.stack(frontier_sizes, axis=1),
            "ovf_expand": ovf_e,
        }
        if capture:
            res["cap"] = cap_out
            res["kcount"] = kcount_out   # small: fetched with the meta
        return res

    return fn


def build_traverse_fn_local(P: int, EB, steps: int,
                            n_blocks: int,
                            pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                            pred_cols: Sequence[str] = (),
                            capture: bool = True,
                            capture_hops: bool = False,
                            yield_cols: Sequence[str] = (),
                            hub_dense=None):
    """Single-chip variant: all P partitions resident on one device, the
    per-part kernel vmapped over the part axis, and the frontier exchange
    an OR-reduce over the mark matrices (the degenerate all_to_all).
    This is the program that runs on one real chip (the bench config) —
    identical semantics to the sharded build, no ICI.  capture_hops
    follows the sharded contract (MATCH mode: per-hop pred + per-hop
    frames, cap arrays (P, steps, n_blocks, EB)).
    """
    return jax.jit(_build_local_fn(
        P, EB, steps, n_blocks, pred=pred, pred_cols=pred_cols,
        capture=capture, capture_hops=capture_hops,
        yield_cols=yield_cols, hub_dense=hub_dense))


def build_traverse_fn_lanes(P: int, EB, steps: int,
                            n_blocks: int,
                            pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                            pred_cols: Sequence[str] = (),
                            capture: bool = True,
                            capture_hops: bool = False,
                            yield_cols: Sequence[str] = (),
                            hub_dense=None):
    """Query-lane-batched single-chip program (ISSUE 15 tentpole).

    The same traversal program with a leading QUERY-ID LANE axis vmapped
    over the frontier: L compatible statements (same kernel family, same
    shape bucket, same predicate/yield program) share ONE device put,
    ONE dispatch and ONE fetch — the CSR blocks are closed over once and
    broadcast across lanes (`in_axes=(None, 0)`), so the marginal cost
    of a lane is its own expansion work, not a full kernel launch.

    Inputs/outputs match the local builder's contract with a leading L
    axis added: frontier (L, P, vmax) bool; every result leaf —
    hop_edges, frontier_sizes, ovf_expand, kcount and the cap arrays —
    gains the lane axis, and the runtime de-muxes lane l back to its
    statement by slicing `[l]`.  Lanes are INDEPENDENT computations
    (no cross-lane reduction anywhere), so each lane's captured edge
    set is bit-identical to the same statement's solo dispatch at the
    same edge budget; padding lanes (all-false frontier) expand zero
    edges and only cost their share of the dense kernel shape.
    """
    fn = _build_local_fn(
        P, EB, steps, n_blocks, pred=pred, pred_cols=pred_cols,
        capture=capture, capture_hops=capture_hops,
        yield_cols=yield_cols, hub_dense=hub_dense)
    return jax.jit(jax.vmap(fn, in_axes=(None, 0)))


def build_traverse_fn_lanes_sharded(mesh, P: int, EB, steps: int,
                                    n_blocks: int,
                                    pred: Optional[Callable[[Dict[str, Any]], Any]] = None,
                                    pred_cols: Sequence[str] = (),
                                    capture: bool = True,
                                    capture_hops: bool = False,
                                    yield_cols: Sequence[str] = (),
                                    hub_dense=None):
    """The lanes × shards launch grid: ONE shard_map program over the
    2-axis ("lane", "part") mesh that fuses PR 12's query-id lane axis
    with the partition axis.

    Unlike `build_traverse_fn_lanes` (single chip: CSR broadcast to every
    lane via `in_axes=(None, 0)`), the CSR blocks here are MESH-RESIDENT:
    their in_specs name the part axis, so device (l, p) reads partition
    p's adjacency out of its own HBM and never sees the other P-1 shards.
    The frontier is (L, P, vmax) sharded over BOTH axes — each device
    owns L/lanes query lanes of its partition's bitmap — and the per-hop
    bit-packed exchange is ONE `all_to_all` whose payload carries the
    full lanes × parts grid (`_exchange_marks_lanes`).

    The global result contract is IDENTICAL to `build_traverse_fn_lanes`:
    every leaf carries leading (L, P) axes (hop_edges (L, P, steps),
    cap arrays (L, P, nb, EB) / (L, P, steps, nb, EB), ...), so the
    runtime's `_escalate_lanes` / `_lane_attribution` de-mux paths work
    unchanged on either program.

    Degrade semantics: a (1, 1) mesh never reaches this builder (the
    runtime's local mode uses the vmap program), and a (1, P) mesh runs
    it with every lane resident on the part row — same program, lane
    axis unsplit.
    """
    ebs = _norm_ebs(EB, steps, capture_hops)
    hubs_c, hub_owner, hub_local = _hub_consts(hub_dense, P)

    def kernel(blocks_data, frontier):
        fbm = frontier[:, 0]                   # (Ll, vmax) bool
        Ll = fbm.shape[0]
        vmax = fbm.shape[1]
        pid = jax.lax.axis_index("part").astype(jnp.int32)
        hop_edges: List[Any] = []
        frontier_sizes: List[Any] = []
        ovf_e = jnp.zeros((Ll,), bool)
        cap_out = None
        hop_caps: List[Dict[str, Any]] = []

        for hop in range(steps):
            frontier_sizes.append(jnp.sum(fbm, axis=1, dtype=jnp.int32))
            last = hop == steps - 1
            EBh = ebs[hop]
            marks = None                       # (Ll, P, vmax) bool
            edges_this_hop = jnp.zeros((Ll,), jnp.int32)
            caps = {"src": [], "dst": [], "rank": [], "eidx": [],
                    "kcount": []}
            efbm = fbm if hubs_c is None else _extend_fbm_sharded_lanes(
                fbm, pid, hub_owner, hub_local)
            for bi in range(n_blocks):
                b = blocks_data[bi]
                dcap = _delta_cap(b)
                dl = ({k: b[k][0] for k in
                       ("d_src", "d_dst", "d_rank", "d_valid", "d_tomb")}
                      if dcap else None)
                emax = b["nbr"].shape[-1]

                def lane_expand(f):
                    out = _expand_block(
                        b["indptr"][0], b["nbr"][0], b["rank"][0], f, EBh,
                        P, pid, vmax_local=vmax, hub_dense=hubs_c)
                    s, d, r, e, v, t, o = out
                    if dl is not None:
                        # per-lane merge: delta-row activity depends on
                        # THIS lane's frontier bitmap
                        s, d, r, e, v, t = _merge_delta(
                            dl, f, s, d, r, e, v, t, P, pid, emax)
                    return s, d, r, e, v, t, o

                src, dst, rk, eidx, ve, total, ovf = jax.vmap(
                    lane_expand)(efbm)
                ovf_e = ovf_e | ovf
                edges_this_hop = edges_this_hop + total

                def _col(name):
                    c = b["props"][name][0]
                    if dcap:
                        c = jnp.concatenate([c, b["d_props"][name][0]])
                    return c

                if pred is not None and (last or capture_hops):
                    cols = {"_rank": rk, "_src": src, "_dst": dst}
                    for name in pred_cols:
                        if not name.startswith("_"):
                            cols[name] = _col(name)[eidx]
                    keep = pred(cols) & ve
                else:
                    keep = ve
                if capture and (last or capture_hops):
                    cs, cd, cr, ce, kc = jax.vmap(
                        lambda s, d, r, e, k: _compact_cap(
                            s, d, r, e, k,
                            EBh + dcap))(src, dst, rk, eidx, keep)
                    caps["src"].append(cs)
                    caps["dst"].append(cd)
                    caps["rank"].append(cr)
                    caps["eidx"].append(ce)
                    caps["kcount"].append(kc)
                    if last and not capture_hops:
                        for name in yield_cols:
                            caps.setdefault("prop:" + name, []).append(
                                _col(name)[ce])
                if not last:
                    marks_b = jax.vmap(
                        lambda d, k: _mark(d, k, P, vmax))(dst, keep)
                    marks = marks_b if marks is None else marks | marks_b
            hop_edges.append(edges_this_hop)
            if capture and (last or capture_hops):
                # arrays (Ll, nb, EB); kcount (Ll, nb)
                hop_caps.append({k: jnp.stack(v, axis=1)
                                 for k, v in caps.items()})

            if last:
                if capture:
                    if capture_hops:
                        arr_keys = ("src", "dst", "rank", "eidx")
                        # local (Ll, 1, steps, nb, EB)
                        cap_out = {k: jnp.stack(
                            [hc[k] for hc in hop_caps], axis=1)[:, None]
                            for k in arr_keys}
                        kcount_out = jnp.stack(
                            [hc["kcount"] for hc in hop_caps],
                            axis=1)[:, None]
                    else:
                        cap_out = {k: v[:, None]
                                   for k, v in hop_caps[-1].items()
                                   if k != "kcount"}
                        kcount_out = hop_caps[-1]["kcount"][:, None]
                fbm = jnp.zeros((Ll, vmax), bool)
            else:
                fbm = _exchange_marks_lanes(marks, P, vmax)

        res = {
            "frontier": fbm[:, None],                       # (Ll, 1, vmax)
            "fcount": jnp.sum(fbm, axis=1, dtype=jnp.int32)[:, None],
            "hop_edges": jnp.stack(hop_edges, axis=1)[:, None],
            "frontier_sizes": jnp.stack(frontier_sizes, axis=1)[:, None],
            "ovf_expand": ovf_e[:, None],
        }
        if capture:
            res["cap"] = cap_out
            res["kcount"] = kcount_out
        return res

    from jax.sharding import PartitionSpec
    csr_spec = PartitionSpec("part")
    # legacy 1-D ('part',) meshes carry no lane axis: the global lane
    # dimension stays unsharded (every device holds all lanes) and the
    # same kernel runs with Ll == L
    lane_ax = "lane" if "lane" in mesh.axis_names else None
    lane_spec = PartitionSpec(lane_ax, "part")
    smapped = _shard_map(kernel, mesh=mesh,
                         in_specs=(csr_spec, lane_spec),
                         out_specs=lane_spec)
    return jax.jit(smapped)
