"""TpuMatchAgg: fused fixed-length MATCH → aggregate device pipeline.

The reference executes an IC-shaped aggregate MATCH —

    MATCH (p)-[:E]->(f)-[:E]->(ff) WHERE <vertex preds>
    RETURN id(ff), count(*)

— as a chain of per-hop GetNeighbors RPC fan-outs with row-at-a-time
filter/aggregate executors above them (reference: the Traverse /
AppendVertices / Aggregate executor stack in src/graph/executor
[UNVERIFIED — empty mount, SURVEY §0]).  Here the whole chain collapses
into ONE plan node (SURVEY §2 rows 22–23):

  * one multi-hop device expansion (`TpuRuntime.traverse_hops`) — the
    frontier never leaves HBM between hops;
  * columnar trail assembly on host numpy (the same searchsorted join
    the unfused device Traverse uses, but never decoding Edge/Vertex
    objects at all);
  * vertex predicates (labels, `_hastag`, `v.Tag.prop` filters)
    evaluated as numpy masks over the snapshot's TagTable columns
    (exprjit.compile_vertex_predicate_np) — per POSITION in the
    pattern, pruning trails hop-by-hop;
  * relationship-uniqueness (`_edges_distinct`) enforced by the
    assembly's columnar canonical-key compare — the planner's Filter
    conjunct is absorbed, not re-checked per row;
  * the aggregate itself is a numpy lexsort group-by: count(*) /
    count(id(v)) / count(DISTINCT id(v)) over int64 dense-id columns.

Python row objects are never built: the node's output is the final
(tiny) aggregate table.  Variable-length patterns (`-[e:E*m..M]->`,
the Twitter-proxy benchmark shape) fuse too: one device expansion to
M hops, with the terminal checks gating EMISSION per depth — never
continuation — exactly like the unfused AppendVertices-after-Traverse
ordering.  Anything the rule cannot prove — per-hop edge filters,
non-id group keys, cross-alias predicates, aggregates beyond counts,
unbounded `*m..` — leaves the plan unfused on the general executors,
and any device-plane failure at run time falls back to
`_host_match_agg`, a host implementation with the exact chain
semantics.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import expr as E
from ..core.value import DataSet, Vertex, is_null
from ..exec.executors import executor, _make_edge
from ..query import optimizer as opt
from ..query.plan import PlanNode
from .device import TpuUnavailable
from .exprjit import (CannotCompile, compile_vertex_predicate_np,
                      vertex_compilable)

try:
    import jax
    _JAX_RT_ERRORS = (jax.errors.JaxRuntimeError,)
except (ImportError, AttributeError):
    _JAX_RT_ERRORS = ()


# ---------------------------------------------------------------------------
# Plan-shape helpers
# ---------------------------------------------------------------------------


def _is_edges_distinct(e: E.Expr, edge_aliases: List[str]) -> bool:
    return (isinstance(e, E.FunctionCall) and e.name == "_edges_distinct"
            and all(isinstance(a, E.LabelExpr) for a in e.args)
            and {a.name for a in e.args} == set(edge_aliases))


def _id_alias(e: E.Expr) -> Optional[str]:
    """alias for `id(<alias>)`, else None."""
    if (isinstance(e, E.FunctionCall) and e.name == "id"
            and len(e.args) == 1 and isinstance(e.args[0], E.LabelExpr)):
        return e.args[0].name
    return None


def _head_hastag_tags(cond: E.Expr, alias: str) -> Optional[List[str]]:
    """Filter over the seed GetVertices: AND of _hastag(alias, T) only."""
    tags = []
    for c in E.split_conjuncts(cond):
        if (isinstance(c, E.FunctionCall) and c.name == "_hastag"
                and len(c.args) == 2 and isinstance(c.args[0], E.LabelExpr)
                and c.args[0].name == alias
                and isinstance(c.args[1], E.Literal)
                and isinstance(c.args[1].value, str)):
            tags.append(c.args[1].value)
            continue
        return None
    return tags


# ---------------------------------------------------------------------------
# Fusion rule
# ---------------------------------------------------------------------------


def _single(uses: Dict[int, int], node: PlanNode) -> bool:
    return uses.get(node.id, 2) == 1 and len(node.deps) == 1


def make_match_agg_rule(uses: Dict[int, int], root=None):
    def rule(node: PlanNode) -> Optional[PlanNode]:
        if node.kind != "Aggregate":
            return None
        if len(node.deps) != 1:
            return None
        cur = node.dep()
        filt_conjs: List[E.Expr] = []
        if cur.kind == "Filter":
            if not _single(uses, cur):
                return None
            filt_conjs = E.split_conjuncts(cur.args["condition"])
            cur = cur.dep()
        if cur.kind != "AppendVertices" or not _single(uses, cur):
            return None
        term = cur
        term_alias = term.args["col"]
        sp = term.args.get("space")
        term_labels = list(term.args.get("labels") or [])
        term_filter = term.args.get("filter")
        if term_filter is not None \
                and not vertex_compilable(term_filter, term_alias):
            return None
        cur = term.dep()

        # walk the Traverse[←AppendVertices]←Traverse chain, outermost
        # (= terminal hop) first; record which mid positions carry an
        # AppendVertices (the host plane only existence-checks those)
        hops_rev: List[PlanNode] = []
        checked_aliases = set()
        while cur.kind == "Traverse":
            if not _single(uses, cur):
                return None
            a = cur.args
            if a.get("edge_filter") is not None:
                return None
            if a.get("space") != sp:
                return None
            hops_rev.append(cur)
            nxt = cur.dep()
            if nxt.kind == "AppendVertices":
                if not _single(uses, nxt):
                    return None
                if nxt.args.get("filter") is not None \
                        or nxt.args.get("labels"):
                    return None
                if nxt.args.get("space") != sp:
                    return None
                if nxt.args.get("col") != a.get("src_col"):
                    return None
                checked_aliases.add(a.get("src_col"))
                nxt = nxt.dep()
                if nxt.kind != "Traverse":
                    return None
            cur = nxt
        if not hops_rev:
            return None
        hops = hops_rev[::-1]
        # hop-count shape: either a chain of fixed 1-hop Traverses, or
        # ONE variable-length Traverse (MATCH *m..M — config-4 shape);
        # a var-len node inside a longer chain stays on the general path
        if len(hops) == 1:
            min_hop = hops[0].args.get("min_hop")
            max_hop = hops[0].args.get("max_hop")
            if min_hop is None or max_hop is None or max_hop < 1 \
                    or min_hop < 0 or min_hop > max_hop:
                return None              # unbounded (*m..) stays unfused
            var_len = not (min_hop == 1 and max_hop == 1)
        else:
            if any(h.args.get("min_hop") != 1 or h.args.get("max_hop") != 1
                   for h in hops):
                return None
            min_hop, max_hop = len(hops), len(hops)
            var_len = False
        # chain wiring + uniform expansion parameters
        etypes = hops[0].args.get("edge_types")
        direction = hops[0].args.get("direction")
        for i, h in enumerate(hops):
            if h.args.get("edge_types") != etypes \
                    or h.args.get("direction") != direction:
                return None
            if i > 0 and h.args.get("src_col") != hops[i - 1].args.get(
                    "dst_alias"):
                return None
        if hops[-1].args.get("dst_alias") != term_alias:
            return None

        # chain head: optional label Filter over literal-vid GetVertices
        head = cur
        head_tags: List[str] = []
        src_alias = hops[0].args.get("src_col")
        if head.kind == "Filter":
            if not _single(uses, head):
                return None
            tags = _head_hastag_tags(head.args["condition"], src_alias)
            if tags is None:
                return None
            head_tags = tags
            head = head.dep()
        if head.kind != "GetVertices":
            return None
        if uses.get(head.id, 2) != 1 or head.deps:
            return None
        ha = head.args
        if ha.get("src_col") or ha.get("tags") or ha.get("space") != sp:
            return None
        if (ha.get("as_col") or (head.col_names[0] if head.col_names
                                 else None)) != src_alias:
            return None
        vids = ha.get("vids") or []
        for v in vids:
            if isinstance(v, E.Expr) and not isinstance(v, E.Literal):
                return None

        edge_aliases = [h.args.get("edge_alias") for h in hops]
        vertex_aliases = [src_alias] + [h.args.get("dst_alias")
                                        for h in hops]
        if len(set(vertex_aliases)) != len(vertex_aliases):
            # a cyclic pattern re-binds an alias: equality join between
            # positions — not modeled here, stay on the general path
            return None
        checked_aliases.add(src_alias)       # GetVertices builds vertices
        checked_aliases.add(term_alias)      # terminal AppendVertices

        # classify residual Filter conjuncts: relationship uniqueness
        # (absorbed into assembly) or a single-alias vertex predicate
        # (absorbed into that pattern position).  A predicate may only
        # land on a position whose vertex the host plane materialized
        # (an unchecked mid carries a props-less shell Vertex, whose
        # prop reads answer NULL — different semantics).
        edges_distinct = False
        alias_preds: Dict[str, List[E.Expr]] = {}
        for cj in filt_conjs:
            if _is_edges_distinct(cj, edge_aliases):
                edges_distinct = True
                continue
            placed = False
            for al in vertex_aliases:
                if al in checked_aliases and vertex_compilable(cj, al):
                    alias_preds.setdefault(al, []).append(cj)
                    placed = True
                    break
            if not placed:
                return None
        if term_filter is not None:
            alias_preds.setdefault(term_alias, []).append(term_filter)

        # aggregate surface: id(alias) group keys, count aggregates
        group_keys = node.args.get("group_keys") or []
        group_aliases: List[str] = []
        for gk in group_keys:
            al = _id_alias(gk)
            if al is None or al not in vertex_aliases:
                return None
            group_aliases.append(al)
        agg_specs: List[Tuple] = []
        key_texts = [E.to_text(gk) for gk in group_keys]
        for ce, _name in node.args.get("columns") or []:
            if isinstance(ce, E.AggExpr):
                if ce.func != "count":
                    return None
                if ce.arg is None:
                    agg_specs.append(("count", None, False))
                    continue
                al = _id_alias(ce.arg)
                if al is None or al not in vertex_aliases:
                    return None
                agg_specs.append(("count", al, bool(ce.distinct)))
                continue
            txt = E.to_text(ce)
            if txt in key_texts:
                agg_specs.append(("key", group_aliases[key_texts.index(txt)]))
                continue
            return None

        if var_len:
            # the var-len Traverse's DFS enforces distinct edges within
            # each path internally — not via a planner Filter conjunct
            edges_distinct = True
        return PlanNode(
            "TpuMatchAgg", deps=[],
            args={"space": sp, "vids": list(vids), "src_alias": src_alias,
                  "etypes": list(etypes or []), "direction": direction,
                  "steps": max_hop, "min_hop": min_hop, "var_len": var_len,
                  "vertex_aliases": vertex_aliases,
                  "checked_aliases": sorted(checked_aliases),
                  "head_tags": head_tags,
                  "term_labels": term_labels,
                  "alias_preds": {al: E.join_conjuncts(ps)
                                  for al, ps in alias_preds.items()},
                  "edges_distinct": edges_distinct,
                  "group_aliases": group_aliases,
                  "agg_specs": agg_specs},
            col_names=list(node.col_names))

    return rule


opt.TPU_RULES.append(make_match_agg_rule)


# ---------------------------------------------------------------------------
# Executor — device plane
# ---------------------------------------------------------------------------


def _seed_vids(a: Dict[str, Any]) -> List[Any]:
    from ..core.expr import DictContext
    from ..core.value import hashable_key
    out, seen = [], set()
    for ve in a.get("vids") or []:
        v = ve.eval(DictContext()) if isinstance(ve, E.Expr) else ve
        if isinstance(v, Vertex):
            v = v.vid
        if is_null(v):
            continue
        k = hashable_key(v)
        if k in seen:
            continue
        seen.add(k)
        out.append(v)
    return out


def _exists_flat(snap) -> np.ndarray:
    """dense-indexed 'vertex exists' mask (any tag present, mirroring
    build_vertex returning None for tag-less vids); cached on the
    snapshot (epoch-keyed object, so the cache dies with the epoch)."""
    m = getattr(snap, "_exists_flat", None)
    if m is None:
        P = snap.num_parts
        m = np.zeros(P * snap.vmax, bool)
        for tt in snap.tags.values():
            m |= tt.present.T.ravel()
        try:
            snap._exists_flat = m
        except AttributeError:
            pass
    return m


def _tag_flat(snap, tag: str) -> Optional[np.ndarray]:
    tt = snap.tags.get(tag)
    return None if tt is None else tt.present.T.ravel()


def _position_mask_fn(alias: str, a: Dict[str, Any], snap, sd):
    """Build the combined existence + label + predicate mask function
    for one pattern position (compile once, evaluate per depth —
    code-review r4).  Positions without an AppendVertices in the
    unfused plan are never existence-checked by the host plane, so
    they aren't here either (parity over dangling edges)."""
    checked = alias in (a.get("checked_aliases") or ())
    labels = a["term_labels"] if alias == a["vertex_aliases"][-1] else []
    tag_flats = []
    dead = False
    for lb in labels:
        tf = _tag_flat(snap, lb)
        if tf is None:
            dead = True
            break
        tag_flats.append(tf)
    pred = (a.get("alias_preds") or {}).get(alias)
    pred_fn = compile_vertex_predicate_np(pred, alias, snap, sd) \
        if pred is not None else None
    exists = _exists_flat(snap) if checked else None

    def mask(dense: np.ndarray) -> np.ndarray:
        if dead:
            return np.zeros(dense.shape, bool)
        m = exists[dense] if exists is not None \
            else np.ones(dense.shape, bool)
        for tf in tag_flats:
            m &= tf[dense]
        if pred_fn is not None:
            m &= pred_fn(dense)
        return m

    return mask


def _position_mask(dense: np.ndarray, alias: str, a: Dict[str, Any],
                   snap, sd) -> np.ndarray:
    return _position_mask_fn(alias, a, snap, sd)(dense)


def _group_rows(a: Dict[str, Any], cols: Dict[str, np.ndarray],
                d2v: np.ndarray) -> List[List[Any]]:
    """numpy lexsort group-by over emitted-trail dense-id columns (one
    per referenced vertex alias, all equal length) → output rows."""
    group_aliases = a["group_aliases"]
    agg_specs = a["agg_specs"]
    n = next(iter(cols.values())).size if cols else 0

    def col(al):
        return cols.get(al, np.empty(0, np.int64))

    if not group_aliases:
        row = []
        for spec in agg_specs:
            if spec[1] is None or not spec[2]:
                row.append(int(n))
            else:
                row.append(int(np.unique(col(spec[1])).size) if n else 0)
        return [row]

    if n == 0:
        return []
    keys = [col(al) for al in group_aliases]
    order = np.lexsort(keys[::-1])
    sk = [k[order] for k in keys]
    new_grp = np.zeros(n, bool)
    new_grp[0] = True
    for k in sk:
        new_grp[1:] |= k[1:] != k[:-1]
    starts = np.flatnonzero(new_grp)
    sizes = np.diff(np.concatenate([starts, [n]]))
    gid = np.cumsum(new_grp) - 1          # group id per sorted trail

    out_cols: List[Any] = []
    for spec in agg_specs:
        if spec[0] == "key":
            out_cols.append(d2v[sk[group_aliases.index(spec[1])][starts]])
        elif spec[1] is None or not spec[2]:
            out_cols.append(sizes)
        else:
            tcol = col(spec[1])[order]
            o2 = np.lexsort((tcol, gid))
            g2, t2 = gid[o2], tcol[o2]
            first = np.ones(n, bool)
            first[1:] = (g2[1:] != g2[:-1]) | (t2[1:] != t2[:-1])
            out_cols.append(np.bincount(g2[first],
                                        minlength=starts.size))
    rows = []
    cols_py = [c.tolist() for c in out_cols]
    for i in range(starts.size):
        rows.append([c[i] for c in cols_py])
    return rows


@executor("TpuMatchAgg")
def _tpu_match_agg(node, qctx, ectx, space):
    a = node.args
    rt = getattr(qctx, "tpu_runtime", None)
    if rt is not None:
        from ..utils.config import get_config
        if get_config().get("tpu_match_device"):
            try:
                return _device_match_agg(node, qctx, ectx, a, rt)
            except (CannotCompile, TpuUnavailable) + _JAX_RT_ERRORS as ex:
                qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
    return _host_match_agg(node, qctx, a)


def _device_match_agg(node, qctx, ectx, a, rt):
    from .runtime import _d2v, join_frontier_trails, trail_distinct_keep
    sp = a["space"]
    store = qctx.store
    try:
        sd = store.space(sp)
        sd.dense_id
    except AttributeError:
        raise TpuUnavailable("store has no dense-id surface")

    dev = rt.pin(store, sp)
    snap = dev.host
    steps = a["steps"]
    src_alias = a["src_alias"]

    vids = _seed_vids(a)
    dense = np.asarray([sd.dense_id(v) for v in vids], np.int64) \
        if vids else np.empty(0, np.int64)
    keep_vids: List[Any] = []
    if dense.size:
        m = dense >= 0
        if m.any():
            d = dense[m]
            pm = _exists_flat(snap)[d]
            for tg in a.get("head_tags") or []:
                tf = _tag_flat(snap, tg)
                pm &= tf[d] if tf is not None else False
            pred = (a.get("alias_preds") or {}).get(src_alias)
            if pred is not None:
                pm &= compile_vertex_predicate_np(pred, src_alias, snap,
                                                  sd)(d)
            kept = d[pm]
            kv = np.asarray(vids, object)[m][pm]
            keep_vids = kv.tolist()
            dense = kept
        else:
            dense = np.empty(0, np.int64)

    if not keep_vids:
        return DataSet(list(node.col_names),
                       _group_rows(a, {}, None)
                       if not a["group_aliases"] else [])

    frames, stats = rt.traverse_hops(store, sp, keep_vids, a["etypes"],
                                     a["direction"], steps)
    qctx.last_tpu_stats = stats
    tracker = getattr(ectx, "tracker", None)
    term_alias = a["vertex_aliases"][-1]
    min_hop = a.get("min_hop", steps)
    d2v = _d2v(snap)

    if a.get("var_len"):
        # MATCH *m..M: terminal checks gate EMISSION at each depth in
        # [max(m,1), M] — they never prune continuation (the unfused
        # plan's AppendVertices filters rows AFTER the whole var-len
        # Traverse).  Edge-distinctness always applies within a path.
        scol, last = dense, dense
        path: List[np.ndarray] = []
        emit_s: List[np.ndarray] = []
        emit_d: List[np.ndarray] = []
        term_mask = _position_mask_fn(term_alias, a, snap, sd)
        if min_hop == 0:
            pm = term_mask(dense)
            emit_s.append(dense[pm])
            emit_d.append(dense[pm])
        for h in range(steps):
            fr = frames[h]
            if scol.size == 0 or fr.n == 0:
                break
            parent, fidx = join_frontier_trails(fr, last)
            if fidx.size == 0:
                break
            if path:
                keep = trail_distinct_keep(frames, path, parent, fr, fidx)
                sel = np.flatnonzero(keep)
                parent, fidx = parent[sel], fidx[sel]
                if fidx.size == 0:
                    break
            scol = scol[parent]
            last = fr.dst[fidx]
            path = [pe[parent] for pe in path] + [fidx]
            if tracker is not None:
                tracker.charge(int(fidx.size) * 8 * (h + 2))
            if h + 1 >= max(min_hop, 1):
                pm = term_mask(last)
                emit_s.append(scol[pm])
                emit_d.append(last[pm])
        es = np.concatenate(emit_s) if emit_s else np.empty(0, np.int64)
        ed = np.concatenate(emit_d) if emit_d else np.empty(0, np.int64)
        cols = {a["src_alias"]: es, term_alias: ed}
        return DataSet(list(node.col_names), _group_rows(a, cols, d2v))

    vcols: List[np.ndarray] = [dense]
    path = []
    alive = True
    for h in range(steps):
        fr = frames[h]
        if vcols[0].size == 0 or fr.n == 0:
            alive = False
            break
        parent, fidx = join_frontier_trails(fr, vcols[-1])
        if fidx.size == 0:
            alive = False
            break
        if a["edges_distinct"] and path:
            keep = trail_distinct_keep(frames, path, parent, fr, fidx)
            sel = np.flatnonzero(keep)
            parent, fidx = parent[sel], fidx[sel]
        nxt = fr.dst[fidx]
        al = a["vertex_aliases"][h + 1]
        pm = _position_mask(nxt, al, a, snap, sd)
        if pm is not None and not pm.all():
            sel = np.flatnonzero(pm)
            parent, fidx, nxt = parent[sel], fidx[sel], nxt[sel]
        vcols = [c[parent] for c in vcols] + [nxt]
        path = [pe[parent] for pe in path] + [fidx]
        if vcols[0].size == 0:
            alive = False
            break

    if not alive:
        vcols = [np.empty(0, np.int64)] * len(a["vertex_aliases"])

    if tracker is not None and vcols[0].size:
        tracker.charge(int(vcols[0].size) * 8 * (steps + 1))

    cols = {al: vcols[i] for i, al in enumerate(a["vertex_aliases"])}
    return DataSet(list(node.col_names), _group_rows(a, cols, d2v))


# ---------------------------------------------------------------------------
# Host fallback — exact chain semantics, no device
# ---------------------------------------------------------------------------


def _host_match_agg(node, qctx, a):
    from ..core.expr import to_bool3
    from ..core.value import hashable_key
    from ..exec.context import RowContext

    sp = a["space"]
    store = qctx.store
    steps = a["steps"]
    etypes = a["etypes"]
    etype_ids = {e: store.catalog.get_edge(sp, e).edge_type for e in etypes}
    direction = a["direction"]
    aliases = a["vertex_aliases"]
    alias_preds = a.get("alias_preds") or {}
    term_alias = aliases[-1]

    vcache: Dict[Any, Optional[Vertex]] = {}

    def vertex_of(vid):
        if vid not in vcache:
            vcache[vid] = qctx.build_vertex(sp, vid)
        return vcache[vid]

    vd_cache: Dict[Tuple[str, Any], bool] = {}

    checked = set(a.get("checked_aliases") or ())

    def position_ok(alias: str, vid) -> bool:
        key = (alias, hashable_key(vid))
        v = vd_cache.get(key)
        if v is None:
            if alias not in checked:
                vd_cache[key] = v = True
                return v
            full = vertex_of(vid)
            ok = full is not None
            if ok and alias == term_alias:
                ok = all(lb in full.tag_names()
                         for lb in a.get("term_labels") or [])
            if ok and alias == aliases[0]:
                ok = all(tg in full.tag_names()
                         for tg in a.get("head_tags") or [])
            pred = alias_preds.get(alias)
            if ok and pred is not None:
                rc = RowContext(qctx, sp, {alias: full})
                ok = to_bool3(pred.eval(rc)) is True
            vd_cache[key] = v = ok
        return v

    groups: Dict[Tuple, Dict[str, Any]] = {}
    order: List[Tuple] = []
    group_aliases = a["group_aliases"]
    agg_specs = a["agg_specs"]
    var_len = a.get("var_len")
    min_hop = a.get("min_hop", steps)
    term_alias = aliases[-1]

    def emit(vals: Dict[str, Any]):
        key = tuple(hashable_key(vals[al]) for al in group_aliases)
        g = groups.get(key)
        if g is None:
            g = groups[key] = {"vids": [vals[al] for al in group_aliases],
                               "n": 0,
                               "sets": [set() for _ in agg_specs]}
            order.append(key)
        g["n"] += 1
        for i, spec in enumerate(agg_specs):
            if spec[0] == "count" and spec[1] is not None and spec[2]:
                g["sets"][i].add(hashable_key(vals[spec[1]]))

    def dfs(vid, depth: int, trail: List[Any], eseen: set):
        if depth == steps:
            emit({al: trail[i] for i, al in enumerate(aliases)})
            return
        for (s, et, rank, other, props, sgn) in store.get_neighbors(
                sp, [vid], etypes, direction):
            e = _make_edge(s, other, et, rank, props, sgn, etype_ids[et])
            ek = e.key()
            if a["edges_distinct"] and ek in eseen:
                continue
            if not position_ok(aliases[depth + 1], other):
                continue
            trail.append(other)
            if a["edges_distinct"]:
                eseen.add(ek)
            dfs(other, depth + 1, trail, eseen)
            if a["edges_distinct"]:
                eseen.discard(ek)
            trail.pop()

    def dfs_var(seed, vid, depth: int, eseen: set):
        # emission gates on the terminal checks; continuation does not
        # (the unfused AppendVertices filters rows AFTER the Traverse)
        for (s, et, rank, other, props, sgn) in store.get_neighbors(
                sp, [vid], etypes, direction):
            e = _make_edge(s, other, et, rank, props, sgn, etype_ids[et])
            ek = e.key()
            if ek in eseen:
                continue
            if depth + 1 >= max(min_hop, 1) \
                    and position_ok(term_alias, other):
                emit({aliases[0]: seed, term_alias: other})
            if depth + 1 < steps:
                eseen.add(ek)
                dfs_var(seed, other, depth + 1, eseen)
                eseen.discard(ek)

    for vid in _seed_vids(a):
        if not position_ok(aliases[0], vid):
            continue
        if var_len:
            if min_hop == 0 and position_ok(term_alias, vid):
                emit({aliases[0]: vid, term_alias: vid})
            dfs_var(vid, vid, 0, set())
        else:
            dfs(vid, 0, [vid], set())

    rows: List[List[Any]] = []
    if not order and not group_aliases:
        row = []
        for spec in agg_specs:
            row.append(0)
        return DataSet(list(node.col_names), [row])
    for key in order:
        g = groups[key]
        row: List[Any] = []
        for i, spec in enumerate(agg_specs):
            if spec[0] == "key":
                row.append(g["vids"][group_aliases.index(spec[1])])
            elif spec[1] is not None and spec[2]:
                row.append(len(g["sets"][i]))
            else:
                row.append(g["n"])
        rows.append(row)
    return DataSet(list(node.col_names), rows)
