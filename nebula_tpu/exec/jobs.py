"""Job manager + snapshots — the metad JobManager analog
(reference: src/meta/processors/job [UNVERIFIED — empty mount, SURVEY §0]).

Single-process form: jobs run synchronously and record their status; the
cluster metad wraps this with background scheduling.  Job kinds mirror the
reference: stats, compact (a no-op re-pack host-side), balance data /
balance leader (meaningful in cluster mode; recorded here), ingest.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.value import DataSet

class JobStopped(Exception):
    """A task observed its cancel token (STOP JOB) and aborted."""


@dataclass
class Job:
    job_id: int
    command: str
    status: str = "QUEUE"
    start_time: float = 0.0
    stop_time: float = 0.0
    result: Optional[Dict[str, Any]] = None
    space: Optional[str] = None          # RECOVER re-runs in this space
    cancel: Any = None                   # threading.Event (task lifecycle)
    on_start: Any = None                 # fn(job) when a worker picks it up
    on_done: Any = None                  # fn(job) after the worker ends


class JobManager:
    """Async admin-task manager (SURVEY §2 row 16, the AdminTaskManager
    analog): SUBMIT returns the job id immediately; a bounded worker
    pool (flag max_concurrent_admin_jobs) drains the QUEUE — excess
    submissions wait their turn (task throttling), STOP JOB cancels a
    QUEUE'd job outright and interrupts a RUNNING one at its next
    cancel point, and wait() is the test/console convenience for the
    reference TCK's "wait the job to finish" step."""

    def __init__(self):
        from ..utils.racecheck import make_lock
        self.jobs: Dict[int, Job] = {}
        self._ids = itertools.count(1)   # per-manager: deterministic ids
        self._lock = make_lock("job_manager")
        self._queue: list = []           # pending (job, qctx)
        self._running = 0

    @staticmethod
    def _max_concurrent() -> int:
        from ..utils.config import get_config
        try:
            return max(1, int(get_config().get(
                "max_concurrent_admin_jobs")))
        except Exception:  # noqa: BLE001 — config missing in odd embeds
            return 2

    def submit(self, qctx, command: str, space: Optional[str],
               job_id: Optional[int] = None, on_start=None,
               on_done=None) -> Job:
        """Enqueue a job.  `job_id` pins the id (cluster mode: the
        metad-allocated cluster-wide id); `on_start(job)`/`on_done(job)`
        fire from the worker thread (cluster mode: mirror the
        RUNNING/terminal status back to metad's replicated job table)."""
        import threading
        with self._lock:
            jid = job_id if job_id is not None else next(self._ids)
            job = Job(jid, command, space=space,
                      cancel=threading.Event())
            job.on_start = on_start
            job.on_done = on_done
            self.jobs[jid] = job
            self._queue.append((job, qctx))
            self._dispatch_locked()
        return job

    def enqueue_rerun(self, job: Job, qctx):
        """RECOVER JOB: put a FAILED/STOPPED job back on the queue."""
        with self._lock:
            job.status = "QUEUE"
            if job.cancel is not None:
                job.cancel.clear()   # the re-run gets a LIVE cancel token
            self._queue.append((job, qctx))
            self._dispatch_locked()

    def stop(self, job: Job):
        """STOP JOB under the manager lock: purge the queue entry (a
        stale tuple would re-dispatch after RECOVER — double execution)
        and serialize against the QUEUE→RUNNING promotion; a RUNNING
        job only gets its cancel event (aborts at its next cancel
        point)."""
        with self._lock:
            self._queue = [(j, q) for (j, q) in self._queue
                           if j is not job]
            if job.cancel is not None:
                job.cancel.set()
            if job.status != "RUNNING":
                job.status = "STOPPED"
                job.stop_time = time.time()

    def _dispatch_locked(self):
        import threading
        while self._queue and self._running < self._max_concurrent():
            job, qctx = self._queue.pop(0)
            if job.status == "STOPPED":
                continue             # STOP JOB beat the dispatcher
            self._running += 1
            job.status = "RUNNING"
            job.start_time = time.time()
            threading.Thread(target=self._worker, args=(job, qctx),
                             daemon=True,
                             name=f"admin-job-{job.job_id}").start()

    def _worker(self, job: Job, qctx):
        if job.on_start is not None:
            try:
                job.on_start(job)
            except Exception:  # noqa: BLE001 — mirror is best-effort
                pass
        try:
            job.result = self._run(qctx, job.command, job.space, job)
            job.status = "FINISHED"
        except JobStopped:
            job.status = "STOPPED"
            job.result = {"stopped": True}
        except Exception as ex:  # noqa: BLE001 - job errors are recorded
            job.status = "FAILED"
            job.result = {"error": str(ex)}
        finally:
            job.stop_time = time.time()
            if job.on_done is not None:
                try:
                    job.on_done(job)
                except Exception:  # noqa: BLE001 — mirror is best-effort
                    pass
            with self._lock:
                self._running -= 1
                self._dispatch_locked()

    def wait(self, job_id: Optional[int] = None,
             timeout: float = 60.0) -> bool:
        """Block until the job (or ALL jobs) leave QUEUE/RUNNING."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                live = any(
                    (job_id is None or j.job_id == job_id)
                    and j.status in ("QUEUE", "RUNNING")
                    for j in self.jobs.values())
            if not live:
                return True
            time.sleep(0.005)
        return False

    def _run(self, qctx, command: str, space: Optional[str],
             job: Optional[Job] = None) -> Dict[str, Any]:
        token = job.cancel if job is not None else None
        if command.startswith("repartition "):
            # the part split/merge task (SURVEY §2 row 16): re-home the
            # space onto a new partition count; cancellable mid-scan
            if not space:
                raise ValueError("repartition job needs a space")
            if not hasattr(qctx.store, "repartition"):
                raise ValueError(
                    "repartition runs on the standalone store; the "
                    "cluster form needs a metad-orchestrated part-move "
                    "plan (BALANCE DATA) instead")
            n = int(command[len("repartition "):])
            moved = qctx.store.repartition(space, n, cancel=token)
            if moved < 0:
                raise JobStopped()
            return {"moved_vertices": moved, "partition_num": n}
        if command == "stats":
            if not space:
                raise ValueError("stats job needs a space")
            return qctx.store.stats(space)
        if command == "compact":
            # TTL GC — the reference's compaction-filter pass
            removed = 0
            spaces = [space] if space else sorted(
                qctx.store.catalog.spaces)
            for sp in spaces:
                if hasattr(qctx.store, "compact"):
                    removed += qctx.store.compact(sp)
            out = {"compacted": True, "expired_removed": removed}
            if getattr(qctx.store, "_engine", None) is not None:
                # durability leg: checkpoint + journal truncation (the
                # SST-compaction analog, SURVEY §2 row 10)
                out["journal_compacted_to"] = qctx.store.compact_journal()
            return out
        if command in ("balance data", "balance leader") \
                or command.startswith("balance data remove "):
            meta = getattr(qctx.store, "meta", None)
            if meta is not None:        # cluster: run the real plan
                from ..cluster.balance import balance_data, balance_leader
                if command == "balance leader":
                    return balance_leader(qctx.store, space)
                exclude = None
                if command.startswith("balance data remove "):
                    exclude = command[len("balance data remove "):].split(",")
                return balance_data(qctx.store, space, exclude=exclude)
            # standalone: one host owns every part — nothing to move
            if space:
                return {"parts": qctx.store.stats(space)["per_part_edges"]}
            return {}
        if command == "ingest":
            return {}
        if command == "flush":
            # persist in-memory state: a checkpoint + journal truncation
            # (the memtable-flush analog of the reference's FLUSH job)
            if getattr(qctx.store, "_engine", None) is not None:
                return {"journal_compacted_to":
                        qctx.store.compact_journal()}
            return {"flushed": False, "reason": "in-memory store"}
        if command.startswith("rebuild index "):
            if not space:
                raise ValueError("rebuild index job needs a space")
            name = command[len("rebuild index "):]
            return {"entries": qctx.store.rebuild_index(space, name)}
        if command.startswith("rebuild fulltext"):
            if not space:
                raise ValueError("rebuild fulltext job needs a space")
            name = command[len("rebuild fulltext"):].strip()
            names = ([name] if name else
                     [d.name for d in
                      qctx.catalog.fulltext_indexes(space)])
            return {"entries": sum(
                qctx.store.rebuild_fulltext_index(space, n)
                for n in names)}
        raise ValueError(f"unknown job `{command}'")


_snapshots: Dict[str, float] = {}


def job_manager(store) -> JobManager:
    """The store's job manager (created on demand) — store-scoped like
    the catalog, so engines and tests get isolated job state (the
    reference's JobManager lives in each cluster's metad)."""
    mgr = getattr(store, "_job_manager", None)
    if mgr is None:
        mgr = store._job_manager = JobManager()
    return mgr


def _wire_result(result) -> str:
    try:
        import json as _json
        return _json.dumps(result)
    except (TypeError, ValueError):
        return str(result)


def submit_tracked(qctx, command: str, space: Optional[str]) -> Job:
    """Run a job through the local worker pool; in cluster mode the id
    comes from metad's raft-replicated job table (cluster-visible SHOW
    JOBS from any graphd — the reference's metad JobManager) and the
    terminal status is mirrored back on completion."""
    mgr = job_manager(qctx.store)
    cluster = getattr(qctx, "cluster", None)
    if cluster is None:
        return mgr.submit(qctx, command, space)
    # the executor graphd rides in the add_job proposal itself: the row
    # is born with its executor, so STOP can always route
    jid = cluster.submit_job(command, space,
                             graphd=getattr(cluster, "my_addr", ""))

    def on_start(job: Job):
        cluster.update_job(jid, status="RUNNING")

    def on_done(job: Job):
        cluster.update_job(jid, status=job.status,
                           result=_wire_result(job.result))
    return mgr.submit(qctx, command, space, job_id=jid,
                      on_start=on_start, on_done=on_done)


def submit_job(node, qctx) -> DataSet:
    job = submit_tracked(qctx, node.args["job"], node.args.get("space"))
    return DataSet(["New Job Id"], [[job.job_id]])


def stop_job(node, qctx) -> DataSet:
    """STOP JOB <id>: a QUEUE'd job is cancelled outright; a RUNNING
    one gets its cancel event set and aborts at its next cancel point
    (repartition: between source partitions).  Stopping a FINISHED job
    is an error (reference semantics).  In cluster mode the stop routes
    to the EXECUTING graphd named in metad's job table."""
    jid = node.args["job_id"]
    mgr = job_manager(qctx.store)
    job = mgr.jobs.get(jid)
    cluster = getattr(qctx, "cluster", None)
    if job is None and cluster is not None:
        row = next((j for j in cluster.list_jobs() if j["jid"] == jid),
                   None)
        if row is None:
            raise ValueError(f"job {jid} not found")
        if row["status"] == "FINISHED":
            raise ValueError(f"job {jid} already finished")
        addr = row.get("graphd")
        status = None
        if addr:
            from .executors import _graphd_call
            try:
                status = _graphd_call(addr, "graph.stop_job", job_id=jid)
            except Exception:  # noqa: BLE001 — executor down
                status = None
        # Only write a TERMINAL status from the issuer: a reachable
        # executor's running job will mirror its own terminal state via
        # on_done (an issuer-side "RUNNING" write could land after it
        # and wedge the row non-terminal forever).  The STOPPED
        # fallback marks an executor-less/unreachable row recoverable.
        if status in (None, "STOPPED", "FAILED"):
            cluster.update_job(jid, status=status or "STOPPED")
        return DataSet(["Result"], [["Job stopped"]])
    if job is None:
        raise ValueError(f"job {jid} not found")
    if job.status == "FINISHED":
        raise ValueError(f"job {jid} already finished")
    mgr.stop(job)
    if cluster is not None and job.status != "RUNNING":
        # queued-stop never reaches a worker, so no on_done will fire —
        # the issuer owns the terminal write; a RUNNING job's abort is
        # mirrored by its own on_done
        try:
            cluster.update_job(jid, status=job.status)
        except Exception:  # noqa: BLE001
            pass
    return DataSet(["Result"], [["Job stopped"]])


def recover_job(node, qctx) -> DataSet:
    """RECOVER JOB [<id>]: re-queue FAILED/STOPPED jobs (all of them
    when no id is given); returns how many were re-queued.  In cluster
    mode the recovery list comes from metad's table, and THIS graphd
    becomes the executor of each re-run (a dead submitter's jobs are
    re-homed — the reference's job-recovery semantics)."""
    mgr = job_manager(qctx.store)
    jid = node.args.get("job_id")
    cluster = getattr(qctx, "cluster", None)
    if cluster is not None:
        table = cluster.list_jobs()
        rows = [j for j in table
                if j["status"] in ("FAILED", "STOPPED")
                and (jid is None or j["jid"] == jid)]
        if jid is not None and not rows:
            known = {j["jid"]: j for j in table}
            if jid not in known:
                raise ValueError(f"job {jid} not found")
            raise ValueError(
                f"job {jid} is {known[jid]['status']}, not recoverable")
        me = getattr(cluster, "my_addr", "")
        n = 0
        for row in rows:
            local = mgr.jobs.get(row["jid"])
            if local is not None and local.status in ("QUEUE", "RUNNING"):
                # metad says STOPPED (e.g. an issuer's fallback write
                # while this executor was unreachable) but the worker is
                # still live — re-queueing would run the job twice
                continue

            def on_start(job: Job, _jid=row["jid"]):
                cluster.update_job(_jid, status="RUNNING")

            def on_done(job: Job, _jid=row["jid"]):
                cluster.update_job(_jid, status=job.status,
                                   result=_wire_result(job.result))
            cluster.update_job(row["jid"], graphd=me, status="QUEUE")
            if local is not None:
                local.on_start = on_start
                local.on_done = on_done
                mgr.enqueue_rerun(local, qctx)
            else:
                mgr.submit(qctx, row["cmd"], row.get("space"),
                           job_id=row["jid"], on_start=on_start,
                           on_done=on_done)
            n += 1
        return DataSet(["Recovered job num"], [[n]])
    targets = [j for j in mgr.jobs.values()
               if j.status in ("FAILED", "STOPPED")
               and (jid is None or j.job_id == jid)]
    if jid is not None and not targets:
        j = mgr.jobs.get(jid)
        if j is None:
            raise ValueError(f"job {jid} not found")
        raise ValueError(f"job {jid} is {j.status}, not recoverable")
    for j in targets:
        mgr.enqueue_rerun(j, qctx)
    return DataSet(["Recovered job num"], [[len(targets)]])


def show_jobs(node, qctx) -> DataSet:
    jid = node.args.get("job_id")
    cols = ["Job Id", "Command", "Status"]
    cluster = getattr(qctx, "cluster", None)
    if cluster is not None:
        # metad's raft-replicated table: jobs are visible from EVERY
        # graphd, not just the submitter
        rows = [[j["jid"], j["cmd"], j["status"]]
                for j in cluster.list_jobs()
                if jid is None or j["jid"] == jid]
        return DataSet(cols, rows)
    rows = []
    for j in sorted(job_manager(qctx.store).jobs.values(),
                    key=lambda x: x.job_id):
        if jid is not None and j.job_id != jid:
            continue
        rows.append([j.job_id, j.command, j.status])
    return DataSet(cols, rows)


def _backup_dir() -> str:
    from ..utils.config import get_config
    return get_config().get("backup_dir")


def _backup_path(name: str) -> str:
    """backup_dir/<name>, refusing names that escape backup_dir — a
    backquoted identifier may contain ANY character, and DROP BACKUP
    rmtree's the resolved path (code-review r4: path traversal)."""
    import os
    base = _backup_dir()
    if not name or "/" in name or os.sep in name or name in (".", ".."):
        raise ValueError(f"invalid backup name `{name}'")
    path = os.path.join(base, name)
    real = os.path.realpath(path)
    if os.path.basename(real) != name or \
            os.path.dirname(real) != os.path.realpath(base):
        raise ValueError(f"invalid backup name `{name}'")
    return path


def write_backup_meta(path: str, manifest: Dict[str, Any]) -> None:
    """backup.json sidecar — ONE writer for the statement and the
    offline tool so the formats cannot drift."""
    import json
    import os
    with open(os.path.join(path, "backup.json"), "w") as f:
        json.dump({"created": time.time(),
                   "spaces": sorted(manifest["spaces"])}, f)


def iter_backups(base: str):
    """Yield (name, info) for every backup under `base`, skipping
    non-backup dirs — shared by SHOW BACKUPS and the offline tool."""
    import json
    import os
    if not os.path.isdir(base):
        return
    for name in sorted(os.listdir(base)):
        meta = os.path.join(base, name, "backup.json")
        if not os.path.isfile(meta):
            continue
        with open(meta) as f:
            yield name, json.load(f)


def create_backup(qctx, name: Optional[str]) -> DataSet:
    """CREATE BACKUP [AS <name>]: a restorable full-store checkpoint
    (catalog + every space's part states) under backup_dir — the
    statement surface of the reference's BR backup leg.  Online-safe:
    checkpoint() takes each space's lock for a point-in-time cut."""
    import os
    if not hasattr(qctx.store, "checkpoint"):
        raise ValueError("BACKUP needs a standalone store; back up a "
                         "cluster with the offline tool per storaged "
                         "(tools/backup.py), like the reference's br")
    if name is None:
        ts = int(time.time())
        seq = 0
        while True:
            name = f"BACKUP_{ts}" + (f"_{seq}" if seq else "")
            if not os.path.isdir(os.path.join(_backup_dir(), name)):
                break
            seq += 1
    path = _backup_path(name)
    if os.path.isdir(path):
        raise ValueError(f"backup `{name}' already exists")
    manifest = qctx.store.checkpoint(path)
    write_backup_meta(path, manifest)
    return DataSet(["Name"], [[name]])


def list_backups() -> DataSet:
    rows = [[name, "VALID", ",".join(info.get("spaces") or []),
             int(info.get("created", 0))]
            for name, info in iter_backups(_backup_dir())]
    return DataSet(["Name", "Status", "Spaces", "Create Time"], rows)


def drop_backup(qctx, name: str) -> DataSet:
    import os
    import shutil
    path = _backup_path(name)
    if not os.path.isdir(path):
        raise ValueError(f"backup `{name}' not found")
    shutil.rmtree(path)
    return DataSet()


def restore_backup(qctx, name: str) -> DataSet:
    import os
    path = _backup_path(name)
    if not os.path.isdir(path):
        raise ValueError(f"backup `{name}' not found")
    if not hasattr(qctx.store, "restore_backup"):
        raise ValueError("RESTORE BACKUP needs a standalone store; "
                         "restore a cluster offline with "
                         "tools/backup.py per storaged, like the "
                         "reference's br restore")
    out = qctx.store.restore_backup(path)
    return DataSet(["Restored Spaces"], [[",".join(out["spaces"])]])


def create_snapshot(qctx) -> DataSet:
    """CREATE SNAPSHOT: a durable on-disk checkpoint of every space
    (catalog + per-part state + manifest) under the snapshot_dir flag."""
    import os

    from ..utils.config import get_config
    name = f"SNAPSHOT_{int(time.time())}_{len(_snapshots)}"
    base = get_config().get("snapshot_dir")
    path = os.path.join(base, name)
    if hasattr(qctx.store, "checkpoint"):
        qctx.store.checkpoint(path)
    _snapshots[name] = time.time()
    return DataSet(["Name"], [[name]])


def drop_snapshot_dir(name: str):
    import os
    import shutil

    from ..utils.config import get_config
    path = os.path.join(get_config().get("snapshot_dir"), name)
    if os.path.isdir(path):
        shutil.rmtree(path)


def list_snapshots() -> DataSet:
    return DataSet(["Name", "Status", "Hosts"],
                   [[n, "VALID", "local"] for n in sorted(_snapshots)])


def drop_snapshot(qctx, name: str) -> DataSet:
    _snapshots.pop(name, None)
    drop_snapshot_dir(name)
    return DataSet()
