"""Job manager + snapshots — the metad JobManager analog
(reference: src/meta/processors/job [UNVERIFIED — empty mount, SURVEY §0]).

Single-process form: jobs run synchronously and record their status; the
cluster metad wraps this with background scheduling.  Job kinds mirror the
reference: stats, compact (a no-op re-pack host-side), balance data /
balance leader (meaningful in cluster mode; recorded here), ingest.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.value import DataSet

class JobStopped(Exception):
    """A task observed its cancel token (STOP JOB) and aborted."""


@dataclass
class Job:
    job_id: int
    command: str
    status: str = "QUEUE"
    start_time: float = 0.0
    stop_time: float = 0.0
    result: Optional[Dict[str, Any]] = None
    space: Optional[str] = None          # RECOVER re-runs in this space
    cancel: Any = None                   # threading.Event (task lifecycle)


class JobManager:
    def __init__(self):
        self.jobs: Dict[int, Job] = {}
        self._ids = itertools.count(1)   # per-manager: deterministic ids

    def submit(self, qctx, command: str, space: Optional[str]) -> Job:
        import threading
        job = Job(next(self._ids), command, space=space,
                  cancel=threading.Event())
        self.jobs[job.job_id] = job
        job.status = "RUNNING"
        job.start_time = time.time()
        try:
            job.result = self._run(qctx, command, space, job)
            job.status = "FINISHED"
        except JobStopped:
            job.status = "STOPPED"
            job.result = {"stopped": True}
        except Exception as ex:  # noqa: BLE001 - job errors are recorded
            job.status = "FAILED"
            job.result = {"error": str(ex)}
        job.stop_time = time.time()
        return job

    def _run(self, qctx, command: str, space: Optional[str],
             job: Optional[Job] = None) -> Dict[str, Any]:
        token = job.cancel if job is not None else None
        if command.startswith("repartition "):
            # the part split/merge task (SURVEY §2 row 16): re-home the
            # space onto a new partition count; cancellable mid-scan
            if not space:
                raise ValueError("repartition job needs a space")
            if not hasattr(qctx.store, "repartition"):
                raise ValueError(
                    "repartition runs on the standalone store; the "
                    "cluster form needs a metad-orchestrated part-move "
                    "plan (BALANCE DATA) instead")
            n = int(command[len("repartition "):])
            moved = qctx.store.repartition(space, n, cancel=token)
            if moved < 0:
                raise JobStopped()
            return {"moved_vertices": moved, "partition_num": n}
        if command == "stats":
            if not space:
                raise ValueError("stats job needs a space")
            return qctx.store.stats(space)
        if command == "compact":
            # TTL GC — the reference's compaction-filter pass
            removed = 0
            spaces = [space] if space else sorted(
                qctx.store.catalog.spaces)
            for sp in spaces:
                if hasattr(qctx.store, "compact"):
                    removed += qctx.store.compact(sp)
            out = {"compacted": True, "expired_removed": removed}
            if getattr(qctx.store, "_engine", None) is not None:
                # durability leg: checkpoint + journal truncation (the
                # SST-compaction analog, SURVEY §2 row 10)
                out["journal_compacted_to"] = qctx.store.compact_journal()
            return out
        if command in ("balance data", "balance leader") \
                or command.startswith("balance data remove "):
            meta = getattr(qctx.store, "meta", None)
            if meta is not None:        # cluster: run the real plan
                from ..cluster.balance import balance_data, balance_leader
                if command == "balance leader":
                    return balance_leader(qctx.store, space)
                exclude = None
                if command.startswith("balance data remove "):
                    exclude = command[len("balance data remove "):].split(",")
                return balance_data(qctx.store, space, exclude=exclude)
            # standalone: one host owns every part — nothing to move
            if space:
                return {"parts": qctx.store.stats(space)["per_part_edges"]}
            return {}
        if command == "ingest":
            return {}
        if command == "flush":
            # persist in-memory state: a checkpoint + journal truncation
            # (the memtable-flush analog of the reference's FLUSH job)
            if getattr(qctx.store, "_engine", None) is not None:
                return {"journal_compacted_to":
                        qctx.store.compact_journal()}
            return {"flushed": False, "reason": "in-memory store"}
        if command.startswith("rebuild index "):
            if not space:
                raise ValueError("rebuild index job needs a space")
            name = command[len("rebuild index "):]
            return {"entries": qctx.store.rebuild_index(space, name)}
        if command.startswith("rebuild fulltext"):
            if not space:
                raise ValueError("rebuild fulltext job needs a space")
            name = command[len("rebuild fulltext"):].strip()
            names = ([name] if name else
                     [d.name for d in
                      qctx.catalog.fulltext_indexes(space)])
            return {"entries": sum(
                qctx.store.rebuild_fulltext_index(space, n)
                for n in names)}
        raise ValueError(f"unknown job `{command}'")


_snapshots: Dict[str, float] = {}


def job_manager(store) -> JobManager:
    """The store's job manager (created on demand) — store-scoped like
    the catalog, so engines and tests get isolated job state (the
    reference's JobManager lives in each cluster's metad)."""
    mgr = getattr(store, "_job_manager", None)
    if mgr is None:
        mgr = store._job_manager = JobManager()
    return mgr


def submit_job(node, qctx) -> DataSet:
    job = job_manager(qctx.store).submit(qctx, node.args["job"],
                                         node.args.get("space"))
    return DataSet(["New Job Id"], [[job.job_id]])


def stop_job(node, qctx) -> DataSet:
    """STOP JOB <id>: single-process jobs run synchronously, so a live
    job can't actually be interrupted — QUEUE'd jobs are cancelled and
    anything unfinished is marked STOPPED (the reference semantics for
    an already-finished job: an error)."""
    jid = node.args["job_id"]
    job = job_manager(qctx.store).jobs.get(jid)
    if job is None:
        raise ValueError(f"job {jid} not found")
    if job.status == "FINISHED":
        raise ValueError(f"job {jid} already finished")
    if job.cancel is not None:
        job.cancel.set()         # a RUNNING task aborts at its next
        # cancel point (repartition: between source partitions)
    if job.status != "RUNNING":
        job.status = "STOPPED"
        job.stop_time = time.time()
    return DataSet(["Result"], [["Job stopped"]])


def recover_job(node, qctx) -> DataSet:
    """RECOVER JOB [<id>]: re-run FAILED/STOPPED jobs (all of them when
    no id is given); returns how many were recovered."""
    mgr = job_manager(qctx.store)
    jid = node.args.get("job_id")
    targets = [j for j in mgr.jobs.values()
               if j.status in ("FAILED", "STOPPED")
               and (jid is None or j.job_id == jid)]
    if jid is not None and not targets:
        j = mgr.jobs.get(jid)
        if j is None:
            raise ValueError(f"job {jid} not found")
        raise ValueError(f"job {jid} is {j.status}, not recoverable")
    n = 0
    for j in targets:
        j.status = "RUNNING"
        j.start_time = time.time()
        if j.cancel is not None:
            j.cancel.clear()     # the re-run gets a LIVE cancel token —
            # STOP JOB on a recovered task must still work
        try:
            j.result = mgr._run(qctx, j.command, j.space, j)
            j.status = "FINISHED"
        except JobStopped:
            j.status = "STOPPED"
            j.result = {"stopped": True}
        except Exception as ex:  # noqa: BLE001 — job errors are recorded
            j.status = "FAILED"
            j.result = {"error": str(ex)}
        j.stop_time = time.time()
        n += 1
    return DataSet(["Recovered job num"], [[n]])


def show_jobs(node, qctx) -> DataSet:
    jid = node.args.get("job_id")
    cols = ["Job Id", "Command", "Status"]
    rows = []
    for j in sorted(job_manager(qctx.store).jobs.values(),
                    key=lambda x: x.job_id):
        if jid is not None and j.job_id != jid:
            continue
        rows.append([j.job_id, j.command, j.status])
    return DataSet(cols, rows)


def _backup_dir() -> str:
    from ..utils.config import get_config
    return get_config().get("backup_dir")


def _backup_path(name: str) -> str:
    """backup_dir/<name>, refusing names that escape backup_dir — a
    backquoted identifier may contain ANY character, and DROP BACKUP
    rmtree's the resolved path (code-review r4: path traversal)."""
    import os
    base = _backup_dir()
    if not name or "/" in name or os.sep in name or name in (".", ".."):
        raise ValueError(f"invalid backup name `{name}'")
    path = os.path.join(base, name)
    real = os.path.realpath(path)
    if os.path.basename(real) != name or \
            os.path.dirname(real) != os.path.realpath(base):
        raise ValueError(f"invalid backup name `{name}'")
    return path


def write_backup_meta(path: str, manifest: Dict[str, Any]) -> None:
    """backup.json sidecar — ONE writer for the statement and the
    offline tool so the formats cannot drift."""
    import json
    import os
    with open(os.path.join(path, "backup.json"), "w") as f:
        json.dump({"created": time.time(),
                   "spaces": sorted(manifest["spaces"])}, f)


def iter_backups(base: str):
    """Yield (name, info) for every backup under `base`, skipping
    non-backup dirs — shared by SHOW BACKUPS and the offline tool."""
    import json
    import os
    if not os.path.isdir(base):
        return
    for name in sorted(os.listdir(base)):
        meta = os.path.join(base, name, "backup.json")
        if not os.path.isfile(meta):
            continue
        with open(meta) as f:
            yield name, json.load(f)


def create_backup(qctx, name: Optional[str]) -> DataSet:
    """CREATE BACKUP [AS <name>]: a restorable full-store checkpoint
    (catalog + every space's part states) under backup_dir — the
    statement surface of the reference's BR backup leg.  Online-safe:
    checkpoint() takes each space's lock for a point-in-time cut."""
    import os
    if not hasattr(qctx.store, "checkpoint"):
        raise ValueError("BACKUP needs a standalone store; back up a "
                         "cluster with the offline tool per storaged "
                         "(tools/backup.py), like the reference's br")
    if name is None:
        ts = int(time.time())
        seq = 0
        while True:
            name = f"BACKUP_{ts}" + (f"_{seq}" if seq else "")
            if not os.path.isdir(os.path.join(_backup_dir(), name)):
                break
            seq += 1
    path = _backup_path(name)
    if os.path.isdir(path):
        raise ValueError(f"backup `{name}' already exists")
    manifest = qctx.store.checkpoint(path)
    write_backup_meta(path, manifest)
    return DataSet(["Name"], [[name]])


def list_backups() -> DataSet:
    rows = [[name, "VALID", ",".join(info.get("spaces") or []),
             int(info.get("created", 0))]
            for name, info in iter_backups(_backup_dir())]
    return DataSet(["Name", "Status", "Spaces", "Create Time"], rows)


def drop_backup(qctx, name: str) -> DataSet:
    import os
    import shutil
    path = _backup_path(name)
    if not os.path.isdir(path):
        raise ValueError(f"backup `{name}' not found")
    shutil.rmtree(path)
    return DataSet()


def restore_backup(qctx, name: str) -> DataSet:
    import os
    path = _backup_path(name)
    if not os.path.isdir(path):
        raise ValueError(f"backup `{name}' not found")
    if not hasattr(qctx.store, "restore_backup"):
        raise ValueError("RESTORE BACKUP needs a standalone store; "
                         "restore a cluster offline with "
                         "tools/backup.py per storaged, like the "
                         "reference's br restore")
    out = qctx.store.restore_backup(path)
    return DataSet(["Restored Spaces"], [[",".join(out["spaces"])]])


def create_snapshot(qctx) -> DataSet:
    """CREATE SNAPSHOT: a durable on-disk checkpoint of every space
    (catalog + per-part state + manifest) under the snapshot_dir flag."""
    import os

    from ..utils.config import get_config
    name = f"SNAPSHOT_{int(time.time())}_{len(_snapshots)}"
    base = get_config().get("snapshot_dir")
    path = os.path.join(base, name)
    if hasattr(qctx.store, "checkpoint"):
        qctx.store.checkpoint(path)
    _snapshots[name] = time.time()
    return DataSet(["Name"], [[name]])


def drop_snapshot_dir(name: str):
    import os
    import shutil

    from ..utils.config import get_config
    path = os.path.join(get_config().get("snapshot_dir"), name)
    if os.path.isdir(path):
        shutil.rmtree(path)


def list_snapshots() -> DataSet:
    return DataSet(["Name", "Status", "Hosts"],
                   [[n, "VALID", "local"] for n in sorted(_snapshots)])


def drop_snapshot(qctx, name: str) -> DataSet:
    _snapshots.pop(name, None)
    drop_snapshot_dir(name)
    return DataSet()
