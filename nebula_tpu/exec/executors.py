"""Executors: one per PlanNode kind.

Analog of the reference's Executor hierarchy (reference: src/graph/executor
[UNVERIFIED — empty mount, SURVEY §0]).  Each executor is a function
``(node, qctx, ectx, space) -> DataSet`` reading its inputs from the
ExecutionContext by the node's input_vars and returning its output DataSet.

The CPU path here is the row-parity oracle; `TpuTraverse` (registered from
nebula_tpu.tpu) replaces ExpandAll chains on device.
"""
from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.expr import (AggExpr, DictContext, Expr, collect_aggregates,
                         has_aggregate, to_bool3)
from ..core.value import (NULL, DataSet, Edge, Path, Step, Tag, Vertex,
                          hashable_key, is_null, total_order_key)
from ..graphstore.schema import PropDef, PropType, SchemaError
from ..graphstore.store import GraphStore
from .context import ExecutionContext, QueryContext, ResultSet, RowContext, row_dict


class ExecError(Exception):
    pass


EXECUTORS: Dict[str, Callable] = {}


def executor(kind: str):
    def deco(fn):
        EXECUTORS[kind] = fn
        return fn
    return deco


def run_node(node, qctx: QueryContext, ectx: ExecutionContext,
             space: Optional[str]) -> DataSet:
    fn = EXECUTORS.get(node.kind)
    if fn is None:
        raise ExecError(f"no executor for plan node `{node.kind}'")
    return fn(node, qctx, ectx, space)


def _input(node, ectx: ExecutionContext, i: int = 0) -> DataSet:
    if not node.input_vars:
        return DataSet()
    return ectx.get_result(node.input_vars[i])


# ---------------------------------------------------------------------------
# control
# ---------------------------------------------------------------------------


@executor("Start")
def _start(node, qctx, ectx, space):
    return DataSet(list(node.col_names), [])


@executor("PassThrough")
def _passthrough(node, qctx, ectx, space):
    return _input(node, ectx)


@executor("Sequence")
def _sequence(node, qctx, ectx, space):
    return _input(node, ectx, 1)


@executor("SetVariable")
def _set_variable(node, qctx, ectx, space):
    ds = _input(node, ectx)
    ectx.set_result(f"${node.args['var']}", ds)
    return ds


@executor("Argument")
def _argument(node, qctx, ectx, space):
    from ..core.value import ColumnarDataSet
    src = ectx.get_result(node.args["from_var"])
    col = node.args["col"]
    i = src.col_index(col)
    if isinstance(src, ColumnarDataSet) and src._cols is not None \
            and src._cols[i].dtype != object:
        # columnar input (device results): first-occurrence distinct
        # without boxing the rows
        c = src._cols[i]
        _, idx = np.unique(c, return_index=True)
        return ColumnarDataSet([col], [c[np.sort(idx)]])
    seen, rows = set(), []
    for r in src.rows:
        k = hashable_key(r[i])
        if k not in seen:
            seen.add(k)
            rows.append([r[i]])
    return DataSet([col], rows)


# ---------------------------------------------------------------------------
# explore
# ---------------------------------------------------------------------------


def _make_edge(src_vid, other_vid, etype_name, rank, props, signed_dir, etype_id):
    from ..core.value import make_edge
    return make_edge(src_vid, other_vid, etype_name, rank, props,
                     signed_dir, etype_id)


@executor("ExpandAll")
def _expand_all(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    store: GraphStore = qctx.store
    etypes = a["edge_types"]
    etype_ids = {e: store.catalog.get_edge(sp, e).edge_type for e in etypes}
    direction = a["direction"]
    edge_filter: Optional[Expr] = a.get("edge_filter")
    limit = a.get("limit")
    carry: List[str] = a.get("carry") or []

    # resolve sources: literal vids or an input column
    src_rows: List[Tuple[List[Any], Any]] = []  # (carried values, src vid)
    if a.get("src_col") is None:
        for ve in a.get("vids") or []:
            vid = ve.eval(DictContext()) if isinstance(ve, Expr) else ve
            src_rows.append(([], vid))
    else:
        ds = _input(node, ectx)
        ci = ds.col_index(a["src_col"])
        carry_idx = [ds.col_index(c) for c in carry]
        seen = set()
        dedup = a.get("dedup_input") and not carry
        for r in ds.rows:
            vid = r[ci]
            if isinstance(vid, Vertex):
                vid = vid.vid
            if is_null(vid):
                continue
            if dedup:
                k = hashable_key(vid)
                if k in seen:
                    continue
                seen.add(k)
            src_rows.append(([r[j] for j in carry_idx], vid))

    # storage-side pushdown (SURVEY §2 row 12): an edge-only predicate
    # executes where the data is; graphd then skips the re-check.  The
    # per-src limit rides along only when the filter went too (a
    # pre-filter limit would under-produce).
    from ..cluster.pushdown import pushable
    pushed = edge_filter is not None and pushable(edge_filter, etypes)
    push_filter = edge_filter if pushed else None
    push_limit = limit if (edge_filter is None or pushed) else None

    out_cols = carry + ["_src", "_edge", "_dst"]
    rows: List[List[Any]] = []
    for carried, vid in src_rows:
        n_for_src = 0
        for (s, et, rank, other, props, sd) in store.get_neighbors(
                sp, [vid], etypes, direction,
                edge_filter=push_filter, limit_per_src=push_limit):
            e = _make_edge(s, other, et, rank, props, sd, etype_ids[et])
            if edge_filter is not None and not pushed:
                rc = RowContext(qctx, sp, {"_src": s, "_edge": e, "_dst": other,
                                           **dict(zip(carry, carried))})
                if to_bool3(edge_filter.eval(rc)) is not True:
                    continue
            rows.append(carried + [s, e, other])
            n_for_src += 1
            if limit is not None and n_for_src >= limit:
                break
    return DataSet(out_cols, rows)


@executor("ScanVertices")
def _scan_vertices(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    tag = a.get("tag")
    col = a.get("as_col") or node.col_names[0]
    seen = set()
    rows = []
    for vid, t, props in qctx.store.scan_vertices(sp, tag=tag):
        if vid in seen:
            continue
        seen.add(vid)
        v = qctx.build_vertex(sp, vid)
        if v is not None:
            rows.append([v])
    rows.sort(key=lambda r: total_order_key(r[0].vid))
    lim = a.get("limit")
    if lim is not None:
        rows = rows[:lim]       # bound planted by push_limit_down_scan
    return DataSet([col], rows)


@executor("GetVertices")
def _get_vertices(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    tags = a.get("tags") or None
    col = a.get("as_col") or node.col_names[0]
    vids: List[Any] = []
    if a.get("src_col"):
        ds = _input(node, ectx)
        ref = a["src_col"]
        if ref.startswith("$"):
            var = ref[1:].split(".")[0]
            ds = ectx.get_result(f"${var}")
            ref = ref.split(".")[1]
        ci = ds.col_index(ref)
        for r in ds.rows:
            vids.append(r[ci])
    else:
        for ve in a.get("vids") or []:
            vids.append(ve.eval(DictContext()) if isinstance(ve, Expr) else ve)
    rows = []
    seen = set()
    for vid in vids:
        if isinstance(vid, Vertex):
            vid = vid.vid
        if is_null(vid):
            continue
        k = hashable_key(vid)
        if k in seen:
            continue
        seen.add(k)
        v = qctx.build_vertex(sp, vid, tags)
        if v is not None:
            rows.append([v])
    return DataSet([col], rows)


@executor("GetEdges")
def _get_edges(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    et = a["etype"]
    etype_id = qctx.store.catalog.get_edge(sp, et).edge_type
    rows = []
    for (src, dst, rank) in a["keys"]:
        props = qctx.store.get_edge(sp, src, et, dst, rank)
        if props is not None:
            rows.append([Edge(src, dst, et, rank, props, etype_id)])
    return DataSet([node.col_names[0]], rows)


@executor("IndexScan")
def _index_scan(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    schema = a["schema"]
    filt = a.get("filter")
    if a.get("index"):
        return _index_scan_indexed(node, qctx, sp, schema, filt, a)
    rows = []
    if a["is_edge"]:
        etype_id = qctx.store.catalog.get_edge(sp, schema).edge_type
        for (src, et, rank, dst, props) in qctx.store.scan_edges(sp, schema):
            e = Edge(src, dst, et, rank, dict(props), etype_id)
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": e, "_edge": e},
                                extra_vars={schema: e})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([e])
        rows.sort(key=lambda r: total_order_key(r[0].key()))
    else:
        seen = set()
        for vid, t, props in qctx.store.scan_vertices(sp, tag=schema):
            if vid in seen:
                continue
            seen.add(vid)
            v = qctx.build_vertex(sp, vid)
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": v}, extra_vars={schema: v})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([v])
        rows.sort(key=lambda r: total_order_key(r[0].vid))
    lim = a.get("limit")
    if lim is not None:
        rows = rows[:lim]       # planted by push_limit_down_index_scan
    return DataSet([node.col_names[0]], rows)


def _index_scan_indexed(node, qctx, sp, schema, filt, a):
    """LOOKUP via secondary index: prefix/range scan → entity fetch →
    residual filter (SURVEY §2 row 15).  geo_ranges (cell-token
    intervals from covering_ranges) route to the geo index scan; the
    exact ST_ predicate stays in `filt` because the cover is a bbox
    superset of the query region."""
    if a.get("geo_ranges"):
        entities = qctx.store.index_scan_geo(sp, a["index"],
                                             a["geo_ranges"])
    else:
        entities = qctx.store.index_scan(sp, a["index"], a.get("eq") or [],
                                         a.get("range"))
    rows = []
    if a["is_edge"]:
        etype_id = qctx.store.catalog.get_edge(sp, schema).edge_type
        seen_e = set()
        for (src, rank, dst) in entities:
            # a multi-cell geo entry yields its entity once per cell
            # when the scan crosses parts or rides the generic path
            ek = (hashable_key(src), rank, hashable_key(dst))
            if ek in seen_e:
                continue
            seen_e.add(ek)
            props = qctx.store.get_edge(sp, src, schema, dst, rank)
            if props is None:
                continue
            e = Edge(src, dst, schema, rank, dict(props), etype_id)
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": e, "_edge": e},
                                extra_vars={schema: e})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([e])
        rows.sort(key=lambda r: total_order_key(r[0].key()))
    else:
        seen = set()
        for vid in entities:
            if vid in seen:
                continue
            seen.add(vid)
            v = qctx.build_vertex(sp, vid)
            if v is None:
                continue
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": v},
                                extra_vars={schema: v})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([v])
        rows.sort(key=lambda r: total_order_key(r[0].vid))
    lim = a.get("limit")
    if lim is not None:
        rows = rows[:lim]       # planted by push_limit_down_index_scan
    return DataSet([node.col_names[0]], rows)


@executor("FulltextIndexScan")
def _fulltext_index_scan(node, qctx, ectx, space):
    """LOOKUP via text predicate: inverted-index search → entity fetch →
    residual filter (reference: ES-backed LOOKUP; SURVEY §2 row 10
    Listener + row 15)."""
    a = node.args
    sp = a["space"]
    schema = a["schema"]
    filt = a.get("filter")
    entities = qctx.store.fulltext_search(sp, a["index"], a["op"],
                                          a["pattern"])
    rows = []
    if a["is_edge"]:
        etype_id = qctx.store.catalog.get_edge(sp, schema).edge_type
        for (src, rank, dst) in entities:
            props = qctx.store.get_edge(sp, src, schema, dst, rank)
            if props is None:
                continue
            e = Edge(src, dst, schema, rank, dict(props), etype_id)
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": e, "_edge": e},
                                extra_vars={schema: e})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([e])
        rows.sort(key=lambda r: total_order_key(r[0].key()))
    else:
        seen = set()
        for vid in entities:
            if vid in seen:
                continue
            seen.add(vid)
            v = qctx.build_vertex(sp, vid)
            if v is None:
                continue
            if filt is not None:
                rc = RowContext(qctx, sp, {"_matched": v},
                                extra_vars={schema: v})
                if to_bool3(filt.eval(rc)) is not True:
                    continue
            rows.append([v])
        rows.sort(key=lambda r: total_order_key(r[0].vid))
    lim = a.get("limit")
    if lim is not None:
        rows = rows[:lim]       # planted by push_limit_down_index_scan
    return DataSet([node.col_names[0]], rows)


def _traverse_device(node, qctx, ectx, ds, ci, sp, etypes, direction,
                     min_hop, max_hop, var_len, edge_filter, edge_ok,
                     out_cols):
    """MATCH Traverse on the device plane (SURVEY §2 row 23; VERDICT r1
    item 5).

    One batched device expansion to max_hop over ALL distinct sources —
    predicate applied per hop on device when it vectorizes, else frames
    are a superset re-checked by edge_ok during assembly — then a
    vectorized trail assembly over the layered HopFrames.  Rows are
    emitted in LEVEL order across all input rows (not the host DFS's
    per-row stack order); parity with the host path holds up to row
    reordering, which the unordered-MATCH contract permits (consumers
    sort or aggregate).  Returns rows, or None to take the host path
    (no runtime, flag off, store without a device snapshot surface,
    non-convergent escalation...).
    """
    rt = getattr(qctx, "tpu_runtime", None)
    if rt is None or not ds.rows or max_hop < 1:
        return None
    from ..utils.config import get_config
    if not get_config().get("tpu_match_device"):
        return None
    from ..tpu.device import TpuUnavailable
    from ..tpu.exprjit import CannotCompile, compilable
    try:
        import jax
        _rt_errors = (jax.errors.JaxRuntimeError,)
    except (ImportError, AttributeError):
        _rt_errors = ()

    store = qctx.store
    try:
        sd = store.space(sp)
        sd.dense_id
    except AttributeError:
        return None

    # distinct source vids across input rows
    srcs, seen = [], set()
    src_of_row = []
    for r in ds.rows:
        sv = r[ci]
        svid = sv.vid if isinstance(sv, Vertex) else sv
        src_of_row.append(svid)
        k = hashable_key(svid)
        if not is_null(svid) and k not in seen:
            seen.add(k)
            srcs.append(svid)

    dev_pred = edge_filter if (edge_filter is not None
                               and compilable(edge_filter, etypes)) else None
    try:
        frames, stats = rt.traverse_hops(store, sp, srcs, etypes,
                                         direction, max_hop,
                                         edge_filter=dev_pred)
    except (CannotCompile, TpuUnavailable) + _rt_errors as ex:
        qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
        return None
    qctx.last_tpu_stats = stats
    host_check = edge_filter is not None and dev_pred is None

    tracker = getattr(ectx, "tracker", None)
    if tracker is not None:
        # frames are columnar (7 int64 columns per entry); Edge objects
        # are decoded lazily during emission and charged per row below
        tracker.charge(sum(f.n for f in frames) * 64)

    # Vectorized trail assembly over the layered frames (VERDICT r2
    # item 4): per hop, ONE searchsorted join of all current path
    # endpoints against the frame's src index, then a component-wise
    # canonical-key comparison against every earlier hop for trail
    # (distinct-edge) semantics — the per-path Python DFS with set
    # copies becomes numpy batch work; Python touches only emitted rows.
    rows: List[List[Any]] = []
    in_rows = ds.rows
    n_in = len(in_rows)
    d0 = np.full(n_in, -1, np.int64)
    for i, svid in enumerate(src_of_row):
        if is_null(svid):
            continue
        if min_hop == 0:
            rows.append(list(in_rows[i])
                        + [[] if var_len else NULL, Vertex(svid)])
        d0[i] = sd.dense_id(svid)
    ridx = np.flatnonzero(d0 >= 0)
    last = d0[ridx]
    path: List[np.ndarray] = []       # per-hop frame indices, path-major
    pending = 0
    from ..tpu.runtime import join_frontier_trails, trail_distinct_keep
    for h in range(max_hop):
        if ridx.size == 0:
            break
        fr = frames[h]
        if fr.n == 0:
            break
        parent, fidx = join_frontier_trails(fr, last)
        total = fidx.size
        if total == 0:
            break
        keep = trail_distinct_keep(frames, path, parent, fr, fidx)
        if host_check and keep.any():
            # non-vectorizable predicate: frames are a superset; re-check
            # each surviving candidate against its input row on host
            cand = np.flatnonzero(keep)
            eobj = fr.decode(fidx[cand])
            rsel = ridx[parent[cand]]
            for j, kidx in enumerate(cand.tolist()):
                if not edge_ok(eobj[j], in_rows[rsel[j]]):
                    keep[kidx] = False
        sel = np.flatnonzero(keep)
        if sel.size == 0:
            break
        parent = parent[sel]
        fidx = fidx[sel]
        ridx = ridx[parent]
        last = fr.dst[fidx]
        path = [pe[parent] for pe in path] + [fidx]
        depth = h + 1
        if tracker is not None:
            pending += sel.size * 8 * (depth + 2)
            if pending > (1 << 20):
                tracker.charge(pending)
                pending = 0
        if depth >= min_hop or min_hop == 0:
            eobjs = [frames[kk].decode(path[kk]) for kk in range(depth)]
            elast = eobjs[-1]
            if tracker is not None:
                pending += ridx.size * (128 + 96 * depth)
                if pending > (1 << 20):
                    tracker.charge(pending)
                    pending = 0
            if var_len:
                for i in range(ridx.size):
                    rows.append(list(in_rows[ridx[i]])
                                + [[eo[i] for eo in eobjs],
                                   Vertex(elast[i].dst)])
            else:
                for i in range(ridx.size):
                    e = eobjs[0][i]
                    rows.append(list(in_rows[ridx[i]])
                                + [e, Vertex(e.dst)])
    if tracker is not None and pending:
        tracker.charge(pending)
    return rows


@executor("Traverse")
def _traverse(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    store = qctx.store
    etypes = a["edge_types"]
    etype_ids = {e: store.catalog.get_edge(sp, e).edge_type for e in etypes}
    direction = a["direction"]
    min_hop, max_hop = a["min_hop"], a["max_hop"]
    if max_hop < 0:
        max_hop = qctx.max_match_hops
    edge_filter = a.get("edge_filter")
    filter_alias = a.get("edge_filter_alias", "__edge__")
    ds = _input(node, ectx)
    src_col = a["src_col"]
    ci = ds.col_index(src_col)
    var_len = not (min_hop == 1 and max_hop == 1)

    out_cols = list(ds.column_names) + [a["edge_alias"], a["dst_alias"]]
    rows: List[List[Any]] = []

    def edge_ok(e: Edge, row) -> bool:
        if edge_filter is None:
            return True
        rc = RowContext(qctx, sp, row_dict(ds, row),
                        extra_vars={filter_alias: e, "__edge__": e})
        return to_bool3(edge_filter.eval(rc)) is True

    dev_rows = _traverse_device(node, qctx, ectx, ds, ci, sp, etypes,
                                direction, min_hop, max_hop, var_len,
                                edge_filter, edge_ok, out_cols)
    if dev_rows is not None:
        return DataSet(out_cols, dev_rows)

    # variable-length expansion explodes (path lists + per-path edge
    # sets); charge the memory tracker mid-loop so a runaway MATCH is
    # killed before it OOMs the process (SURVEY §2 row 5)
    tracker = getattr(ectx, "tracker", None)
    pending = 0

    # MATCH edge predicates apply per hop — push them into the storage
    # scan when they reference only the edge (SURVEY §2 row 12)
    from ..cluster.pushdown import pushable
    ef_pushed = edge_filter is not None and pushable(edge_filter, etypes)
    push_filter = edge_filter if ef_pushed else None

    if not var_len:
        # single-hop fast path: ONE storage call over the distinct
        # sources instead of one per input row — multi-clause MATCH
        # repeats sources heavily (IC5's membership clause spent 100 ms
        # in per-row get_neighbors calls; batched it is one pass)
        per_src: Dict[Any, List] = {}
        order: List[Any] = []
        for r in ds.rows:
            sv = r[ci]
            svid = sv.vid if isinstance(sv, Vertex) else sv
            if is_null(svid):
                continue
            k = hashable_key(svid)
            if k not in per_src:
                per_src[k] = []
                order.append(svid)
        for (s, et, rank, other, props, sd) in store.get_neighbors(
                sp, order, etypes, direction, edge_filter=push_filter):
            e = _make_edge(s, other, et, rank, props, sd, etype_ids[et])
            per_src[hashable_key(s)].append((e, other))
            # staging holds real Edge objects — charge DURING the build
            # so a runaway frontier is killed before it allocates, same
            # invariant as the DFS path below (SURVEY §2 row 5)
            pending += 200
            if tracker is not None and pending > (1 << 20):
                tracker.charge(pending)
                pending = 0
        eval_filter = edge_filter is not None and not ef_pushed
        for r in ds.rows:
            sv = r[ci]
            svid = sv.vid if isinstance(sv, Vertex) else sv
            if is_null(svid):
                continue
            edges = per_src.get(hashable_key(svid), ())
            if not edges:
                continue
            if eval_filter:
                # one context per ROW; only the edge slot mutates per
                # edge (a fresh RowContext + row_dict per edge dominated
                # the IC5 membership clause)
                extra = {filter_alias: None, "__edge__": None}
                rc = RowContext(qctx, sp, row_dict(ds, r),
                                extra_vars=extra)
            for (e, other) in edges:
                if eval_filter:
                    extra[filter_alias] = e
                    extra["__edge__"] = e
                    if to_bool3(edge_filter.eval(rc)) is not True:
                        continue
                rows.append(list(r) + [e, Vertex(other)])
                pending += 224
                if tracker is not None and pending > (1 << 20):
                    tracker.charge(pending)
                    pending = 0
        if tracker is not None and pending:
            tracker.charge(pending)
        return DataSet(out_cols, rows)

    for r in ds.rows:
        sv = r[ci]
        svid = sv.vid if isinstance(sv, Vertex) else sv
        if is_null(svid):
            continue
        # DFS with trail semantics (no repeated edge within one path)
        stack: List[Tuple[Any, List[Edge], set]] = [(svid, [], set())]
        if min_hop == 0:
            rows.append(list(r) + [[] if var_len else NULL, Vertex(svid)])
        while stack:
            cur, epath, eseen = stack.pop()
            depth = len(epath)
            if depth >= max_hop:
                continue
            for (s, et, rank, other, props, sd) in store.get_neighbors(
                    sp, [cur], etypes, direction,
                    edge_filter=push_filter):
                e = _make_edge(s, other, et, rank, props, sd, etype_ids[et])
                ek = e.key()
                if ek in eseen:
                    continue
                if not ef_pushed and not edge_ok(e, r):
                    continue
                npath = epath + [e]
                if min_hop <= len(npath):
                    ev = npath if var_len else npath[0]
                    rows.append(list(r) + [list(ev) if var_len else ev,
                                           Vertex(other)])
                    pending += 128 + 96 * len(npath)
                if len(npath) < max_hop:
                    stack.append((other, npath, eseen | {ek}))
                    pending += 96 * (len(npath) + len(eseen))
                if tracker is not None and pending > (1 << 20):
                    tracker.charge(pending)
                    pending = 0
    if tracker is not None and pending:
        tracker.charge(pending)
    return DataSet(out_cols, rows)


@executor("AppendVertices")
def _append_vertices(node, qctx, ectx, space):
    from ..core.expr import walk as _walk
    a = node.args
    sp = a["space"]
    ds = _input(node, ectx)
    col = a["col"]
    ci = ds.col_index(col)
    labels = a.get("labels") or []
    filt = a.get("filter")
    # a filter that reads ONLY the appended vertex has a constant
    # verdict per vid — evaluate once per unique vertex, not per row
    # (MATCH rows repeat terminal vertices heavily)
    per_vertex = False
    if filt is not None:
        refs = set()
        only_vertex_refs = True
        for x in _walk(filt):
            k = x.kind
            if k == "label":
                refs.add(x.name)
            elif k == "label_tag_prop":
                refs.add(x.var)
            elif k in ("literal", "binary", "unary", "function", "list",
                       "set", "map", "case", "subscript", "slice"):
                pass                     # composition over the leaves
            else:
                # anything that can read OTHER row state ($-.col, $var,
                # vertex/edge context, props of other aliases) — or a
                # kind this classifier doesn't model — disables the
                # per-vertex shortcut
                only_vertex_refs = False
        per_vertex = only_vertex_refs and refs <= {col}
    verdicts: Dict[Any, bool] = {}
    rows = []
    cache: Dict[Any, Optional[Vertex]] = {}
    for r in ds.rows:
        v = r[ci]
        vid = v.vid if isinstance(v, Vertex) else v
        if vid not in cache:
            cache[vid] = qctx.build_vertex(sp, vid)
        full = cache[vid]
        if full is None:
            continue
        if labels and not all(l in full.tag_names() for l in labels):
            continue
        nr = list(r)
        nr[ci] = full
        if filt is not None:
            if per_vertex:
                vd = verdicts.get(vid)
                if vd is None:
                    rc = RowContext(qctx, sp, {col: full})
                    vd = to_bool3(filt.eval(rc)) is True
                    verdicts[vid] = vd
                if not vd:
                    continue
            else:
                rc = RowContext(qctx, sp, row_dict(ds, nr))
                if to_bool3(filt.eval(rc)) is not True:
                    continue
        rows.append(nr)
    return DataSet(list(ds.column_names), rows)


@executor("BuildPath")
def _build_path(node, qctx, ectx, space):
    a = node.args
    ds = _input(node, ectx)
    n_idx = [ds.col_index(c) for c in a["nodes"]]
    e_idx = [ds.col_index(c) for c in a["edges"]]
    rows = []
    for r in ds.rows:
        src = r[n_idx[0]]
        p = Path(src if isinstance(src, Vertex) else Vertex(src))
        ok = True
        prev = p.src
        for k, ei in enumerate(e_idx):
            ev = r[ei]
            edges = ev if isinstance(ev, list) else ([] if is_null(ev) else [ev])
            for e in edges:
                nxt_vid = e.dst
                prev_vid = prev.vid if isinstance(prev, Vertex) else prev
                # e.src should equal prev for forward chaining
                if e.src != prev_vid and e.dst == prev_vid:
                    nxt_vid = e.src
                dstv = r[n_idx[k + 1]]
                dst_final = dstv.vid if isinstance(dstv, Vertex) else dstv
                nv = Vertex(nxt_vid)
                p.steps.append(Step(nv, e.name, e.ranking, e.props, e.etype))
                prev = nv
            # snap final node of this hop to the full vertex value
            dstv = r[n_idx[k + 1]]
            if isinstance(dstv, Vertex) and p.steps:
                p.steps[-1] = Step(dstv, p.steps[-1].name, p.steps[-1].ranking,
                                   p.steps[-1].props, p.steps[-1].etype)
                prev = dstv
        if ok:
            rows.append(list(r) + [p])
    return DataSet(list(ds.column_names) + [a["alias"]], rows)


# ---------------------------------------------------------------------------
# relational
# ---------------------------------------------------------------------------


@executor("Filter")
def _filter(node, qctx, ectx, space):
    ds = _input(node, ectx)
    cond = node.args["condition"]
    rows = []
    for r in ds.rows:
        rc = RowContext(qctx, space, row_dict(ds, r))
        if to_bool3(cond.eval(rc)) is True:
            rows.append(r)
    return DataSet(list(ds.column_names), rows)


@executor("Project")
def _project(node, qctx, ectx, space):
    from ..core.expr import InputProp, LabelExpr
    from ..core.value import ColumnarDataSet
    a = node.args
    ds = _input(node, ectx)
    if a.get("empty"):
        return DataSet(list(node.col_names), [])
    cols: List[Tuple[Expr, str]] = a["columns"]
    names = [n for _, n in cols]
    schema_alias = a.get("schema") if a.get("lookup_row") else None
    if isinstance(ds, ColumnarDataSet) and ds._cols is not None \
            and schema_alias is None:
        # bare column selection over a columnar input stays columnar —
        # the GO/MATCH bulk path never boxes per-row values just to
        # rename/reorder columns (RowContext would return row[name]
        # verbatim for these expression shapes)
        sel = []
        for e, _ in cols:
            if isinstance(e, (InputProp, LabelExpr)) \
                    and e.name in ds.column_names:
                sel.append(ds._cols[ds.col_index(e.name)])
            else:
                sel = None
                break
        if sel is not None:
            return ColumnarDataSet(names, sel)
    rows = []
    src_rows = ds.rows
    if not ds.column_names and not ds.rows:
        src_rows = [[]]  # constant YIELD with no input: one row
    for r in src_rows:
        rd = row_dict(ds, r)
        extra = {schema_alias: rd.get("_matched")} if schema_alias else None
        if schema_alias and a.get("is_edge"):
            # edge LOOKUP yields reference edge props as EdgeProp exprs
            # (rewritten by _rewrite_go_expr) — bind the matched edge
            # where edge-prop resolution looks for it
            rd.setdefault("_edge", rd.get("_matched"))
        rc = RowContext(qctx, space, rd, extra_vars=extra)
        rows.append([e.eval(rc) for e, _ in cols])
    return DataSet(names, rows)


@executor("VarInput")
def _var_input(node, qctx, ectx, space):
    return ectx.get_result(f"${node.args['var']}")


@executor("Unwind")
def _unwind(node, qctx, ectx, space):
    a = node.args
    ds = _input(node, ectx)
    rows = []
    source_rows = ds.rows if ds.column_names else [[]]
    for r in source_rows:
        rc = RowContext(qctx, space, row_dict(ds, r))
        v = a["expr"].eval(rc)
        items = v if isinstance(v, list) else ([] if is_null(v) else [v])
        for item in items:
            rows.append(list(r) + [item])
    return DataSet(list(ds.column_names) + [a["alias"]], rows)


@executor("Dedup")
def _dedup(node, qctx, ectx, space):
    ds = _input(node, ectx)
    seen, rows = set(), []
    for r in ds.rows:
        k = tuple(hashable_key(c) for c in r)
        if k not in seen:
            seen.add(k)
            rows.append(r)
    return DataSet(list(ds.column_names), rows)


@executor("Aggregate")
def _aggregate(node, qctx, ectx, space):
    a = node.args
    ds = _input(node, ectx)
    group_keys: List[Expr] = a.get("group_keys") or []
    cols: List[Tuple[Expr, str]] = a["columns"]
    names = [n for _, n in cols]

    # per-column aggregate structure is static — derive it ONCE, not per
    # row (has_aggregate/collect_aggregates per row dominated the whole
    # executor on wide inputs)
    col_aggs = [collect_aggregates(e) if has_aggregate(e) else None
                for e, _ in cols]

    groups: Dict[Tuple, Dict[str, Any]] = {}
    order: List[Tuple] = []
    src_rows = ds.rows
    if not ds.column_names and not src_rows:
        # constant aggregate with no input (standalone `RETURN max(5)`,
        # incl. mixed `RETURN 1 AS a, count(*) AS c` where the constant
        # becomes a derived group key): one implicit row, same contract
        # as the Project executor's constant-YIELD case — 0 rows would
        # report the empty-input aggregate identities (NULL/0/[])
        # instead of folding the value
        src_rows = [[]]
    for r in src_rows:
        rc = RowContext(qctx, space, row_dict(ds, r))
        key_vals = [k.eval(rc) for k in group_keys]
        key = tuple(hashable_key(v) for v in key_vals)
        g = groups.get(key)
        if g is None:
            g = groups[key] = {"key_vals": key_vals,
                               "agg_inputs": [[] for _ in cols]}
            order.append(key)
        for i, (e, _) in enumerate(cols):
            aggs = col_aggs[i]
            if aggs is not None:
                g["agg_inputs"][i].append(
                    [ag.eval(rc) if ag.arg is not None else 1 for ag in aggs])
            else:
                g["agg_inputs"][i].append([e.eval(rc)])

    rows = []
    if not groups and not group_keys:
        # aggregates over empty input: one row (COUNT→0, SUM→0, others NULL)
        out = []
        for e, _ in cols:
            if isinstance(e, AggExpr):
                out.append(e.apply([]))
            elif has_aggregate(e):
                out.append(_eval_with_aggs(e, [], qctx, space))
            else:
                out.append(NULL)
        return DataSet(names, [out])

    for key in order:
        g = groups[key]
        out = []
        for i, (e, _) in enumerate(cols):
            vals = g["agg_inputs"][i]
            if isinstance(e, AggExpr):
                out.append(e.apply([v[0] for v in vals]))
            elif col_aggs[i] is not None:
                out.append(_eval_with_aggs(e, vals, qctx, space))
            else:
                out.append(vals[0][0] if vals else NULL)
        rows.append(out)
    return DataSet(names, rows)


def _eval_with_aggs(e: Expr, rows_inputs: List[List[Any]], qctx, space):
    """Evaluate an expression containing AggExpr nodes by substituting each
    agg's folded value in traversal order (supports count(*)+1, avg(x)/sum(y)).

    collect_aggregates and rewrite both traverse depth-first, so the i-th
    AggExpr encountered during rewrite corresponds to folded[i]."""
    from ..core.expr import rewrite, Literal
    aggs = collect_aggregates(e)
    folded = [ag.apply([ri[i] for ri in rows_inputs])
              for i, ag in enumerate(aggs)]
    idx = [0]

    def substitute(x):
        if isinstance(x, AggExpr):
            v = folded[idx[0]]
            idx[0] += 1
            return Literal(v)
        return None

    e2 = rewrite(e, substitute)
    return e2.eval(DictContext())


@executor("Sort")
def _sort(node, qctx, ectx, space):
    a = node.args
    ds = _input(node, ectx)
    factors = a["factors"]
    # precompute all factor keys once per row; mixed asc/desc via repeated
    # stable sorts on the cached keys, last factor first
    keyed = []
    for r in ds.rows:
        rc = RowContext(qctx, space, row_dict(ds, r))
        keyed.append(([total_order_key(e.eval(rc)) for e, _ in factors], r))
    for fi in range(len(factors) - 1, -1, -1):
        asc = factors[fi][1]
        keyed.sort(key=lambda kr, _fi=fi: kr[0][_fi], reverse=not asc)
    return DataSet(list(ds.column_names), [r for _, r in keyed])


@executor("TopN")
def _topn(node, qctx, ectx, space):
    ds = _sort(node, qctx, ectx, space)
    off = node.args.get("offset", 0)
    cnt = node.args.get("count", -1)
    rows = ds.rows[off:] if cnt < 0 else ds.rows[off:off + cnt]
    return DataSet(ds.column_names, rows)


@executor("Limit")
def _limit(node, qctx, ectx, space):
    from ..core.value import ColumnarDataSet
    ds = _input(node, ectx)
    off = node.args.get("offset", 0)
    cnt = node.args.get("count", -1)
    if isinstance(ds, ColumnarDataSet) and ds._cols is not None:
        # columnar input (device GO results): slice the numpy columns —
        # LIMIT over a million-row result never boxes the dropped rows
        end = None if cnt is None or cnt < 0 else off + cnt
        return ColumnarDataSet(list(ds.column_names),
                               [c[off:end] for c in ds._cols])
    rows = ds.rows[off:] if cnt is None or cnt < 0 else ds.rows[off:off + cnt]
    return DataSet(list(ds.column_names), rows)


@executor("Sample")
def _sample(node, qctx, ectx, space):
    ds = _input(node, ectx)
    n = node.args.get("count", 0)
    rows = ds.rows if len(ds.rows) <= n else random.sample(ds.rows, n)
    return DataSet(list(ds.column_names), rows)


@executor("Union")
def _union(node, qctx, ectx, space):
    l = _input(node, ectx, 0)
    r = _input(node, ectx, 1)
    rows = list(l.rows) + list(r.rows)
    ds = DataSet(list(node.col_names) or list(l.column_names), rows)
    if node.args.get("distinct"):
        seen, out = set(), []
        for row in ds.rows:
            k = tuple(hashable_key(c) for c in row)
            if k not in seen:
                seen.add(k)
                out.append(row)
        ds.rows = out
    return ds


@executor("Intersect")
def _intersect(node, qctx, ectx, space):
    l = _input(node, ectx, 0)
    r = _input(node, ectx, 1)
    rkeys = {tuple(hashable_key(c) for c in row) for row in r.rows}
    out, seen = [], set()
    for row in l.rows:
        k = tuple(hashable_key(c) for c in row)
        if k in rkeys and k not in seen:
            seen.add(k)
            out.append(row)
    return DataSet(list(l.column_names), out)


@executor("Minus")
def _minus(node, qctx, ectx, space):
    l = _input(node, ectx, 0)
    r = _input(node, ectx, 1)
    rkeys = {tuple(hashable_key(c) for c in row) for row in r.rows}
    out, seen = [], set()
    for row in l.rows:
        k = tuple(hashable_key(c) for c in row)
        if k not in rkeys and k not in seen:
            seen.add(k)
            out.append(row)
    return DataSet(list(l.column_names), out)


def _join_common(node, qctx, ectx, left_outer: bool):
    l = _input(node, ectx, 0)
    r = _input(node, ectx, 1)
    keys = node.args["keys"]
    li = [l.col_index(k) for k in keys]
    ri = [r.col_index(k) for k in keys]
    r_extra = [j for j, c in enumerate(r.column_names) if c not in l.column_names]
    out_cols = list(l.column_names) + [r.column_names[j] for j in r_extra]
    index: Dict[Tuple, List[List[Any]]] = {}
    for row in r.rows:
        k = tuple(hashable_key(row[j]) for j in ri)
        index.setdefault(k, []).append(row)
    rows = []
    for row in l.rows:
        k = tuple(hashable_key(row[j]) for j in li)
        matches = index.get(k, [])
        if matches:
            for m in matches:
                rows.append(list(row) + [m[j] for j in r_extra])
        elif left_outer:
            rows.append(list(row) + [NULL for _ in r_extra])
    return DataSet(out_cols, rows)


@executor("HashInnerJoin")
def _inner_join(node, qctx, ectx, space):
    return _join_common(node, qctx, ectx, False)


@executor("HashLeftJoin")
def _left_join(node, qctx, ectx, space):
    return _join_common(node, qctx, ectx, True)


@executor("CrossJoin")
def _cross_join(node, qctx, ectx, space):
    l = _input(node, ectx, 0)
    r = _input(node, ectx, 1)
    out_cols = list(l.column_names) + list(r.column_names)
    rows = [list(a) + list(b) for a in l.rows for b in r.rows]
    return DataSet(out_cols, rows)


# ---------------------------------------------------------------------------
# algorithms (host reference; device versions in nebula_tpu.tpu)
# ---------------------------------------------------------------------------


def _resolve_vid_list(a, key_vids, key_ref, ectx) -> List[Any]:
    out = []
    if a.get(key_ref):
        ref = a[key_ref]
        if ref.startswith("$"):
            var = ref[1:].split(".")[0]
            ds = ectx.get_result(f"${var}")
            ref = ref.split(".")[1]
        else:
            ds = None
        if ds is None:
            return []
        ci = ds.col_index(ref)
        for r in ds.rows:
            out.append(r[ci])
    else:
        for ve in a.get(key_vids) or []:
            out.append(ve.eval(DictContext()) if isinstance(ve, Expr) else ve)
    uniq, seen = [], set()
    for v in out:
        if isinstance(v, Vertex):
            v = v.vid
        k = hashable_key(v)
        if k not in seen:
            seen.add(k)
            uniq.append(v)
    return uniq


@executor("FindPath")
def _find_path(node, qctx, ectx, space):
    from .algorithms import find_path_device, find_path_host
    rt = getattr(qctx, "tpu_runtime", None)
    a = node.args
    if rt is not None and a["kind"] == "shortest":
        from ..tpu.device import TpuUnavailable
        from ..tpu.exprjit import CannotCompile
        from ..tpu.paths import find_shortest_device
        from ..tpu.traverse import _JAX_RT_ERRORS
        try:
            return find_shortest_device(node, qctx, ectx)
        except (CannotCompile, TpuUnavailable) + _JAX_RT_ERRORS as ex:
            # device can't serve this space/config/filter; host has
            # identical semantics — record the cause, don't swallow it
            qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
    if a["kind"] in ("all", "noloop"):
        ds = find_path_device(node, qctx, ectx)
        if ds is not None:
            return ds
    return find_path_host(node, qctx, ectx)


@executor("Subgraph")
def _subgraph(node, qctx, ectx, space):
    from .algorithms import subgraph_device, subgraph_host
    ds = subgraph_device(node, qctx, ectx)
    if ds is not None:
        return ds
    return subgraph_host(node, qctx, ectx)


@executor("CallAlgo")
def _call_algo(node, qctx, ectx, space):
    """CALL algo.* (ISSUE 13): the vertex-program engine — device
    iterations with live per-iteration progress and kill/deadline
    checks BETWEEN iterations, numpy host oracle otherwise."""
    from ..algo.engine import AlgoError, run_call_algo
    try:
        return run_call_algo(node, qctx, ectx)
    except AlgoError as ex:
        raise ExecError(str(ex)) from None


# ---------------------------------------------------------------------------
# mutate
# ---------------------------------------------------------------------------


@executor("InsertVertices")
def _insert_vertices(node, qctx, ectx, space):
    a = node.args
    rows = []
    seen = set()
    for vid, per_tag in a["rows"]:
        if a["if_not_exists"]:
            # first occurrence wins WITHIN the statement too (the
            # per-row path saw its own earlier insert via get_vertex;
            # batching defers the writes, so dedupe explicitly)
            key = repr(vid)
            if key in seen or qctx.store.get_vertex(a["space"], vid):
                continue
            seen.add(key)
        for (tag, names), props in zip(a["tags"], per_tag):
            rows.append((vid, tag, props, names))
    # cluster store: the whole statement buffers per partition and
    # ships one batched rpc_write per part (group commit, ISSUE 3);
    # the standalone GraphStore keeps the per-row path
    bulk = getattr(qctx.store, "insert_vertices", None)
    if bulk is not None:
        bulk(a["space"], rows)
    else:
        for vid, tag, props, names in rows:
            qctx.store.insert_vertex(a["space"], vid, tag, props, names)
    return DataSet()


@executor("InsertEdges")
def _insert_edges(node, qctx, ectx, space):
    a = node.args
    rows = []
    seen = set()
    for src, dst, rank, props in a["rows"]:
        if a["if_not_exists"]:
            key = (repr(src), repr(dst), rank)
            if key in seen or qctx.store.get_edge(
                    a["space"], src, a["etype"], dst, rank) is not None:
                continue
            seen.add(key)
        rows.append((src, dst, rank, props))
    # cluster store: one coalesced TOSS chain per (src_pid, dst_pid)
    # pair for the whole statement instead of 3 consensus rounds/edge
    bulk = getattr(qctx.store, "insert_edges", None)
    if bulk is not None:
        bulk(a["space"], a["etype"], rows, a["prop_names"])
    else:
        for src, dst, rank, props in rows:
            qctx.store.insert_edge(a["space"], src, a["etype"], dst, rank,
                                   props, a["prop_names"])
    return DataSet()


@executor("DeleteVertices")
def _delete_vertices(node, qctx, ectx, space):
    a = node.args
    vids = _resolve_vid_list(a, "vids", "src_ref", ectx)
    for vid in vids:
        qctx.store.delete_vertex(a["space"], vid, with_edges=True)
    return DataSet()


@executor("DeleteEdges")
def _delete_edges(node, qctx, ectx, space):
    a = node.args
    keys = list(a["keys"])
    if a.get("ref") is not None:
        ds = _input(node, ectx)
        se, de, re_ = a["ref"]
        for r in ds.rows:
            rc = RowContext(qctx, a["space"], row_dict(ds, r))
            rank = re_.eval(rc) if re_ is not None else 0
            keys.append((se.eval(rc), de.eval(rc), rank))
    for (src, dst, rank) in keys:
        qctx.store.delete_edge(a["space"], src, a["etype"], dst, rank)
    return DataSet()


@executor("DeleteTags")
def _delete_tags(node, qctx, ectx, space):
    a = node.args
    vids = _resolve_vid_list(a, "vids", "src_ref", ectx)
    tags = a["tags"]
    for vid in vids:
        if not tags:
            tv = qctx.store.get_vertex(a["space"], vid)
            tags_here = list(tv.keys()) if tv else []
            qctx.store.delete_tag(a["space"], vid, tags_here)
        else:
            qctx.store.delete_tag(a["space"], vid, tags)
    return DataSet()


@executor("Update")
def _update(node, qctx, ectx, space):
    a = node.args
    sp = a["space"]
    store = qctx.store
    if a["is_edge"]:
        src, dst, rank = a["edge_key"]
        cur = store.get_edge(sp, src, a["schema"], dst, rank)
        if cur is None:
            if not a["insertable"]:
                raise ExecError("edge not found for UPDATE")
            cur = {}
    else:
        vid = a["vid"]
        tv = store.get_vertex(sp, vid)
        cur = (tv or {}).get(a["schema"])
        if cur is None:
            if not a["insertable"]:
                raise ExecError("vertex not found for UPDATE")
            cur = {}

    rc = RowContext(qctx, sp, dict(cur))
    if a.get("when") is not None:
        if to_bool3(a["when"].eval(rc)) is not True:
            return DataSet([n for _, n in a["yield"]], [])
    updates = {}
    for name, e in a["sets"]:
        updates[name] = e.eval(rc)
    if a["is_edge"]:
        src, dst, rank = a["edge_key"]
        ok = store.update_edge(sp, src, a["schema"], dst, rank, updates)
        if not ok and a["insertable"]:
            store.insert_edge(sp, src, a["schema"], dst, rank, updates)
    else:
        ok = store.update_vertex(sp, a["vid"], a["schema"], updates)
        if not ok and a["insertable"]:
            store.insert_vertex(sp, a["vid"], a["schema"], updates)
    if a["yield"]:
        newp = dict(cur)
        newp.update(updates)
        rc2 = RowContext(qctx, sp, newp)
        return DataSet([n for _, n in a["yield"]],
                       [[e.eval(rc2) for e, _ in a["yield"]]])
    return DataSet()


# ---------------------------------------------------------------------------
# DDL / admin
# ---------------------------------------------------------------------------


def _ptype_from_ast(p) -> PropDef:
    pt = PropType.parse(p.type_name)
    default = None
    has_default = False
    if p.default is not None:
        default = p.default.eval(DictContext())
        has_default = True
    return PropDef(p.name, pt, p.nullable, default, has_default, p.fixed_len)


@executor("SwitchSpace")
def _switch_space(node, qctx, ectx, space):
    return DataSet()


@executor("CreateSpace")
def _create_space(node, qctx, ectx, space):
    a = node.args
    qctx.store.create_space(a["name"], partition_num=a["partition_num"],
                            replica_factor=a["replica_factor"],
                            vid_type=a["vid_type"],
                            if_not_exists=a["if_not_exists"])
    return DataSet()


@executor("DropSpace")
def _drop_space(node, qctx, ectx, space):
    qctx.store.drop_space(node.args["name"], if_exists=node.args["if_exists"])
    return DataSet()


@executor("CreateSchema")
def _create_schema(node, qctx, ectx, space):
    a = node.args
    props = [_ptype_from_ast(p) for p in a["props"]]
    if a["is_edge"]:
        qctx.catalog.create_edge(a["space"], a["name"], props,
                                 a["if_not_exists"], a["ttl_col"], a["ttl_duration"])
    else:
        qctx.catalog.create_tag(a["space"], a["name"], props,
                                a["if_not_exists"], a["ttl_col"], a["ttl_duration"])
    return DataSet()


@executor("AlterSchema")
def _alter_schema(node, qctx, ectx, space):
    a = node.args
    cat = qctx.catalog
    get = cat.get_edge if a["is_edge"] else cat.get_tag
    schema = get(a["space"], a["name"])
    props = list(schema.latest.props)
    for d in a["drops"]:
        props = [p for p in props if p.name != d]
    for ch in a["changes"]:
        props = [p for p in props if p.name != ch.name]
        props.append(_ptype_from_ast(ch))
    for ad in a["adds"]:
        if any(p.name == ad.name for p in props):
            raise ExecError(f"prop `{ad.name}' already exists")
        props.append(_ptype_from_ast(ad))
    if a["is_edge"]:
        cat.alter_edge(a["space"], a["name"], props, a["ttl_col"], a["ttl_duration"])
    else:
        cat.alter_tag(a["space"], a["name"], props, a["ttl_col"], a["ttl_duration"])
    return DataSet()


@executor("DropSchema")
def _drop_schema(node, qctx, ectx, space):
    a = node.args
    if a["is_edge"]:
        qctx.catalog.drop_edge(a["space"], a["name"], a["if_exists"])
    else:
        qctx.catalog.drop_tag(a["space"], a["name"], a["if_exists"])
    return DataSet()


@executor("CreateIndex")
def _create_index(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.create_index(a["space"], a["index_name"], a["schema_name"],
                              a["fields"], a["is_edge"], a["if_not_exists"],
                              field_lens=a.get("field_lens"))
    return DataSet()


@executor("DropIndex")
def _drop_index(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.drop_index(a["space"], a["index_name"], a["if_exists"])
    return DataSet()


@executor("RebuildIndex")
def _rebuild_index(node, qctx, ectx, space):
    a = node.args
    from .jobs import submit_tracked
    job = submit_tracked(qctx, f"rebuild index {a['index_name']}",
                         a["space"])
    return DataSet(["New Job Id"], [[job.job_id]])


@executor("CreateSpaceAs")
def _create_space_as(node, qctx, ectx, space):
    """CREATE SPACE <new> AS <src>: clone the schema plane (options,
    tags, edges, secondary + fulltext indexes) — never the data
    (reference semantics).  Composed from the ordinary catalog ops, so
    it works identically against the standalone catalog and the
    metad-replicated CatalogProxy."""
    a = node.args
    cat = qctx.catalog
    src = a["source"]
    ine = a["if_not_exists"]
    sp = cat.get_space(src)
    # every step is individually idempotent under IF NOT EXISTS, so a
    # retry after a partial failure COMPLETES the clone instead of
    # short-circuiting on the half-created space
    qctx.store.create_space(a["name"], partition_num=sp.partition_num,
                            replica_factor=sp.replica_factor,
                            vid_type=sp.vid_type, if_not_exists=ine)
    for t in cat.tags(src):
        sv = t.latest
        cat.create_tag(a["name"], t.name, sv.props, if_not_exists=ine,
                       ttl_col=sv.ttl_col, ttl_duration=sv.ttl_duration)
    for e in cat.edges(src):
        sv = e.latest
        cat.create_edge(a["name"], e.name, sv.props, if_not_exists=ine,
                        ttl_col=sv.ttl_col, ttl_duration=sv.ttl_duration)
    for d in cat.indexes(src):
        cat.create_index(a["name"], d.name, d.schema_name, d.fields,
                         d.is_edge, if_not_exists=ine,
                         field_lens=getattr(d, "field_lens", None))
    for d in cat.fulltext_indexes(src):
        cat.create_fulltext_index(a["name"], d.name, d.schema_name,
                                  d.fields[0], d.is_edge,
                                  if_not_exists=ine)
    return DataSet()


@executor("CreateFulltextIndex")
def _create_ft_index(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.create_fulltext_index(
        a["space"], a["index_name"], a["schema_name"], a["field"],
        a["is_edge"], a["if_not_exists"])
    return DataSet()


@executor("DropFulltextIndex")
def _drop_ft_index(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.drop_fulltext_index(a["space"], a["index_name"],
                                     a["if_exists"])
    return DataSet()


@executor("RebuildFulltextIndex")
def _rebuild_ft_index(node, qctx, ectx, space):
    a = node.args
    from .jobs import submit_tracked
    cmd = "rebuild fulltext" + (f" {a['index_name']}"
                                if a.get("index_name") else "")
    job = submit_tracked(qctx, cmd, a["space"])
    return DataSet(["New Job Id"], [[job.job_id]])


@executor("AddListener")
def _add_listener(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.add_listener(a["space"], a["ltype"],
                              ",".join(a["endpoints"]))
    return DataSet()


@executor("RemoveListener")
def _remove_listener(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.remove_listener(a["space"], a["ltype"])
    return DataSet()


@executor("Describe")
def _describe(node, qctx, ectx, space):
    a = node.args
    cat = qctx.catalog
    if a["kind"] == "space":
        sp = cat.get_space(a["name"])
        return DataSet(["ID", "Name", "Partition Number", "Replica Factor",
                        "Vid Type"],
                       [[sp.space_id, sp.name, sp.partition_num,
                         sp.replica_factor, sp.vid_type]])
    space_name = a.get("space")
    if not space_name:
        raise ExecError("no space selected")
    if a["kind"] == "index":
        d = next((x for x in cat.indexes(space_name)
                  if x.name == a["name"]), None)
        if d is None:
            raise ExecError(f"index `{a['name']}' not found "
                            f"in space `{space_name}'")
        schema = (cat.get_edge if d.is_edge else cat.get_tag)(
            space_name, d.schema_name)
        lens = list(getattr(d, "field_lens", None) or [])
        lens += [0] * (len(d.fields) - len(lens))
        return DataSet(
            ["Field", "Type"],
            [[(f"{f}({ln})" if ln else f),
              (p.ptype.value if (p := schema.latest.prop(f))
               else "(dropped)")]
             for f, ln in zip(d.fields, lens)])
    get = cat.get_edge if a["kind"] == "edge" else cat.get_tag
    schema = get(space_name, a["name"])
    rows = []
    for p in schema.latest.props:
        rows.append([p.name, p.ptype.value, "YES" if p.nullable else "NO",
                     p.default if p.has_default else NULL])
    return DataSet(["Field", "Type", "Null", "Default"], rows)


@executor("Show")
def _show(node, qctx, ectx, space):
    a = node.args
    cat = qctx.catalog
    kind = a["kind"]
    if kind == "spaces":
        return DataSet(["Name"], [[n] for n in sorted(cat.spaces)])
    if kind in ("tags", "edges"):
        sp = a.get("space")
        if not sp:
            raise ExecError("no space selected")
        items = cat.tags(sp) if kind == "tags" else cat.edges(sp)
        return DataSet(["Name"], [[t.name] for t in
                                  sorted(items, key=lambda x: x.name)])
    if kind == "users":
        return DataSet(["Account"], [[n] for n in sorted(cat.users)])
    if kind == "zones":
        cluster = getattr(qctx, "cluster", None)
        zones = cluster.list_zones() if cluster is not None else {}
        return DataSet(["Name", "Host", "Port"],
                       [[z, h.rsplit(":", 1)[0], int(h.rsplit(":", 1)[1])]
                        for z in sorted(zones) for h in zones[z]])
    if kind == "roles":
        sp = a.get("extra")
        cat.get_space(sp)
        rows = [[n, u.roles[sp]] for n, u in sorted(cat.users.items())
                if sp in u.roles]
        return DataSet(["Account", "Role Type"], rows)
    if kind in ("tag_indexes", "edge_indexes"):
        sp = a.get("space")
        want_edge = kind == "edge_indexes"
        idx = [d for d in cat.indexes(sp) if d.is_edge == want_edge]
        def _cols(d):
            lens = list(getattr(d, "field_lens", None) or [])
            lens += [0] * (len(d.fields) - len(lens))
            return [f"{f}({ln})" if ln else f
                    for f, ln in zip(d.fields, lens)]
        return DataSet(["Index Name", "By Tag" if not want_edge else "By Edge",
                        "Columns"],
                       [[d.name, d.schema_name, _cols(d)] for d in idx])
    if kind == "traces":
        # newest first; the running SHOW TRACES statement's own trace is
        # still open (stored at statement end), so it never lists itself
        from ..utils.trace import trace_store
        return DataSet(
            ["Trace Id", "Name", "Spans", "Latency (us)"],
            [[t["tid"], t["name"], t["spans"], t["dur_us"]]
             for t in trace_store().list()])
    if kind == "flight_recorder":
        # newest first; like SHOW TRACES, the running statement itself
        # is not recorded yet (it records on completion)
        from ..utils.flight import flight_recorder
        return DataSet(
            ["Id", "Status", "Kind", "Latency (us)", "Operators",
             "Trace Id", "Statement"],
            [[e["id"], e["status"], e["kind"], e["latency_us"],
              e["operators"], e["trace_id"], e["stmt"]]
             for e in flight_recorder().list()])
    if kind == "stalls":
        # stall-watchdog captures (ISSUE 9) — summaries only; the full
        # thread stacks / dispatch table / kernel ledger of one capture
        # are served by GET /stalls?id=<n>
        from ..utils.workload import stall_watchdog
        rows = []
        for e in stall_watchdog().list(limit=50):
            subj = e["subject"]
            rows.append([e["id"], e["kind"],
                         subj.get("stmt") or subj.get("kernel", ""),
                         e["elapsed_s"], e["threshold_s"],
                         e["threads"]])
        return DataSet(["Id", "Kind", "Subject", "Elapsed (s)",
                        "Threshold (s)", "Threads"], rows)
    if kind == "slo":
        from ..utils.slo import slo_engine
        return DataSet(
            ["Objective", "Window", "Target", "Total", "Bad",
             "Bad Ratio", "Burn Rate"],
            [[r["objective"], r["window"], r["target"], r["total"],
              r["bad"], r["bad_ratio"], r["burn"]]
             for r in slo_engine().burn_rates()])
    if kind == "charset":
        return DataSet(
            ["Charset", "Description", "Default collation", "Maxlen"],
            [["utf8", "UTF-8 Unicode", "utf8_bin", 4]])
    if kind == "collation":
        return DataSet(["Collation", "Charset"], [["utf8_bin", "utf8"]])
    if kind == "fulltext_indexes":
        sp = a.get("space")
        if not sp:
            raise ExecError("no space selected")
        return DataSet(
            ["Name", "Schema Type", "Schema Name", "Fields"],
            [[d.name, "Edge" if d.is_edge else "Tag", d.schema_name,
              d.fields[0]]
             for d in sorted(cat.fulltext_indexes(sp),
                             key=lambda x: x.name)])
    if kind == "listener":
        sp = a.get("space")
        if not sp:
            raise ExecError("no space selected")
        lsn = getattr(qctx.store, "_ft_listener", None)
        if lsn is not None:
            lsn.drain()     # report settled lag, not a racing snapshot
        rows = []
        for ltype, ep in cat.listeners(sp):
            st = lsn.status() if lsn is not None else {"lag": 0}
            rows.append([0, ltype, ep, "ONLINE", st.get("lag", 0)])
        return DataSet(["PartId", "Type", "Host", "Status", "Lag"], rows)
    if kind == "hosts":
        role = a.get("extra")               # None | graph | storage | meta
        cluster = getattr(qctx, "cluster", None)
        if cluster is not None:
            with cluster.lock:
                pm = dict(cluster.part_map)
            rows = []
            for h in cluster.list_hosts():
                if role is not None and h.get("role") != role:
                    continue
                host, port = h["addr"].rsplit(":", 1)
                leaders = sum(1 for parts in pm.values()
                              for reps in parts if reps[:1] == [h["addr"]])
                dist = ", ".join(f"{sp}:{len(pids)}" for sp, pids in
                                 sorted(h["parts"].items())) or "No valid partition"
                # a fresh metad leader reports UNKNOWN (not OFFLINE)
                # for hosts it has not heard from yet (ISSUE 14: the
                # post-election liveness grace — never declared dead)
                status = h.get("status") or \
                    ("ONLINE" if h["alive"] else "OFFLINE")
                rows.append([host, int(port), status, leaders, dist])
            return DataSet(["Host", "Port", "Status", "Leader count",
                            "Partition distribution"], rows)
        return DataSet(["Host", "Port", "Status", "Leader count",
                        "Partition distribution"],
                       [["127.0.0.1", 0, "ONLINE", 0, "in-process"]])
    if kind in ("tag_indexes_status", "edge_indexes_status"):
        cluster = getattr(qctx, "cluster", None)
        if cluster is not None:
            # rebuild jobs live in metad's table: status is visible from
            # every graphd, not just the one that ran the rebuild
            rows = [[j["cmd"][len("rebuild index "):], j["status"]]
                    for j in cluster.list_jobs()
                    if j["cmd"].startswith("rebuild index ")]
            return DataSet(["Name", "Index Status"], rows)
        from .jobs import job_manager
        rows = [[j.command[len("rebuild index "):], j.status]
                for j in sorted(job_manager(qctx.store).jobs.values(),
                                key=lambda x: x.job_id)
                if j.command.startswith("rebuild index ")]
        return DataSet(["Name", "Index Status"], rows)
    if kind == "meta_leader":
        cluster = getattr(qctx, "cluster", None)
        if cluster is not None:
            cluster.call("meta.ready")           # refresh the hint
            addr = cluster._leader or ""
            host, _, port = addr.partition(":")
            return DataSet(["Meta Leader", "secs from last heart beat"],
                           [[f"{host}:{port}", 0]])
        return DataSet(["Meta Leader", "secs from last heart beat"],
                       [["in-process", 0]])
    if kind == "text_search_clients":
        from ..graphstore.fulltext import text_services
        return DataSet(["Host", "Port", "Connection type"],
                       [[c["host"], c["port"], c["conn"]]
                        for c in text_services(qctx.store).clients])
    if kind == "parts":
        sp = a.get("space")
        if not sp:
            raise ExecError("no space selected")
        meta = getattr(qctx.store, "meta", None)
        if meta is not None:
            # cluster: real replica sets from the meta part map
            # (replicas[0] is the placement leader)
            return DataSet(["Partition Id", "Leader", "Peers"],
                           [[pid, reps[0] if reps else "", list(reps)]
                            for pid, reps in
                            enumerate(meta.parts_of(sp))])
        sd = qctx.store.space(sp)
        return DataSet(["Partition Id", "Leader", "Peers"],
                       [[p, "127.0.0.1", ["127.0.0.1"]]
                        for p in range(sd.num_parts)])
    if kind == "stats":
        sp = a.get("space")
        if not sp:
            raise ExecError("no space selected")
        det = qctx.store.stats_detail(sp)   # ONE scan/fan-out: the
        # per-schema rows and the Space totals come from one snapshot
        rows = [["Tag", t, n] for t, n in sorted(det["tags"].items())]
        rows += [["Edge", e, n] for e, n in sorted(det["edges"].items())]
        rows += [["Space", "vertices", det["vertices"]],
                 ["Space", "edges", det["total_edges"]]]
        return DataSet(["Type", "Name", "Count"], rows)
    if kind == "sessions":
        scols = ["SessionId", "UserName", "SpaceName", "CreateTime",
                 "UpdateTime", "ActiveQueries", "GraphAddr"]
        cluster = getattr(qctx, "cluster", None)
        if a.get("extra") == "local":
            cluster = None      # SHOW LOCAL SESSIONS: this graphd only
        if cluster is not None:
            # metad's replicated table has user/space/created; the LIVE
            # half (last-used time, in-flight statement count) lives on
            # each owning graphd — one short fan-out fills it in, a
            # dead graphd's sessions just show blanks (ISSUE 9)
            sess = cluster.list_sessions()
            live = {}
            for addr in sorted({s["graphd"] for s in sess
                                if s.get("graphd")}):
                try:
                    got = _graphd_call(addr, "graph.session_live")
                except Exception:  # noqa: BLE001 — graphd down
                    continue
                for k, v in got.items():
                    live[int(k)] = v
            rows = []
            for s in sess:
                lu = live.get(s["sid"])
                # None (rendered blank), never 0: a dead graphd's
                # sessions must not read as epoch-1970 idle sessions
                rows.append([s["sid"], s["user"], s.get("space"),
                             int(s.get("created", 0)),
                             int(lu[0]) if lu else None,
                             int(lu[1]) if lu else None,
                             s["graphd"]])
            return DataSet(scols, rows)
        eng = getattr(qctx, "engine", None)
        rows = [[s.id, s.user, s.space, int(s.created),
                 int(s.last_used), len(s.queries), "in-process"]
                for s in (list(eng.sessions.values()) if eng else ())]
        return DataSet(scols, sorted(rows))
    if kind == "repairs":
        # auto-repair plans (ISSUE 14): the metad leader's raft-
        # persisted RepairPlan table — visible from every graphd, like
        # SHOW JOBS.  Standalone stores have no repair plane.
        rcols = ["Repair Id", "Space", "Part", "Dead Host", "Target",
                 "Phase", "Status", "Created", "Updated", "Error"]
        cluster = getattr(qctx, "cluster", None)
        if cluster is None:
            return DataSet(rcols, [])
        return DataSet(rcols, [
            [r["rid"], r["space"], r["part"], r["dead"], r["target"],
             r["phase"], r["status"], int(r.get("created") or 0),
             int(r.get("updated") or 0), r.get("error")]
            for r in cluster.list_repairs()])
    if kind == "snapshots":
        from .jobs import list_snapshots
        return list_snapshots()
    if kind == "backups":
        from .jobs import list_backups
        return list_backups()
    if kind == "queries":
        # live workload rows (ISSUE 9): current plan node, rows so far,
        # queue-wait vs device vs host µs, memory charged — the columns
        # come straight from the engine's WorkloadRegistry rows
        # Batch (ISSUE 15): "bid/lane" while the statement is enrolled
        # in a multi-lane device batch (forming or in flight), else ""
        qcols = ["SessionId", "ExecutionPlanId", "User", "Query",
                 "Status", "Operator", "Rows", "DurationUs", "QueueUs",
                 "DeviceUs", "HostUs", "MemoryBytes", "Consistency",
                 "Batch", "Fingerprint", "GraphAddr"]
        cluster = getattr(qctx, "cluster", None)
        if a.get("extra") == "local":
            cluster = None      # SHOW LOCAL QUERIES: this graphd only
        if cluster is not None:
            # fan out over every graphd in metad's session table — a
            # running query always belongs to a registered session, so
            # the addr set is complete; a dead graphd's queries died
            # with it (skip).  Short timeout, no retries: one hung
            # graphd must not stall an interactive statement.
            rows = []
            for addr in sorted({s["graphd"]
                                for s in cluster.list_sessions()
                                if s.get("graphd")}):
                try:
                    got = _graphd_call(addr, "graph.list_queries")
                except Exception:  # noqa: BLE001 — graphd down
                    continue
                rows.extend(list(r) + [addr] for r in got)
            return DataSet(qcols, rows)
        eng = getattr(qctx, "engine", None)
        rows = [r + ["in-process"]
                for r in (eng.list_running_queries() if eng else ())]
        return DataSet(qcols, rows)
    if kind == "statements":
        # aggregate workload digest (ISSUE 16): per-fingerprint calls,
        # triage, mergeable latency quantiles, device share and plan
        # history — the column contract lives in docs/OBSERVABILITY.md
        # §10.  Cluster-wide by default (per-graphd registries merged
        # exactly: fixed shared buckets); SHOW LOCAL STATEMENTS reads
        # only this graphd's registry.
        from ..utils.insights import (merge_statement_snapshots,
                                      statement_columns)
        stcols = ["Fingerprint", "Sample", "Calls", "Errors", "P50 Us",
                  "P95 Us", "Rows", "DeviceShare", "PlanHash",
                  "PlanChanged", "Regressed"]
        cluster = getattr(qctx, "cluster", None)
        if a.get("extra") == "local":
            cluster = None      # SHOW LOCAL STATEMENTS: this graphd only
        eng = getattr(qctx, "engine", None)
        if cluster is not None:
            # fan out over every registered graph host (idle graphds
            # still hold history, unlike the SHOW QUERIES session set);
            # a dead graphd's registry died with it (skip)
            snaps = []
            for h in cluster.list_hosts():
                if h.get("role") != "graph" or not h.get("addr"):
                    continue
                try:
                    snaps.append(_graphd_call(h["addr"],
                                              "graph.list_statements"))
                except Exception:  # noqa: BLE001 — graphd down
                    continue
            if not snaps and eng is not None:
                snaps = [eng.insights.snapshot()]
            return DataSet(stcols,
                           statement_columns(
                               merge_statement_snapshots(snaps)))
        snap = eng.insights.snapshot() if eng is not None else []
        return DataSet(stcols, statement_columns(snap))
    if kind == "tenants":
        # fleet tenant QoS view (ISSUE 20): per-tenant DWRR weight,
        # live running/queued and lifetime admission share, summed
        # across every graph host's admission controller.  SHOW LOCAL
        # TENANTS reads only this process's controller.
        tcols = ["Tenant", "Weight", "Running", "Queued", "Admitted",
                 "Share", "Graphds"]
        from ..utils.admission import admission
        cluster = getattr(qctx, "cluster", None)
        if a.get("extra") == "local":
            cluster = None
        snaps = []
        if cluster is not None:
            for h in cluster.list_hosts():
                if h.get("role") != "graph" or not h.get("addr"):
                    continue
                try:
                    snaps.append(_graphd_call(h["addr"],
                                              "graph.tenant_snapshot"))
                except Exception:  # noqa: BLE001 — graphd down
                    continue
        if not snaps:
            snaps = [admission().tenant_snapshot()]
        merged: Dict[str, list] = {}
        for snap in snaps:
            for r in snap or []:
                m = merged.get(r["tenant"])
                if m is None:
                    merged[r["tenant"]] = [r["tenant"], r["weight"],
                                           r["running"], r["queued"],
                                           r["admitted"], 0.0, 1]
                else:
                    m[1] = max(m[1], r["weight"])
                    m[2] += r["running"]
                    m[3] += r["queued"]
                    m[4] += r["admitted"]
                    m[6] += 1
        total = sum(m[4] for m in merged.values()) or 1
        rows = []
        for m in sorted(merged.values()):
            m[5] = round(m[4] / total, 4)
            rows.append(m)
        return DataSet(tcols, rows)
    if kind == "hotspots":
        # per-partition heat map (ISSUE 16): metad merges the PartHeat
        # tables ridden up on every storaged heartbeat and ranks parts
        # by load, with replica placement for balancing context
        hcols = ["Space", "Part", "Score", "ReadQps", "WriteQps",
                 "Reads", "Writes", "ReadRows", "WriteRows",
                 "ReadLatUs", "WriteLatUs", "Leader", "Replicas"]
        cluster = getattr(qctx, "cluster", None)
        if cluster is None:
            # standalone engines have no storaged partition plane
            return DataSet(hcols, [])
        rows = [[r["space"], r["part"], r["score"], r["read_qps"],
                 r["write_qps"], r["reads"], r["writes"],
                 r["read_rows"], r["write_rows"], r["read_lat_us"],
                 r["write_lat_us"], r.get("leader", ""),
                 list(r.get("replicas", []))]
                for r in cluster.call("meta.hotspots")]
        return DataSet(hcols, rows)
    if kind == "configs":
        return DataSet(["Module", "Name", "Type", "Mode", "Value"],
                       _config_rows(qctx))
    if kind == "create":
        which, name = a["extra"]
        sp = a.get("space")
        if which == "space":
            spd = cat.get_space(name)
            return DataSet(["Space", "Create Space"],
                           [[name, f"CREATE SPACE `{name}` (partition_num = "
                             f"{spd.partition_num}, replica_factor = "
                             f"{spd.replica_factor}, vid_type = {spd.vid_type})"]])
        get = cat.get_edge if which == "edge" else cat.get_tag
        schema = get(sp, name)
        sv = schema.latest
        parts = []
        for p in sv.props:
            s = f"`{p.name}` {p.ptype.value}"
            s += " NULL" if p.nullable else " NOT NULL"
            if p.has_default:
                s += f" DEFAULT {p.default!r}"
            parts.append(s)
        kw = "EDGE" if which == "edge" else "TAG"
        ddl = f"CREATE {kw} `{name}` (" + ", ".join(parts) + ")"
        if sv.ttl_col and sv.ttl_duration > 0:
            # the emitted DDL must round-trip the FULL schema — TTL
            # included (it was silently dropped before)
            ddl += (f" TTL_DURATION = {sv.ttl_duration}, "
                    f"TTL_COL = \"{sv.ttl_col}\"")
        return DataSet([kw.title(), f"Create {kw.title()}"],
                       [[name, ddl]])
    raise ExecError(f"unsupported SHOW {kind}")


def _need_cluster(qctx, what: str):
    cluster = getattr(qctx, "cluster", None)
    if cluster is None:
        raise ExecError(f"{what} needs cluster mode "
                        "(hosts/zones are a metad placement concept)")
    return cluster


def _graphd_call(addr: str, method: str, **params):
    """One short-deadline, no-retry call to a peer graphd (SHOW/KILL
    QUERY fan-out): an unreachable peer costs ≤3 s, never the RPC
    default of 30 s × 3 attempts, and the socket is closed."""
    from ..cluster.rpc import RpcClient
    cl = RpcClient.from_addr(addr, timeout=3.0, retries=0)
    try:
        return cl.call(method, **params)
    finally:
        cl.close()


@executor("AddHosts")
def _add_hosts(node, qctx, ectx, space):
    cluster = _need_cluster(qctx, "ADD HOSTS ... INTO ZONE")
    cluster.add_hosts_to_zone(node.args["hosts"], node.args["zone"])
    return DataSet()


@executor("DropHosts")
def _drop_hosts(node, qctx, ectx, space):
    from ..cluster.rpc import RpcError
    cluster = _need_cluster(qctx, "DROP HOSTS")
    try:
        cluster.drop_hosts(node.args["hosts"])
    except RpcError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("DropZone")
def _drop_zone(node, qctx, ectx, space):
    cluster = _need_cluster(qctx, "DROP ZONE")
    cluster.drop_zone(node.args["zone"])
    return DataSet()


@executor("MergeZone")
def _merge_zone(node, qctx, ectx, space):
    from ..cluster.rpc import RpcError
    cluster = _need_cluster(qctx, "MERGE ZONE")
    try:
        cluster.merge_zones(node.args["zones"], node.args["into"])
    except RpcError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("RenameZone")
def _rename_zone(node, qctx, ectx, space):
    from ..cluster.rpc import RpcError
    cluster = _need_cluster(qctx, "RENAME ZONE")
    try:
        cluster.rename_zone(node.args["old"], node.args["new"])
    except RpcError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("DivideZone")
def _divide_zone(node, qctx, ectx, space):
    from ..cluster.rpc import RpcError
    cluster = _need_cluster(qctx, "DIVIDE ZONE")
    try:
        cluster.divide_zone(node.args["zone"], node.args["parts"])
    except RpcError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("DescZone")
def _desc_zone(node, qctx, ectx, space):
    cluster = _need_cluster(qctx, "DESC ZONE")
    zones = cluster.list_zones()
    z = node.args["zone"]
    if z not in zones:
        raise ExecError(f"zone `{z}' not found")
    return DataSet(["Hosts"], [[h] for h in zones[z]])


@executor("ClearSpace")
def _clear_space(node, qctx, ectx, space):
    from ..graphstore.schema import SchemaError
    try:
        qctx.store.clear_space(node.args["name"],
                               if_exists=node.args["if_exists"])
    except SchemaError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("StopJob")
def _stop_job(node, qctx, ectx, space):
    from .jobs import stop_job
    try:
        return stop_job(node, qctx)
    except ValueError as ex:
        raise ExecError(str(ex)) from None


@executor("RecoverJob")
def _recover_job(node, qctx, ectx, space):
    from .jobs import recover_job
    try:
        return recover_job(node, qctx)
    except ValueError as ex:
        raise ExecError(str(ex)) from None


@executor("KillSession")
def _kill_session(node, qctx, ectx, space):
    sid = node.args["session_id"]
    cluster = getattr(qctx, "cluster", None)
    if cluster is not None:
        # metad's table names the OWNING graphd — the kill must reach it
        # so its live session registry drops the entry too (removing the
        # metad row alone would leave the session serving queries)
        sess = next((s for s in cluster.list_sessions()
                     if s["sid"] == sid), None)
        if sess is None:
            # Double-kill idempotency (ISSUE 20): metad keeps a bounded
            # tombstone list of removed sids.  A sid that existed and
            # was killed means the goal state already holds — quiet
            # success.  A sid that never existed still errors.
            if getattr(cluster, "session_gone", None) and \
                    cluster.session_gone(sid):
                return DataSet()
            raise ExecError(f"session {sid} not found")
        try:
            from ..cluster.rpc import RpcClient
            RpcClient.from_addr(sess["graphd"]).call(
                "graph.kill_session", session_id=sid)
        except Exception:  # noqa: BLE001 — owner down: still drop meta row
            cluster.remove_session(sid)
        return DataSet()
    eng = getattr(qctx, "engine", None)
    if eng is None or not eng.kill_session(sid):
        raise ExecError(f"session {sid} not found")
    return DataSet()


def _config_rows(qctx):
    """One row per flag + session param — the shared currency of SHOW
    CONFIGS and GET CONFIGS (they must never drift)."""
    from ..utils.config import get_config
    rows = [["graph", k, type(v).__name__, "MUTABLE", str(v)]
            for k, v in sorted(get_config().all_values().items())]
    rows += [["session", k, type(v).__name__, "MUTABLE", str(v)]
             for k, v in sorted(qctx.params.items())]
    return rows


@executor("GetConfigs")
def _get_configs(node, qctx, ectx, space):
    name = node.args.get("name")
    rows = _config_rows(qctx)
    if name is not None:
        rows = [r for r in rows if r[1] == name]
        if not rows:
            raise ExecError(f"unknown config `{name}'")
    return DataSet(["Module", "Name", "Type", "Mode", "Value"], rows)


@executor("SignInTextService")
def _sign_in_text_service(node, qctx, ectx, space):
    from ..graphstore.fulltext import text_services
    text_services(qctx.store).sign_in(
        node.args["endpoints"], node.args.get("user"),
        node.args.get("password"))
    return DataSet()


@executor("SignOutTextService")
def _sign_out_text_service(node, qctx, ectx, space):
    from ..graphstore.fulltext import text_services
    try:
        text_services(qctx.store).sign_out()
    except ValueError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("AlterSpace")
def _alter_space(node, qctx, ectx, space):
    """ALTER SPACE s ADD ZONE z: future replicas of s may also land in
    zone z's hosts.  The placement model here derives candidate hosts
    from ALL zones at CREATE/BALANCE time, so the zone set is validated
    and the statement acknowledged (a per-space zone whitelist is a
    placement-policy refinement the balancer does not yet enforce)."""
    cluster = _need_cluster(qctx, "ALTER SPACE ... ADD ZONE")
    qctx.catalog.get_space(node.args["name"])
    zones = cluster.list_zones()
    if node.args["zone"] not in zones:
        raise ExecError(f"zone `{node.args['zone']}' not found")
    return DataSet()


@executor("Download")
def _download(node, qctx, ectx, space):
    raise ExecError("DOWNLOAD HDFS needs an HDFS endpoint (none is "
                    "configured in this deployment; use the bulk "
                    "importer: nebula_tpu.tools.ldbc_import)")


@executor("DescribeUser")
def _describe_user(node, qctx, ectx, space):
    name = node.args["name"]
    u = qctx.catalog.users.get(name)
    if u is None:
        raise ExecError(f"user `{name}' not found")
    rows = [[r, sp] for sp, r in sorted(u.roles.items())]
    return DataSet(["role", "space"], rows)


@executor("CreateUser")
def _create_user(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.create_user(a["name"], a["password"], a["if_not_exists"])
    return DataSet()


@executor("DropUser")
def _drop_user(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.drop_user(a["name"], a["if_exists"])
    return DataSet()


@executor("AlterUser")
def _alter_user(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.alter_user(a["name"], a["password"])
    return DataSet()


@executor("ChangePassword")
def _change_password(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.change_password(a["name"], a["old"], a["new"])
    return DataSet()


@executor("GrantRole")
def _grant_role(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.grant_role(a["user"], a["space"], a["role"])
    return DataSet()


@executor("RevokeRole")
def _revoke_role(node, qctx, ectx, space):
    a = node.args
    qctx.catalog.revoke_role(a["user"], a["space"], a["role"])
    return DataSet()


@executor("UpdateConfigs")
def _update_configs(node, qctx, ectx, space):
    from ..core.expr import DictContext
    from ..utils.config import ConfigError, get_config
    a = node.args
    updates = {name: vexpr.eval(DictContext())
               for name, vexpr in a["updates"]}
    try:
        # atomic multi-key (ISSUE 10 satellite): every key validates
        # before any applies — UPDATE CONFIGS max_running_queries = 8,
        # admission_queue_capacity = 128 either fully lands (and the
        # admission drain listener wakes the waiting queue) or fully
        # fails; no half-applied overload tuning
        get_config().set_dynamic_many(updates)
    except ConfigError as ex:
        raise ExecError(str(ex)) from None
    return DataSet()


@executor("SubmitJob")
def _submit_job(node, qctx, ectx, space):
    from .jobs import submit_job
    return submit_job(node, qctx)


@executor("ShowJobs")
def _show_jobs(node, qctx, ectx, space):
    from .jobs import show_jobs
    return show_jobs(node, qctx)


@executor("CreateSnapshot")
def _create_snapshot(node, qctx, ectx, space):
    from .jobs import create_snapshot
    return create_snapshot(qctx)


@executor("DropSnapshot")
def _drop_snapshot(node, qctx, ectx, space):
    from .jobs import drop_snapshot
    return drop_snapshot(qctx, node.args["name"])


@executor("CreateBackup")
def _create_backup(node, qctx, ectx, space):
    from .jobs import create_backup
    return create_backup(qctx, node.args.get("name"))


@executor("DropBackup")
def _drop_backup(node, qctx, ectx, space):
    from .jobs import drop_backup
    return drop_backup(qctx, node.args["name"])


@executor("RestoreBackup")
def _restore_backup(node, qctx, ectx, space):
    from .jobs import restore_backup
    return restore_backup(qctx, node.args["name"])


@executor("KillQuery")
def _kill_query(node, qctx, ectx, space):
    """KILL QUERY (session=sid, plan=qid): set the running query's kill
    event — its scheduler aborts before the next plan node.  In cluster
    mode the kill must reach the OWNING graphd (the session's engine
    registry lives there), routed via metad's session table."""
    eng = getattr(qctx, "engine", None)
    sid = node.args.get("session_id")
    qid = node.args.get("plan_id")
    cluster = getattr(qctx, "cluster", None)
    if cluster is not None:
        sessions = cluster.list_sessions()
        if sid is not None:
            addrs = [s["graphd"] for s in sessions if s["sid"] == sid]
            if not addrs:
                raise ExecError(f"session {sid} not found")
        else:
            addrs = sorted({s["graphd"] for s in sessions
                            if s.get("graphd")})
        hit = False
        owner_dead = False
        for addr in addrs:
            try:
                hit |= bool(_graphd_call(addr, "graph.kill_query",
                                         session_id=sid, plan_id=qid))
            except Exception:  # noqa: BLE001 — owner down: nothing runs
                owner_dead = True
                continue
        if not hit and owner_dead:
            # the race KILL exists to win, closed idempotently
            # (ISSUE 20): the owning graphd died between the session
            # lookup and the kill — its queries died with it, so the
            # kill's goal state already holds.  Quiet success, never
            # "no running query matches" for a provably-dead victim.
            from ..utils.stats import stats
            stats().inc("kill_owner_dead")
            return DataSet()
        if not hit and (sid is not None or qid is not None):
            raise ExecError(f"no running query matches "
                            f"(session={sid}, plan={qid})")
        return DataSet()
    if eng is None:
        return DataSet()
    if not eng.kill_running(sid, qid) and (sid is not None
                                           or qid is not None):
        raise ExecError(f"no running query matches "
                        f"(session={sid}, plan={qid})")
    return DataSet()


@executor("Explain")
def _explain(node, qctx, ectx, space):
    # handled by the engine (doesn't execute deps for plain EXPLAIN)
    return DataSet(["plan"], [[node.dep().describe()]])
