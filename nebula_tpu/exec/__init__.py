"""Execution layer: executors, scheduler, engine, host algorithms."""
from .context import (ExecutionContext, QueryContext, ResultSet, RowContext,
                      row_dict)
from .engine import QueryEngine, Session, quick_engine
from .executors import EXECUTORS, ExecError, executor, run_node
from .scheduler import ProfileStats, Scheduler
