"""PermissionManager: role-gated statement admission.

The reference checks every sentence against the session's role before
validation (PermissionManager::canReadSpace/canWriteSchema/...;
reference: src/graph/service/PermissionManager.cpp [UNVERIFIED — empty
mount, SURVEY §2 row 26]).  Same lattice here:

    GOD > ADMIN > DBA > USER > GUEST

GOD is global (the root account); the others are per-space grants.
Checks run only when the `enable_authorize` flag is on, so open
deployments (the default, matching the reference's shipped config)
pay nothing.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..graphstore.schema import ROLE_RANK
from ..query import ast as A

# (level, scope): scope "global" ignores the space; "space" checks the
# session's (or statement's) target space; "self_or_god" is CHANGE
# PASSWORD's own-account carve-out.
_GLOBAL_GOD = (
    A.CreateSpaceSentence, A.CreateSpaceAsSentence, A.DropSpaceSentence, A.CreateUserSentence,
    A.DropUserSentence, A.AlterUserSentence, A.CreateSnapshotSentence,
    A.DropSnapshotSentence, A.CreateBackupSentence, A.DropBackupSentence,
    A.RestoreBackupSentence, A.UpdateConfigsSentence,
    A.AddHostsSentence, A.DropZoneSentence,
    A.DropHostsSentence, A.MergeZoneSentence, A.RenameZoneSentence,
    A.DivideZoneSentence,
    A.ClearSpaceSentence, A.KillSessionSentence, A.StopJobSentence,
    A.RecoverJobSentence, A.SignInTextServiceSentence,
    A.SignOutTextServiceSentence, A.DescribeUserSentence,
    A.AlterSpaceSentence, A.DownloadSentence, A.IngestSentence)
_SPACE_ADMIN = (A.GrantRoleSentence, A.RevokeRoleSentence)
_SPACE_DBA = (
    A.CreateSchemaSentence, A.AlterSchemaSentence, A.DropSchemaSentence,
    A.CreateIndexSentence, A.DropIndexSentence, A.RebuildIndexSentence,
    A.CreateFulltextIndexSentence, A.DropFulltextIndexSentence,
    A.RebuildFulltextIndexSentence, A.AddListenerSentence,
    A.RemoveListenerSentence, A.SubmitJobSentence)
_SPACE_WRITE = (
    A.InsertVerticesSentence, A.InsertEdgesSentence,
    A.DeleteVerticesSentence, A.DeleteEdgesSentence, A.DeleteTagsSentence,
    A.UpdateSentence)


def required(stmt: A.Sentence) -> Tuple[str, str]:
    """-> (min_role, scope) for one sentence."""
    if isinstance(stmt, _GLOBAL_GOD):
        return "GOD", "global"
    if isinstance(stmt, A.KillQuerySentence):
        # killing queries crosses sessions; only GOD may (ownership
        # carve-outs would need the target session's user at admission
        # time, which the reference also resolves GOD-first)
        return "GOD", "global"
    if isinstance(stmt, A.ShowSentence) and stmt.kind == "users":
        return "GOD", "global"
    if isinstance(stmt, A.ShowSentence) and stmt.kind == "roles":
        return "ADMIN", "stmt_space"       # target space is stmt.extra
    if isinstance(stmt, A.ChangePasswordSentence):
        return "GUEST", "self_or_god"
    if isinstance(stmt, _SPACE_ADMIN):
        return "ADMIN", "stmt_space"
    if isinstance(stmt, _SPACE_DBA):
        return "DBA", "space"
    if isinstance(stmt, _SPACE_WRITE):
        return "USER", "space"
    # reads, USE, SHOW, YIELD, EXPLAIN-wrapped handled by caller
    return "GUEST", "space"


def check(stmt: A.Sentence, user: str, catalog,
          current_space: Optional[str]) -> Optional[str]:
    """None if allowed, else a denial message.  Recurses through the
    composition sentences so every leaf is vetted."""
    if isinstance(stmt, A.SeqSentence):
        for sub in stmt.stmts:
            msg = check(sub, user, catalog, current_space)
            if msg:
                return msg
        return None
    if isinstance(stmt, (A.PipedSentence, A.SetOpSentence)):
        return (check(stmt.left, user, catalog, current_space)
                or check(stmt.right, user, catalog, current_space))
    if isinstance(stmt, A.ExplainSentence):
        return check(stmt.stmt, user, catalog, current_space)
    if isinstance(stmt, A.AssignSentence):
        return check(stmt.stmt, user, catalog, current_space)

    role = catalog.role_of(user, None)          # GOD short-circuit
    if role == "GOD":
        return None

    level, scope = required(stmt)
    if scope == "global":
        return f"`{user}' needs the GOD role for this statement"
    if scope == "self_or_god":
        if stmt.name == user:
            return None
        return f"only GOD may change another account's password"

    space = current_space
    if scope == "stmt_space":
        space = stmt.extra if isinstance(stmt, A.ShowSentence) else stmt.space
    if isinstance(stmt, A.UseSentence):
        space = stmt.space
    if space is None:
        # space-scoped statement with no space chosen: let the engine
        # produce its usual "no space selected" semantic error
        return None
    have = catalog.role_of(user, space)
    if have is None:
        return (f"`{user}' has no role on space `{space}' "
                f"(statement needs {level})")
    if ROLE_RANK[have] < ROLE_RANK[level]:
        return (f"`{user}' holds {have} on `{space}' but the statement "
                f"needs {level}")
    return None
