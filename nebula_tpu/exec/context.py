"""Query/Execution contexts + row evaluation contexts.

Analog of the reference's QueryContext / ExecutionContext / Iterator
hierarchy (reference: src/graph/context [UNVERIFIED — empty mount,
SURVEY §0]).  Results are named, versioned DataSets; row contexts adapt a
row of a given shape (GO row, MATCH row, FETCH row) to the ExprContext
protocol.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..core.expr import ExprContext, get_attribute
from ..core.value import (NULL, NULL_BAD_TYPE, NULL_UNKNOWN_PROP, DataSet,
                          Edge, Tag, Vertex, is_null)
from ..graphstore.store import GraphStore


class QueryContext:
    """Per-engine context: store + catalog access, limits, metrics."""

    def __init__(self, store: GraphStore, params: Optional[Dict[str, Any]] = None):
        self.store = store
        self.params = params or {}
        from ..utils.config import get_config
        self.max_match_hops = int(self.params.get(
            "max_match_hops", get_config().get("max_match_hops")))
        self.tpu_runtime = None     # set by nebula_tpu.tpu when pinned
        # write epoch (ISSUE 11): bumped once per successful mutating
        # statement this engine executed — the result cache's data-
        # freshness key half (the catalog version covers DDL).  Local
        # by design: it is what lets cached hot reads keep answering
        # while storage is unreachable, at the documented cost that
        # writes issued through a DIFFERENT coordinator are invisible
        # to it (docs/ROBUSTNESS.md §8).  Bump through
        # bump_write_epoch(): a racy `+= 1` from concurrent statement
        # threads could move the epoch BACKWARD and re-expose a stale
        # cached result.
        self.write_epoch = 0
        self._epoch_mu = threading.Lock()
        # per-thread device-plane breadcrumbs: graphd serves concurrent
        # sessions through ONE engine/qctx, so a shared slot would
        # cross-attribute PROFILE stats between queries
        self._tls = threading.local()

    @property
    def last_tpu_stats(self):
        return getattr(self._tls, "tpu_stats", None)

    @last_tpu_stats.setter
    def last_tpu_stats(self, v):
        self._tls.tpu_stats = v

    @property
    def last_tpu_fallback(self):
        return getattr(self._tls, "tpu_fallback", None)

    @last_tpu_fallback.setter
    def last_tpu_fallback(self, v):
        self._tls.tpu_fallback = v

    def bump_write_epoch(self) -> int:
        with self._epoch_mu:
            self.write_epoch += 1
            return self.write_epoch

    @property
    def catalog(self):
        return self.store.catalog

    def _space_has_ttl(self, space: str) -> bool:
        """Cached per catalog version: does ANY tag in the space carry a
        TTL (which makes vertices time-variant)?"""
        memo = getattr(self, "_ttl_memo", None)
        if memo is None:
            memo = self._ttl_memo = {}
        ver = getattr(self.catalog, "version", None)
        hit = memo.get(space)
        if hit is not None and hit[0] == ver:
            return hit[1]
        try:
            has = any(t.latest.ttl_col and t.latest.ttl_duration > 0
                      for t in self.catalog.tags(space))
        except Exception:  # noqa: BLE001 — no such space yet
            has = True      # unknown: be conservative, skip caching
        memo[space] = (ver, has)
        return has

    def build_vertex(self, space: str, vid: Any,
                     tags: Optional[List[str]] = None) -> Optional[Vertex]:
        # epoch-keyed memo: a Vertex is immutable for a given space
        # epoch (every write bumps it), and MATCH/GO pipelines rebuild
        # the same vertices once per row — across rows AND statements
        # the cache hit is exact, never stale
        cache = key = None
        from ..graphstore.store import GraphStore
        # Local stores only: the cluster _SpaceView's epoch property is
        # a part_stats RPC fan-out, far costlier than the build it
        # would save (and its CatalogProxy makes the TTL probe remote).
        if tags is None and isinstance(self.store, GraphStore):
            # TTL rows go invisible by WALL CLOCK without an epoch bump —
            # a TTL'd space must rebuild every time.
            if not self._space_has_ttl(space):
                try:
                    ep = self.store.space(space).epoch
                except Exception:  # noqa: BLE001 — space raced away
                    ep = None
                if ep is not None:
                    cache = getattr(self, "_vx_cache", None)
                    if cache is None:
                        cache = self._vx_cache = {}
                    # catalog.version covers DDL (ALTER/DROP TAG change
                    # what fill_row produces without touching the epoch)
                    key = (space, ep,
                           getattr(self.catalog, "version", 0), vid)
                    hit = cache.get(key)
                    if hit is not None:
                        return hit if hit is not False else None

        def memo(val):
            if cache is not None:
                if len(cache) > 200_000:
                    cache.clear()
                cache[key] = val
            return val

        tv = self.store.get_vertex(space, vid)
        if tv is None:
            memo(False)
            return None
        out = []
        for t, props in sorted(tv.items()):
            if tags and t not in tags:
                continue
            out.append(Tag(t, props))
        if tags and not out:
            return None
        return memo(Vertex(vid, out))


class ExecutionContext:
    """var name → list of DataSet versions (latest last).

    Carries the query's MemoryTracker: every stored result charges the
    budget, and exploding executors (variable-length Traverse, path
    search) charge mid-loop so they die before allocating, not after.
    """

    def __init__(self, tracker=None):
        self.results: Dict[str, List[DataSet]] = {}
        self.values: Dict[str, Any] = {}
        if tracker is None:
            from ..utils.memtracker import MemoryTracker
            tracker = MemoryTracker()
        self.tracker = tracker
        # deterministic per-statement work counts (edges traversed, RPC
        # calls, wire bytes, device dispatches...) — the scheduler
        # installs this as the thread's counting target around every
        # executor run, so RPC/runtime layers attribute to the right
        # statement even on pool threads (docs/OBSERVABILITY.md)
        from ..utils.stats import WorkCounters
        self.work = WorkCounters()
        # the statement's live workload-registry row (ISSUE 9), or None
        # when the plane is disabled / the context is internal — the
        # scheduler updates it per plan node, the device runtime adds
        # queue/dispatch time through the use_live() thread-local
        self.live = None

    def set_result(self, var: str, ds: DataSet):
        if self.tracker is not None and ds is not None:
            from ..core.value import ColumnarDataSet
            if isinstance(ds, ColumnarDataSet) and ds._cols is not None:
                # charge from the numpy buffers: touching .rows here
                # would materialize per-row Python lists for EVERY
                # columnar result (device GO results, fused MATCH
                # pipelines) — the exact cost the lazy result boundary
                # exists to avoid
                from ..utils.memtracker import approx_columnar_bytes
                self.tracker.charge(approx_columnar_bytes(ds._cols))
            else:
                self.tracker.charge_rows(ds.rows)
        self.results.setdefault(var, []).append(ds)

    def get_result(self, var: str) -> DataSet:
        lst = self.results.get(var)
        if not lst:
            return DataSet()
        return lst[-1]

    def has(self, var: str) -> bool:
        return var in self.results


class RowContext(ExprContext):
    """Adapts one result row to expression evaluation.

    row: dict col_name → value.  Conventions:
      _src/_edge/_dst cols (GO rows) enable $^ / edge / $$ resolution with
      vertex props looked up lazily from the store.
    """

    __slots__ = ("qctx", "space", "row", "extra_vars")

    def __init__(self, qctx: Optional[QueryContext], space: Optional[str],
                 row: Dict[str, Any], extra_vars: Optional[Dict[str, Any]] = None):
        self.qctx = qctx
        self.space = space
        self.row = row
        self.extra_vars = extra_vars or {}

    def get_input_prop(self, name):
        if name in self.row:
            return self.row[name]
        return NULL_UNKNOWN_PROP

    def get_var(self, name):
        if name in self.row:
            return self.row[name]
        if name in self.extra_vars:
            return self.extra_vars[name]
        return NULL_UNKNOWN_PROP

    def get_var_prop(self, var, name):
        v = self.get_var(var)
        if not is_null(v):
            return get_attribute(v, name)
        return NULL_UNKNOWN_PROP

    def _vertex_props(self, vid, tag):
        if self.qctx is None or self.space is None or vid is None:
            return {}
        tv = self.qctx.store.get_vertex(self.space, vid)
        if tv is None:
            return {}
        return tv.get(tag, {})

    def get_src_prop(self, tag, name):
        src = self.row.get("_src")
        if isinstance(src, Vertex):
            return src.prop(tag, name)
        props = self._vertex_props(src, tag)
        return props.get(name, NULL_UNKNOWN_PROP)

    def get_dst_prop(self, tag, name):
        dst = self.row.get("_dst")
        if isinstance(dst, Vertex):
            return dst.prop(tag, name)
        props = self._vertex_props(dst, tag)
        return props.get(name, NULL_UNKNOWN_PROP)

    def get_edge_prop(self, edge, name):
        e = self.row.get("_edge")
        if not isinstance(e, Edge):
            # FETCH PROP ON <edge> rows carry the edge in `edges_`
            # (reference: YIELD knows.since over fetched edges)
            e2 = self.row.get("edges_")
            if isinstance(e2, Edge) and (edge is None or e2.name == edge):
                e = e2
        if isinstance(e, Edge):
            if name == "_src":
                return e.src if e.etype >= 0 else e.dst
            if name == "_dst":
                return e.dst if e.etype >= 0 else e.src
            if name == "_rank":
                return e.ranking
            if name == "_type":
                return e.name
            return e.props.get(name, NULL_UNKNOWN_PROP)
        return NULL_UNKNOWN_PROP

    def get_vertex(self, which=""):
        if which == "$$":
            dst = self.row.get("_dst")
            if isinstance(dst, Vertex):
                return dst
            if dst is not None and self.qctx is not None and self.space:
                v = self.qctx.build_vertex(self.space, dst)
                return v if v is not None else Vertex(dst)
            return NULL_BAD_TYPE
        if which in ("$^", ""):
            src = self.row.get("_src")
            if isinstance(src, Vertex):
                return src
            if src is not None and self.qctx is not None and self.space:
                v = self.qctx.build_vertex(self.space, src)
                return v if v is not None else Vertex(src)
        # FETCH rows: a single vertex value column
        v = self.row.get("vertices_")
        if isinstance(v, Vertex):
            return v
        v = self.row.get("_matched")
        if isinstance(v, Vertex):
            return v
        return NULL_BAD_TYPE

    def get_edge(self):
        e = self.row.get("_edge")
        if isinstance(e, Edge):
            return e
        e = self.row.get("edges_")
        if isinstance(e, Edge):
            return e
        e = self.row.get("_matched")
        if isinstance(e, Edge):
            return e
        return NULL_BAD_TYPE


def row_dict(ds: DataSet, row: List[Any]) -> Dict[str, Any]:
    return dict(zip(ds.column_names, row))


class ResultSet:
    """What a statement returns to the client."""

    __slots__ = ("data", "space", "latency_us", "plan_desc", "error",
                 "comment", "retry_after_ms")

    def __init__(self, data: Optional[DataSet] = None, space: Optional[str] = None,
                 latency_us: int = 0, plan_desc: Optional[str] = None,
                 error: Optional[str] = None, comment: str = ""):
        self.data = data if data is not None else DataSet()
        self.space = space
        self.latency_us = latency_us
        self.plan_desc = plan_desc
        self.error = error
        self.comment = comment
        # structured overload surface (ISSUE 10): set by GraphClient
        # when an E_OVERLOAD error carries a retry-after hint the
        # caller may honor (None for every other outcome)
        self.retry_after_ms: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.error is None

    def __repr__(self):
        if self.error:
            return f"ERROR: {self.error}"
        return repr(self.data)
