"""QueryEngine + Session: the statement lifecycle.

Analog of the reference's QueryInstance (parse → validate → plan →
optimize → schedule → respond; reference: src/graph/service
[UNVERIFIED — empty mount, SURVEY §0]), in-process form.  The cluster
graphd (nebula_tpu.cluster.graph) wraps this with auth/RPC/session
registry.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..core.value import DataSet
from ..graphstore.store import GraphStore
from ..query import ast as A
from ..query.optimizer import optimize
from ..query.parser import ParseError, parse
from ..query.planner import PlannerContext, QueryError, plan_statement
from .context import ExecutionContext, QueryContext, ResultSet
from .scheduler import ProfileStats, Scheduler

_session_ids = itertools.count(1)
_query_ids = itertools.count(1)

from ..utils.config import define_flag as _define_flag

_define_flag("plan_cache_size", 128,
             "parsed-plan LRU entries per engine (0 disables); keyed by "
             "(statement text, space, schema epoch) — DDL bumps the "
             "epoch, so stale plans can never hit")
_define_flag("slow_log_capacity", 256,
             "slow-log entries retained per engine (ring buffer; the "
             "old unbounded list leaked one dict per slow query for "
             "the life of the process)")
_define_flag("result_cache_size", 0,
             "result-cache LRU entries per engine (0 = disabled, the "
             "default — byte-identical to the pre-cache engine); "
             "read-only statements are keyed like the plan cache PLUS "
             "the engine's write epoch, so any DDL or mutating "
             "statement through this engine structurally invalidates "
             "every cached result.  Hot repeated reads then serve "
             "from graphd memory — surviving even total storage "
             "unavailability within an epoch")
_define_flag("result_cache_strict_epoch", False,
             "leader-consistency cached reads pull metad's merged "
             "cluster epoch table at admission (one RPC) before the "
             "cache key is formed — closes even the heartbeat window "
             "for cross-coordinator invalidation (ISSUE 20); weaker "
             "consistency levels keep the bounded heartbeat window")

# read-only statement kinds whose plans are reusable verbatim: planning
# depends only on (text, space, catalog) for these.  DML/DDL/admin
# statements are cheap to plan and carry side-effect nodes — never
# cached.
_CACHEABLE_KINDS = frozenset({
    "Go", "Match", "Lookup", "FetchVertices", "FetchEdges", "Yield",
    "FindPath", "GetSubgraph", "GroupBy", "Unwind"})

# statement kinds that can NOT change graph data: they never bump the
# engine's write epoch (ISSUE 11 result cache).  Everything else —
# DML, DDL, jobs, balance, restore — bumps it once per successful
# statement; over-bumping is always safe (a lost cache hit, never a
# stale one), so the set is deliberately small and explicit.
_NON_MUTATING_KINDS = _CACHEABLE_KINDS | frozenset({
    "Use", "Explain", "Describe", "DescribeUser", "DescZone",
    "GetConfigs", "OrderBy", "Limit", "Sample",
    # CALL algo.* reads the graph; it is deliberately NOT result/plan
    # cacheable (long-running, parameterized) but must not bump the
    # write epoch either (ISSUE 13)
    "CallAlgo"})


def _bumps_write_epoch(kind: str) -> bool:
    return kind not in _NON_MUTATING_KINDS \
        and not kind.startswith(("Show", "Kill"))


class PlanCache:
    """LRU of (statement text, space, schema epoch, device flag) →
    (parsed stmt, optimized plan).  Plans are reusable because nothing
    mutates PlanNodes after optimize() (executors read args; all
    per-run state lives in the ExecutionContext), and the schema epoch
    in the key makes DDL invalidation automatic — ALTER/CREATE TAG or
    index DDL bumps the catalog version, so every cached plan built
    against the old schema simply stops matching and ages out of the
    LRU.  `plan_cache_hits` / `plan_cache_misses` counters and the
    `plan_cache_entries` gauge land in /metrics (docs/OBSERVABILITY.md).
    """

    def __init__(self):
        self._map: "OrderedDict[Tuple, Tuple[Any, Any]]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def capacity() -> int:
        from ..utils.config import get_config
        try:
            return int(get_config().get("plan_cache_size"))
        except Exception:  # noqa: BLE001 — config not initialized
            return 0

    def get(self, key: Tuple):
        from ..utils.stats import stats
        with self._lock:
            ent = self._map.get(key)
            if ent is not None:
                self._map.move_to_end(key)
        if ent is not None:
            stats().inc("plan_cache_hits")
        return ent

    def put(self, key: Tuple, stmt, plan):
        cap = self.capacity()
        if cap <= 0:
            return
        from ..utils.stats import stats
        # a put IS the miss: counting at insert time keeps the miss
        # counter scoped to CACHEABLE statements — bulk INSERT/DDL
        # traffic (looked up, never inserted) must not read as a bad
        # hit rate in /metrics
        stats().inc("plan_cache_misses")
        with self._lock:
            self._map[key] = (stmt, plan)
            self._map.move_to_end(key)
            while len(self._map) > cap:
                self._map.popitem(last=False)
            n = len(self._map)
        stats().gauge("plan_cache_entries", n)

    def clear(self):
        with self._lock:
            self._map.clear()

    def __len__(self):
        with self._lock:
            return len(self._map)


class ResultCache:
    """LRU of (statement text, space, schema epoch, device flag, WRITE
    epoch) → the statement's wire-encoded result rows (ISSUE 11
    tentpole, part 4).

    Entries hold `to_wire(rs.data)` — the exact form that ships to a
    client — and hits decode it back with `from_wire`, so a cached
    reply is byte-identical to uncached execution and never aliases
    mutable row lists between consumers.  Invalidation is structural,
    exactly like the plan cache: DDL bumps the catalog version half of
    the key, and every mutating statement through this engine —
    including failed ones, whose non-atomic fan-out may have committed
    some parts — bumps the write epoch half
    (`QueryContext.write_epoch`), so
    a stale result can never be LOOKED UP — it just ages out of the
    LRU.  The payoff: a hot repeated read keeps answering from graphd
    memory even when every storage replica is unreachable, as long as
    no local write has bumped the epoch."""

    def __init__(self):
        self._map: "OrderedDict[Tuple, Tuple[Any, Optional[str]]]" = \
            OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def capacity() -> int:
        from ..utils.config import get_config
        try:
            return int(get_config().get("result_cache_size"))
        except Exception:  # noqa: BLE001 — config not initialized
            return 0

    def get(self, key: Tuple):
        from ..utils.stats import stats
        with self._lock:
            ent = self._map.get(key)
            if ent is not None:
                self._map.move_to_end(key)
        if ent is not None:
            stats().inc("result_cache_hits")
        return ent

    def put(self, key: Tuple, wire_data: Any, space: Optional[str]):
        cap = self.capacity()
        if cap <= 0:
            return
        from ..utils.stats import stats
        # a put IS the miss (same scoping rationale as PlanCache.put:
        # only statements that COULD have hit count against the rate)
        stats().inc("result_cache_misses")
        with self._lock:
            self._map[key] = (wire_data, space)
            self._map.move_to_end(key)
            while len(self._map) > cap:
                self._map.popitem(last=False)
            n = len(self._map)
        stats().gauge("result_cache_entries", n)

    def note_invalidated(self):
        """A write-epoch bump made every current entry unreachable —
        count it (the `result_cache_invalidations` metric; a
        dedup-window-replayed write still acks as ONE statement, so it
        bumps — and counts — exactly once)."""
        from ..utils.stats import stats
        with self._lock:
            n = len(self._map)
        if n:
            stats().inc("result_cache_invalidations")

    def clear(self):
        with self._lock:
            self._map.clear()

    def __len__(self):
        with self._lock:
            return len(self._map)


class Session:
    def __init__(self, user: str = "root"):
        self.id = next(_session_ids)
        self.user = user
        self.space: Optional[str] = None
        self.ectx = ExecutionContext()       # persists $var results
        self.var_cols: Dict[str, list] = {}
        self.created = time.time()
        self.last_used = self.created
        self.queries: Dict[int, str] = {}    # qid → text (RUNNING)
        self.running_kill: Dict[int, Any] = {}   # qid → kill Event
        self.killed = False


class QueryEngine:
    """parse → plan → optimize → schedule, one call."""

    def __init__(self, store: Optional[GraphStore] = None,
                 params: Optional[Dict[str, Any]] = None,
                 enable_optimizer: bool = True,
                 tpu_runtime=None):
        self.store = store if store is not None else GraphStore()
        self.qctx = QueryContext(self.store, params)
        self.qctx.tpu_runtime = tpu_runtime
        self.qctx.engine = self          # session admin (KILL SESSION)
        self.scheduler = Scheduler(self.qctx)
        self.enable_optimizer = enable_optimizer
        self._slow_override = (params or {}).get("slow_query_threshold_us")
        # bounded ring (ISSUE 8 satellite): the capacity flag is read at
        # engine construction; a deque drops the oldest entry itself
        from collections import deque
        try:
            from ..utils.config import get_config as _gc
            _cap = int(_gc().get("slow_log_capacity"))
        except Exception:  # noqa: BLE001 — config not initialized
            _cap = 256
        self.slow_log: "deque" = deque(maxlen=max(_cap, 1))
        self.sessions: Dict[int, Session] = {}
        # recently-killed qids (ISSUE 20): double KILL QUERY is
        # idempotent — the second kill of a qid that already matched
        # (and may have since drained away) succeeds instead of raising
        # "no running query matches"
        self._recent_kills: "deque" = deque(maxlen=256)
        # parse/plan LRU (ISSUE 2): repeated statements skip
        # parse → validate → plan → optimize entirely
        self.plan_cache = PlanCache()
        # read-only result LRU (ISSUE 11): hot repeated reads skip
        # execution entirely, invalidated by the same schema epoch plus
        # the engine's write epoch (0-capacity default = disabled)
        self.result_cache = ResultCache()
        # cluster-coherent cache epochs (ISSUE 20): peers' per-space
        # write epochs, folded from metad heartbeat replies and from
        # this graphd's own storaged write acks.  gen(space) is part of
        # every cache key — a write through ANY coordinator retires
        # this engine's cached entries within the heartbeat window.
        # Standalone engines never fold, so gen stays 0 and keys are
        # byte-identical to the pre-fleet engine.
        from ..utils.epochs import ClusterEpochs
        self.cluster_epochs = ClusterEpochs()
        # strict-mode hook (set by GraphService): pull + fold metad's
        # merged epoch table on demand, for leader-consistency cached
        # reads under `result_cache_strict_epoch`
        self.epoch_sync = None
        # workload insights (ISSUE 16): per-fingerprint aggregates
        # behind SHOW STATEMENTS.  Per ENGINE, not process-wide: a
        # LocalCluster runs several graphds in one process and the
        # cluster fan-out sums per-graphd registries
        from ..utils.insights import StatementRegistry
        self.insights = StatementRegistry()
        # stall watchdog (ISSUE 9): idempotent start of the process-wide
        # scan thread; gated by stall_watchdog_interval_secs
        from ..utils.workload import stall_watchdog
        stall_watchdog().ensure_started()

    def new_session(self, user: str = "root") -> Session:
        # reap idle sessions so a long-lived embedded engine doesn't
        # accumulate them (the cluster graphd reaps via metad TTL; the
        # standalone registry uses the same idle-timeout flag)
        from ..utils.config import get_config
        ttl = float(get_config().get("session_idle_timeout_secs"))
        now = time.time()
        # list() snapshots atomically under the GIL — a comprehension
        # runs bytecode per item and races concurrent new_session
        # inserts ("dictionary changed size during iteration")
        for sid, ss in list(self.sessions.items()):
            if now - ss.last_used > ttl:
                self.sessions.pop(sid, None)
        s = Session(user)
        self.sessions[s.id] = s
        return s

    def kill_session(self, sid: int) -> bool:
        """KILL SESSION <id>: the session's next execute is rejected.
        Returns False when the id is unknown (standalone engine only —
        the cluster layer kills through metad)."""
        s = self.sessions.pop(sid, None)
        if s is None:
            return False
        s.killed = True
        # in-flight AND admission-queued statements of the session die
        # with it: the kill event is what the scheduler checks between
        # plan nodes and what the admission wait loop polls (a queued
        # statement leaves the queue without ever taking a slot)
        for ev in list(s.running_kill.values()):
            ev.set()
        return True

    def list_running_queries(self) -> list:
        """RUNNING-query rows with live progress (ISSUE 9) — the one
        source for SHOW [LOCAL] QUERIES and the graphd fan-out RPC.
        Row shape: [sid, qid, user, text, status, operator, rows,
        duration_us, queue_us, device_us, host_us, memory_bytes,
        consistency, batch, fingerprint]."""
        from ..utils.workload import live_registry
        rows = []
        for s in list(self.sessions.values()):
            for qid, qtext in list(s.queries.items()):
                lq = live_registry().get(qid)
                if lq is not None:
                    p = lq.snapshot()
                    rows.append([s.id, qid, s.user, qtext, p["status"],
                                 p["operator"], p["rows"],
                                 p["duration_us"], p["queue_us"],
                                 p["device_us"], p["host_us"],
                                 p["memory_bytes"],
                                 p.get("consistency", ""),
                                 p.get("batch", ""),
                                 p.get("fingerprint", "")])
                else:
                    # workload plane disabled: identity columns only
                    rows.append([s.id, qid, s.user, qtext, "RUNNING",
                                 "", 0, 0, 0, 0, 0, 0, "", "", ""])
        return rows

    def kill_running(self, sid=None, qid=None) -> bool:
        """Set kill events of matching RUNNING queries; True if any
        matched (shared by KILL QUERY local path and the graphd RPC)."""
        from ..utils.workload import live_registry
        hit = False
        for s in list(self.sessions.values()):
            if sid is not None and s.id != sid:
                continue
            for q, ev in list(s.running_kill.items()):
                if qid is None or q == qid:
                    ev.set()
                    lq = live_registry().get(q)
                    if lq is not None:
                        # SHOW QUERIES reports KILLED while the victim
                        # drains toward its next cancellation check
                        lq.killed = True
                    hit = True
                    if q not in self._recent_kills:
                        self._recent_kills.append(q)
        if not hit and qid is not None and qid in self._recent_kills:
            # double-kill idempotency (ISSUE 20): the first kill
            # matched and the victim has since drained — killing an
            # already-killed query is a quiet no-op success
            hit = True
        return hit

    @property
    def slow_query_us(self) -> int:
        """Live: UPDATE CONFIGS / PUT /flags must take effect on a
        running engine."""
        if self._slow_override is not None:
            return int(self._slow_override)
        from ..utils.config import get_config
        return int(get_config().get("slow_query_threshold_us"))

    def _fingerprint(self, stmt: A.Sentence, text: str,
                     space: Optional[str],
                     memo: bool = True) -> Optional[str]:
        """Literal-normalized statement fingerprint (ISSUE 16), memoized
        by (text, space) alongside the plan-cache key so the steady-
        state cost is one bounded-LRU lookup.  None when the insights
        plane is off — every downstream consumer treats None as
        'record nothing'."""
        if not self.insights.enabled():
            return None
        sp = space or ""
        if memo:
            fp = self.insights.fingerprints.get(text, sp)
            if fp is not None:
                return fp
        from ..utils.insights import fingerprint_of
        try:
            fp = fingerprint_of(stmt, sp)
        except Exception:  # noqa: BLE001 — insights must never throw
            return None
        if memo:
            self.insights.fingerprints.put(text, sp, fp)
        return fp

    def _cache_key(self, session: Session, text: str) -> Optional[tuple]:
        """Plan-cache key for this statement in this session's context,
        or None when caching cannot apply: $var state makes planning
        session-dependent, and zero-capacity caches are disabled.  The
        schema epoch (catalog version — bumped by EVERY DDL, including
        ALTER/CREATE TAG and index DDL) and the live device flag are
        part of the key, so invalidation is structural, not evented.
        (Shared by the plan cache and, extended with the write epoch,
        the result cache — either being enabled keeps the key alive.)"""
        if (PlanCache.capacity() <= 0 and ResultCache.capacity() <= 0) \
                or session.var_cols:
            return None
        from ..utils.config import get_config
        tpu_on = self.qctx.tpu_runtime is not None and \
            bool(get_config().get("tpu_enable"))
        epoch = getattr(self.qctx.catalog, "version", 0)
        return (text, session.space, epoch, tpu_on)

    def _strict_epoch_check(self) -> bool:
        """True when this cached read must consult metad's merged epoch
        table first: `result_cache_strict_epoch` is on AND the read
        asked for leader consistency (weaker levels accepted bounded
        staleness by contract — the heartbeat window is within it)."""
        from ..utils.config import get_config
        try:
            if not bool(get_config().get("result_cache_strict_epoch")):
                return False
        except Exception:  # noqa: BLE001 — config not initialized
            return False
        from ..utils.consistency import LEADER, effective_consistency
        return effective_consistency() == LEADER

    def execute(self, session: Session, text: str,
                params: Optional[Dict[str, Any]] = None) -> ResultSet:
        t0 = time.perf_counter()
        if session.killed:
            rs = ResultSet()
            rs.error = "Session was killed"
            return rs
        session.last_used = time.time()
        from ..utils.stats import stats
        key = self._cache_key(session, text)
        # result cache first (ISSUE 11): a hit skips parse AND
        # execution — the write epoch in the key guarantees no local
        # write or DDL has landed since the entry was built.  The USER
        # is part of the key: a hit never runs the per-execute
        # permission check (there is no parsed stmt to check), so rows
        # cached by a privileged session must be unreachable to anyone
        # else; role changes are DDL, so the catalog-version half of
        # the key covers grants/revokes for the same user.
        rkey = None
        if key is not None and ResultCache.capacity() > 0:
            # strict check-at-admission (ISSUE 20): a leader-consistency
            # read under `result_cache_strict_epoch` pulls metad's
            # merged epoch table BEFORE the key is formed — a write
            # acked through any coordinator that reached metad retires
            # the entry before this read can hit it.  Best-effort: a
            # metad hiccup degrades to the heartbeat-bounded window,
            # never blocks the read.
            if self.epoch_sync is not None and self._strict_epoch_check():
                try:
                    self.epoch_sync()
                except Exception:  # noqa: BLE001
                    pass
            # the cluster generation joins the coordinator-local write
            # epoch in the key: local writes invalidate at statement
            # granularity, peers' writes at fold granularity
            rkey = key + (session.user, self.qctx.write_epoch,
                          self.cluster_epochs.gen(session.space))
            ent = self.result_cache.get(rkey)
            if ent is not None:
                return self._result_cache_hit(session, text, ent, t0)
        if key is not None:
            ent = self.plan_cache.get(key)
            if ent is not None:
                stmt, plan = ent
                return self._execute_parsed(session, stmt, text, t0,
                                            cached_plan=plan,
                                            result_key=rkey)
        try:
            stmt = parse(text)
        except ParseError as ex:
            stats().inc("num_queries")
            stats().inc("num_query_errors")
            err = f"SyntaxError: {ex}"
            us = int((time.perf_counter() - t0) * 1e6)
            # unparseable text still aggregates (ISSUE 16): repeated
            # garbage lands under one raw-text digest in SHOW STATEMENTS
            fp = None
            if self.insights.enabled():
                from ..utils.insights import parse_error_fingerprint
                fp = parse_error_fingerprint(text, session.space or "")
                self.insights.record(
                    fp=fp, text=text, kind="Parse",
                    space=session.space or "", latency_us=us, error=err)
            # forced capture covers parse errors too (ISSUE 8): a flood
            # of malformed statements burns SLO availability budget and
            # must leave flight-recorder evidence, not just counters
            from ..utils.flight import flight_recorder
            flight_recorder().record(
                stmt=text, kind="Parse", latency_us=us,
                error=err, trace_id=None, session=session.id,
                operators=[], slow_us=self.slow_query_us,
                fingerprint=fp)
            return ResultSet(error=err)
        if isinstance(stmt, A.SeqSentence):
            # `a; b; c` executes sequentially — each statement plans only
            # after the previous ran, so DDL/USE side effects are visible
            # to later statements; the result is the last statement's
            # (reference semantics for compound execute())
            res = ResultSet()
            for sub in stmt.stmts:
                # memo_fp off: the (text, space) memo key would alias
                # every sub-statement of the compound to one fingerprint
                res = self._execute_parsed(session, sub, text,
                                           time.perf_counter(),
                                           memo_fp=False)
                if not res.ok:
                    return res
            return res
        return self._execute_parsed(session, stmt, text, t0,
                                    cache_key=key, result_key=rkey)

    def _result_cache_hit(self, session: Session, text: str, ent,
                          t0: float) -> ResultSet:
        """Serve a statement from the result cache: decode the stored
        wire form (byte-identical to what uncached execution ships) and
        keep the statement-level accounting honest — it still counts in
        /stats and leaves a flight-recorder entry."""
        from ..core.wire import from_wire
        from ..utils.flight import flight_recorder
        from ..utils.stats import stats
        wire_data, space = ent
        data = from_wire(wire_data) if wire_data is not None else None
        us = int((time.perf_counter() - t0) * 1e6)
        stats().inc("num_queries")
        stats().add_value("query_latency_us", us)
        stats().observe("query_latency_us_hist", us,
                        {"kind": "CachedRead"})
        # the hit skipped parse, so the fingerprint is only available
        # from the memo — a miss there (evicted) just skips aggregation
        fp = None
        if self.insights.enabled():
            fp = self.insights.fingerprints.get(text, session.space or "")
            if fp is not None:
                self.insights.record(
                    fp=fp, text=text, kind="CachedRead",
                    space=session.space or "", latency_us=us,
                    rows=(len(data.rows) if data is not None else 0),
                    result_cache_hit=True)
        flight_recorder().record(
            stmt=text, kind="CachedRead", latency_us=us, error=None,
            trace_id=None, session=session.id, operators=[],
            slow_us=self.slow_query_us, fingerprint=fp)
        if space:
            session.space = space
        return ResultSet(data, space=space, latency_us=us,
                         comment="served from result cache")

    @staticmethod
    def _stmt_kind(stmt: A.Sentence) -> str:
        """Statement kind label for metrics/traces: `GoSentence` → `Go`
        (EXPLAIN/PROFILE report the INNER statement's kind)."""
        if isinstance(stmt, A.ExplainSentence):
            stmt = stmt.stmt
        name = type(stmt).__name__
        return name[:-len("Sentence")] if name.endswith("Sentence") \
            else name

    def _execute_parsed(self, session: Session, stmt: A.Sentence,
                        text: str, t0: float, cached_plan=None,
                        cache_key: Optional[tuple] = None,
                        result_key: Optional[tuple] = None,
                        memo_fp: bool = True) -> ResultSet:
        """Metrics + tracing wrapper: every statement outcome (incl.
        semantic and execution errors) is visible in /stats; every
        statement produces one trace in the trace store, queryable via
        /traces and SHOW TRACES — and a per-operator profile that the
        flight recorder retains for sampled/slow/failed statements."""
        from ..utils import trace
        from ..utils.config import get_config
        from ..utils.stats import stats
        kind = self._stmt_kind(stmt)
        # statement fingerprint (ISSUE 16): computed once here (memoized
        # next to the plan-cache key), stamped onto the live row, the
        # slow log and the flight entry, and aggregated on completion
        space0 = session.space or ""
        fp = self._fingerprint(stmt, text, space0, memo=memo_fp)
        tg = None
        if get_config().get("enable_query_tracing"):
            tg = trace.start_trace(f"query:{kind}", service="graphd",
                                   stmt=text[:200], session=session.id)
        # always-on observation (ISSUE 8): per-node timings/rows/remote
        # cost are collected for EVERY statement — PROFILE renders them,
        # the flight recorder retains them for the queries that matter
        obs = ProfileStats()
        if tg is not None:
            with tg:
                res = self._execute_inner(session, stmt, text, t0,
                                          cached_plan, cache_key, obs,
                                          fp=fp)
        else:
            res = self._execute_inner(session, stmt, text, t0,
                                      cached_plan, cache_key, obs,
                                      fp=fp)
        us = int((time.perf_counter() - t0) * 1e6)
        stats().inc("num_queries")
        stats().add_value("query_latency_us", us)
        stats().observe("query_latency_us_hist", us, {"kind": kind})
        if _bumps_write_epoch(kind):
            # one bump per mutating statement, SUCCESS OR FAILURE — a
            # failed multi-part write may still have committed some
            # parts (fan-out is not atomic), so only statements that
            # provably touched nothing may skip the bump.  A PR 5
            # dedup-replayed write still acks as one statement, so it
            # bumps (and invalidates the result cache) exactly once.
            self.qctx.bump_write_epoch()
            self.result_cache.note_invalidated()
        if res.ok and result_key is not None and res.plan_desc is None \
                and not isinstance(stmt, A.ExplainSentence) \
                and kind in _CACHEABLE_KINDS:
            from ..core.wire import to_wire
            self.result_cache.put(
                result_key,
                to_wire(res.data) if res.data is not None else None,
                res.space)
        slow_us = self.slow_query_us
        if not res.ok:
            stats().inc("num_query_errors")
        elif us > slow_us:
            stats().inc("num_slow_queries")
            self.slow_log.append({"stmt": text, "latency_us": us,
                                  "ts": time.time(),
                                  "trace_id": tg.trace_id
                                  if tg is not None else None,
                                  "fingerprint": fp or ""})
        if fp is not None:
            # the one aggregate update per statement (ISSUE 16): the
            # live row was deregistered in _execute_inner's finally but
            # stays readable — its queue/device/lane attribution folds
            # into the per-fingerprint totals here
            lv = getattr(obs, "live", None)
            self.insights.record(
                fp=fp, text=text, kind=kind, space=space0,
                latency_us=us, error=res.error,
                rows=(len(res.data.rows) if res.data is not None else 0),
                queue_us=(lv.queue_us if lv is not None else 0),
                device_us=(lv.device_us if lv is not None else 0),
                dispatches=(lv.dispatches if lv is not None else 0),
                plan_hash=getattr(obs, "plan_hash", None),
                plan_cache_hit=cached_plan is not None,
                lanes=(lv.batch_lanes if lv is not None else 0))
        from ..utils.flight import flight_recorder
        flight_recorder().record(
            stmt=text, kind=kind, latency_us=us, error=res.error,
            trace_id=tg.trace_id if tg is not None else None,
            session=session.id,
            operators=obs.operators,
            work=(obs.work.as_dict if obs.work is not None else None),
            slow_us=slow_us, fingerprint=fp)
        return res

    def _execute_inner(self, session: Session, stmt: A.Sentence,
                       text: str, t0: float, cached_plan=None,
                       cache_key: Optional[tuple] = None,
                       obs: Optional[ProfileStats] = None,
                       fp: Optional[str] = None) -> ResultSet:
        from ..utils.config import get_config
        if get_config().get("enable_authorize"):
            from .permissions import check as _perm_check
            msg = _perm_check(stmt, session.user, self.qctx.store.catalog,
                              session.space)
            if msg:
                return ResultSet(error=f"PermissionError: {msg}")
        # `obs` collects per-node stats for EVERY run (flight recorder
        # substrate); `want_profile` only controls whether the reply
        # renders them — profiled execution is otherwise identical to
        # the real run (same schedule, same result rows)
        profile_stats = obs if obs is not None else ProfileStats()
        want_profile = False
        explain_only = False
        plan_fmt = "row"
        if isinstance(stmt, A.ExplainSentence):
            plan_fmt = stmt.fmt or "row"
            if plan_fmt not in ("row", "dot"):
                return ResultSet(error=f"SemanticError: unknown plan "
                                       f"format `{stmt.fmt}' "
                                       f"(row | dot)")
            if stmt.profile:
                want_profile = True
            else:
                explain_only = True
            inner = stmt.stmt
        else:
            inner = stmt

        pctx = None
        if cached_plan is not None:
            # plan-cache hit: parse/validate/plan/optimize all skipped;
            # the plan is read-only at execution time (per-run state
            # lives in the statement's ExecutionContext), so reuse is
            # verbatim
            plan = cached_plan
        else:
            try:
                pctx = PlannerContext(self.qctx, session.space)
                pctx.var_cols.update(session.var_cols)
                from ..query.validator import ValidationError, validate
                try:
                    validate(inner, pctx)
                except ValidationError as ex:
                    return ResultSet(error=f"SemanticError: {ex}")
                from ..query.planner import _plan
                root = _plan(pctx, inner)
                from ..query.plan import ExecutionPlan
                plan = ExecutionPlan(root, pctx.space)
                from ..utils.config import get_config
                plan = optimize(plan, enable=self.enable_optimizer,
                                tpu=self.qctx.tpu_runtime is not None
                                and bool(get_config().get("tpu_enable")),
                                pctx=pctx)
            except QueryError as ex:
                return ResultSet(error=f"SemanticError: {ex}")
            if cache_key is not None and not explain_only \
                    and not want_profile and not pctx.var_cols \
                    and self._stmt_kind(stmt) in _CACHEABLE_KINDS:
                # the parsed stmt rides along for the per-execute
                # permission check and the metrics kind label
                self.plan_cache.put(cache_key, stmt, plan)

        if explain_only:
            us = int((time.perf_counter() - t0) * 1e6)
            desc = plan.describe(plan_fmt)
            return ResultSet(DataSet(["plan"], [[desc]]),
                             space=plan.space, latency_us=us,
                             plan_desc=desc)
        if fp is not None:
            # plan shape hash for the regression sentinel (ISSUE 16):
            # memoized on the (immutable post-optimize) plan object, so
            # a plan-cache hit pays one getattr
            ph = getattr(plan, "shape_hash", None)
            if ph is None:
                from ..utils.insights import plan_shape_hash
                ph = plan_shape_hash(plan)
                try:
                    plan.shape_hash = ph
                except Exception:  # noqa: BLE001 — slotted plan class
                    pass
            profile_stats.plan_hash = ph
        # Per-statement ExecutionContext seeded with the session's $vars —
        # intermediates die with the statement; only $var results persist.
        stmt_ectx = ExecutionContext()
        stmt_ectx.results.update({k: v for k, v in session.ectx.results.items()
                                  if k.startswith("$")})
        # register as a running query: SHOW QUERIES lists it, KILL QUERY
        # (session=sid, plan=qid) sets its kill event — the scheduler
        # checks it between plan nodes
        import threading as _threading
        qid = next(_query_ids)
        stmt_ectx.kill_event = _threading.Event()
        session.queries[qid] = text
        session.running_kill[qid] = stmt_ectx.kill_event
        # statement deadline budget (ISSUE 5): the timeout becomes an
        # absolute monotonic deadline in the thread-local cancel
        # context; the RPC client clamps every hop to the remaining
        # budget and ships it in the envelope, so graphd → storaged →
        # metad hops all run under ONE decremented budget
        from ..utils import cancel as _cancel
        timeout_s = 0.0
        try:
            timeout_s = float(get_config().get("query_timeout_secs"))
        except Exception:  # noqa: BLE001 — config not initialized
            pass
        dl = (time.monotonic() + timeout_s) if timeout_s > 0 else None
        # live workload registration (ISSUE 9): the statement is visible
        # in SHOW QUERIES / GET /queries with live per-operator progress
        # from HERE until the finally below; the deadline rides along so
        # the stall watchdog can derive this statement's stall threshold
        from ..utils.consistency import effective_consistency
        from ..utils.workload import live_registry
        live = live_registry().register(
            qid=qid, session=session.id, user=session.user, stmt=text,
            kind=self._stmt_kind(stmt), deadline=dl,
            tracker=stmt_ectx.tracker,
            consistency=effective_consistency(),
            fingerprint=fp)
        stmt_ectx.live = live
        # admission control (ISSUE 10): a bounded-slot gate in front of
        # the scheduler — control statements bypass (priority lane),
        # data statements may wait QUEUED (visible in SHOW QUERIES) or
        # be shed with E_OVERLOAD + retry-after when the queue is full.
        # max_running_queries=0 (the default sentinel) makes acquire()
        # a no-op, byte-identical to the pre-admission engine.
        from ..utils import admission as _adm
        ticket = None
        try:
            with _cancel.use_cancel(kill=stmt_ectx.kill_event,
                                    deadline=dl):
                ticket = _adm.admission().acquire(
                    qid=qid, session=session.id,
                    kind=self._stmt_kind(stmt), live=live,
                    tracker=stmt_ectx.tracker, user=session.user)
                if ticket is not None and ticket.queue_wait_us:
                    # pseudo-operator: the admission wait reaches the
                    # flight recorder next to the real plan nodes
                    # (node id -1 — PROFILE's plan walk never shows it)
                    profile_stats.per_node[-1] = {
                        "kind": "Admission",
                        "exec_us": ticket.queue_wait_us, "rows": 0}
                data = self.scheduler.run(plan, stmt_ectx, profile_stats)
        except _adm.OverloadError as ex:
            # shed: never took a slot; the flight recorder force-
            # captures it (classify → "shed") from the E_OVERLOAD error
            return ResultSet(error=str(ex), space=plan.space)
        except _cancel.DeadlineExceeded:
            from ..utils.stats import stats
            stats().inc("query_deadline_exceeded")
            return ResultSet(
                error=f"E_QUERY_TIMEOUT: statement exceeded "
                      f"query_timeout_secs={timeout_s:g}",
                space=plan.space)
        except _cancel.QueryKilled:
            return ResultSet(error="ExecutionError: query was killed",
                             space=plan.space)
        except Exception as ex:  # noqa: BLE001 — runtime errors go to client
            return ResultSet(error=f"ExecutionError: {ex}", space=plan.space)
        finally:
            if ticket is not None:
                ticket.release()
            session.queries.pop(qid, None)
            session.running_kill.pop(qid, None)
            if live is not None:
                live_registry().deregister(qid)
                # the deregistered row stays readable: _execute_parsed
                # folds its queue/device/lane attribution into the
                # insights registry (ISSUE 16)
                profile_stats.live = live
            # the flight recorder reads the statement's work counts off
            # the observer (even for failed statements, which return
            # from the except arms above)
            profile_stats.work = stmt_ectx.work
            # fold the statement's deterministic work counts into a
            # caller-installed probe (bench / regression harnesses wrap
            # execute() in use_work; the scheduler re-targets counting
            # at stmt_ectx.work inside executors)
            from ..utils.stats import current_work
            outer_wc = current_work()
            if outer_wc is not None and outer_wc is not stmt_ectx.work:
                outer_wc.merge(stmt_ectx.work)
        session.ectx.results.update({k: v for k, v in stmt_ectx.results.items()
                                     if k.startswith("$")})

        session.space = plan.space
        if pctx is not None:
            session.var_cols.update(pctx.var_cols)
        us = int((time.perf_counter() - t0) * 1e6)
        plan_desc = None
        if want_profile:
            if plan_fmt == "dot":
                # DOT rendering carries the DAG shape; per-node timing
                # stays in the row format (reference-compatible subset)
                plan_desc = plan.describe_dot()
            else:
                plan_desc = profile_stats.describe(plan)
            # PROFILE parity (ISSUE 8): `data` stays the QUERY's rows —
            # byte-identical to the unprofiled run — and the per-node
            # breakdown rides separately in plan_desc
        return ResultSet(data, space=plan.space, latency_us=us,
                         plan_desc=plan_desc)


def quick_engine() -> "tuple[QueryEngine, Session]":
    eng = QueryEngine()
    return eng, eng.new_session()
