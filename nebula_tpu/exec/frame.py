"""ColumnarFrame: the device plane's intermediate-result currency.

A multi-clause MATCH pipeline (Traverse → WITH DISTINCT → second MATCH →
OPTIONAL MATCH → aggregate) executed by the row executors materializes a
Python list-of-lists between every pair of plan nodes — per-row Vertex
boxing dominates the tail even when the traversal itself ran on device
(VERDICT r5 missing #2: the device plane LOSES to the host on ic5/ic9).
The frame layer keeps those intermediates columnar: dense-id vertex
columns, numpy value columns and canonical-key edge columns, each with
an optional null mask (OPTIONAL MATCH misses), flowing between the
fused pipeline's segment executors (tpu/pipeline.py).  Python rows are
built exactly once, at the result boundary — and vertices/edges only
for the columns the boundary actually carries.

Column kinds:

  VidCol    dense int64 vertex ids (+ null mask).  `checked` records
            whether an AppendVertices/GetVertices existence check ran:
            the boundary materializes a full Vertex for checked columns
            and the same props-less shell Vertex the host plane carries
            for unchecked ones (parity over dangling edges).
  ValCol    plain numpy values (int64/float64/bool/object) + null mask;
            `vkind` tags the element type for the sort/join compilers.
  EdgeCol   canonical physical-edge key columns (et, s, d, rank) — the
            same currency HopFrame/trail_distinct_keep use — plus a
            (HopFrame, fidx) handle so Edge OBJECTS decode lazily at
            the boundary only for emitted rows.
  OpaqueCol a column the frame cannot represent (variable-length edge
            lists).  It occupies its name so plan col-sets stay aligned,
            but any op that READS it refuses to compile.

All nulls compare equal (NullValue semantics: dedup/group-by treat every
null kind as one value), so one bool mask is enough.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.value import NULL, Vertex, hashable_key


class VidCol:
    kind = "vid"
    __slots__ = ("dense", "null", "checked")

    def __init__(self, dense: np.ndarray, null: Optional[np.ndarray] = None,
                 checked: bool = False):
        self.dense = dense
        self.null = null if null is not None and null.any() else None
        self.checked = checked

    def take(self, idx: np.ndarray) -> "VidCol":
        return VidCol(self.dense[idx],
                      None if self.null is None else self.null[idx],
                      self.checked)

    def null_mask(self, n: int) -> np.ndarray:
        return np.zeros(n, bool) if self.null is None else self.null


class ValCol:
    kind = "val"
    __slots__ = ("vals", "null", "vkind")

    def __init__(self, vals: np.ndarray, null: Optional[np.ndarray],
                 vkind: str):
        self.vals = vals
        self.null = null if null is not None and null.any() else None
        self.vkind = vkind               # int | float | bool | str | obj

    def take(self, idx: np.ndarray) -> "ValCol":
        return ValCol(self.vals[idx],
                      None if self.null is None else self.null[idx],
                      self.vkind)

    def null_mask(self, n: int) -> np.ndarray:
        return np.zeros(n, bool) if self.null is None else self.null


class EdgeCol:
    kind = "edge"
    __slots__ = ("et", "ks", "kd", "rank", "frame", "fidx", "null")

    def __init__(self, et, ks, kd, rank, frame, fidx,
                 null: Optional[np.ndarray] = None):
        self.et, self.ks, self.kd, self.rank = et, ks, kd, rank
        self.frame, self.fidx = frame, fidx
        self.null = null if null is not None and null.any() else None

    @classmethod
    def from_frame(cls, frame, fidx: np.ndarray) -> "EdgeCol":
        return cls(frame.key_et[fidx], frame.key_s[fidx],
                   frame.key_d[fidx], frame.rank[fidx], frame, fidx)

    def take(self, idx: np.ndarray) -> "EdgeCol":
        return EdgeCol(self.et[idx], self.ks[idx], self.kd[idx],
                       self.rank[idx], self.frame, self.fidx[idx],
                       None if self.null is None else self.null[idx])

    def null_mask(self, n: int) -> np.ndarray:
        return np.zeros(n, bool) if self.null is None else self.null


class OpaqueCol:
    """Name-holder for a column with no columnar representation."""
    kind = "opaque"
    __slots__ = ()

    def take(self, idx: np.ndarray) -> "OpaqueCol":
        return self

    def null_mask(self, n: int) -> np.ndarray:
        return np.zeros(n, bool)


class ColumnarFrame:
    """Named columns of equal length; the unit flowing between the fused
    pipeline's segment executors."""
    __slots__ = ("n", "names", "cols")

    def __init__(self, n: int, names: List[str], cols: Dict[str, Any]):
        self.n = n
        self.names = list(names)
        self.cols = cols

    def take(self, idx: np.ndarray) -> "ColumnarFrame":
        return ColumnarFrame(int(idx.size), self.names,
                             {nm: c.take(idx) for nm, c in self.cols.items()})

    def col(self, name: str):
        return self.cols[name]


# ---------------------------------------------------------------------------
# Factorization — shared by dedup / join / group-by / sort.  Codes are
# int64 with -1 for null (all nulls equal, NullValue semantics); equal
# codes ⟺ equal values under hashable_key for the column's kind
# (Vertex eq is by vid ⟺ dense id; Edge eq is the canonical key).
# ---------------------------------------------------------------------------


def _factorize_vals(vals: np.ndarray, ordered: bool) -> np.ndarray:
    """Codes for one value array (no nulls inside).  ordered=True makes
    code order follow value order (sort keys need it; identity keys
    don't care)."""
    if vals.size == 0:
        return np.empty(0, np.int64)
    if vals.dtype != object:
        u, inv = np.unique(vals, return_inverse=True)
        return inv.astype(np.int64)
    try:
        u, inv = np.unique(vals, return_inverse=True)
        return inv.astype(np.int64)
    except TypeError:
        if ordered:
            raise
        # unsortable python objects: dict-factorize on hashable_key
        codes = np.empty(vals.size, np.int64)
        seen: Dict[Any, int] = {}
        for i, v in enumerate(vals.tolist()):
            k = hashable_key(v)
            c = seen.get(k)
            if c is None:
                c = seen[k] = len(seen)
            codes[i] = c
        return codes


def col_codes(col, n: int, ordered: bool = False) -> List[np.ndarray]:
    """Identity codes for one column: a list of int64 arrays whose
    componentwise equality ⟺ row equality for dedup/group/join."""
    if col.kind == "vid":
        d = col.dense
        if col.null is not None:
            d = np.where(col.null, np.int64(-1), d)
        return [d]
    if col.kind == "val":
        codes = np.zeros(n, np.int64)
        if col.null is None:
            codes = _factorize_vals(col.vals, ordered)
        else:
            nn = ~col.null
            codes[nn] = _factorize_vals(col.vals[nn], ordered)
            codes[col.null] = -1
        return [codes]
    if col.kind == "edge":
        nullm = col.null
        def z(a):
            return np.where(nullm, np.int64(0), a) if nullm is not None else a
        et = np.where(nullm, np.int64(-1), col.et) if nullm is not None \
            else col.et
        return [et, z(col.ks), z(col.kd), z(col.rank)]
    raise TypeError(f"no codes for column kind {col.kind}")


def join_codes(lcol, rcol, nl: int, nr: int):
    """Joint identity codes across two frames' key columns (shared code
    space so equal values get equal codes on both sides)."""
    if lcol.kind == "vid" and rcol.kind == "vid":
        return col_codes(lcol, nl), col_codes(rcol, nr)
    if lcol.kind == "val" and rcol.kind == "val":
        both = ValCol(np.concatenate([_obj_ok(lcol.vals), _obj_ok(rcol.vals)]),
                      np.concatenate([lcol.null_mask(nl),
                                      rcol.null_mask(nr)]),
                      lcol.vkind)
        codes = col_codes(both, nl + nr)[0]
        return [codes[:nl]], [codes[nl:]]
    raise TypeError("join keys must be vertex or value columns of one kind")


def _obj_ok(a: np.ndarray) -> np.ndarray:
    return a


def group_ids(code_cols: List[np.ndarray], n: int):
    """(gid, reps): gid[i] = group of row i, groups numbered in FIRST
    OCCURRENCE order (host executors' group/dedup order); reps = first
    row index of each group."""
    if n == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    if not code_cols:
        return np.zeros(n, np.int64), np.zeros(1, np.int64)
    order = np.lexsort(code_cols[::-1])
    new = np.zeros(n, bool)
    new[0] = True
    for c in code_cols:
        cs = c[order]
        new[1:] |= cs[1:] != cs[:-1]
    sorted_gid = np.cumsum(new) - 1
    gid_tmp = np.empty(n, np.int64)
    gid_tmp[order] = sorted_gid
    # renumber groups by first-occurrence row index
    ng = int(sorted_gid[-1]) + 1
    first = np.full(ng, n, np.int64)
    np.minimum.at(first, gid_tmp, np.arange(n, dtype=np.int64))
    rank = np.empty(ng, np.int64)
    rank[np.argsort(first, kind="stable")] = np.arange(ng, dtype=np.int64)
    gid = rank[gid_tmp]
    reps = np.sort(first)
    return gid, reps


# ---------------------------------------------------------------------------
# Result-boundary materialization (lazy rows: vertices/edges are built
# only for the columns — and rows — the boundary actually carries).
# ---------------------------------------------------------------------------


def materialize_column(col, n: int, qctx, space: str, d2v) -> np.ndarray:
    """One frame column → an object/numeric numpy array of engine
    Values, exactly what the row executors would have produced."""
    if col.kind == "val":
        if col.null is None:
            return col.vals
        out = col.vals.astype(object) if col.vals.dtype != object \
            else col.vals.copy()
        out[col.null] = NULL
        return out
    if col.kind == "vid":
        out = np.empty(n, object)
        nn = ~col.null if col.null is not None else np.ones(n, bool)
        dense = col.dense[nn]
        if dense.size:
            uniq, inv = np.unique(dense, return_inverse=True)
            built = np.empty(uniq.size, object)
            # d2v holds numpy scalars — round-trip through .tolist() so
            # the vids handed to row executors are plain python values
            # (store hashing/typing rejects np.int64)
            vids = np.asarray(d2v)[uniq].tolist()
            for j, vid in enumerate(vids):
                if col.checked:
                    v = qctx.build_vertex(space, vid)
                    built[j] = v if v is not None else Vertex(vid)
                else:
                    # host parity: positions never existence-checked carry
                    # a props-less shell Vertex (prop reads answer NULL)
                    built[j] = Vertex(vid)
            out[nn] = built[inv]
        if col.null is not None:
            out[col.null] = NULL
        return out
    if col.kind == "edge":
        out = np.empty(n, object)
        nn = ~col.null if col.null is not None else np.ones(n, bool)
        fidx = col.fidx[nn]
        if fidx.size:
            out[nn] = col.frame.decode(fidx)
        if col.null is not None:
            out[col.null] = NULL
        return out
    raise TypeError(f"cannot materialize column kind {col.kind}")
