"""DAG scheduler: runs a plan's executors in dependency order.

Analog of the reference's AsyncMsgNotifyBasedScheduler (reference:
src/graph/scheduler [UNVERIFIED — empty mount, SURVEY §0]).  Plans here
are in-process DAGs; we execute memoized post-order (each shared node runs
exactly once), recording per-node timing/row stats for PROFILE.  Branches
with independent deps can run on a thread pool; the default is sequential
because the Python executors are CPU-bound under the GIL — the parallelism
that matters (the device hop loop) lives inside TpuTraverse.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..core.value import DataSet
from ..query.plan import ExecutionPlan, PlanNode
from .context import ExecutionContext, QueryContext
from .executors import run_node


class ProfileStats:
    def __init__(self):
        self.per_node: Dict[int, Dict] = {}

    def record(self, node: PlanNode, us: int, rows: int):
        self.per_node[node.id] = {"kind": node.kind, "exec_us": us, "rows": rows}

    def describe(self, plan: ExecutionPlan) -> str:
        lines = []

        def visit(n: PlanNode, depth: int):
            st = self.per_node.get(n.id)
            extra = ""
            if st:
                extra = f"  [rows={st['rows']} time={st['exec_us']}us]"
                if "tpu" in st:
                    extra += f" tpu={st['tpu']}"
            lines.append("  " * depth + f"{n.kind}#{n.id}{extra}")
            for d in n.deps:
                visit(d, depth + 1)

        visit(plan.root, 0)
        return "\n".join(lines)


class Scheduler:
    def __init__(self, qctx: QueryContext):
        self.qctx = qctx

    def run(self, plan: ExecutionPlan, ectx: Optional[ExecutionContext] = None,
            profile: Optional[ProfileStats] = None) -> DataSet:
        ectx = ectx if ectx is not None else ExecutionContext()
        done: Dict[int, DataSet] = {}
        order: List[PlanNode] = []
        seen = set()

        def topo(n: PlanNode):
            if n.id in seen:
                return
            seen.add(n.id)
            for d in n.deps:
                topo(d)
            order.append(n)

        topo(plan.root)
        for node in order:
            t0 = time.perf_counter()
            if profile is not None:
                self.qctx.last_tpu_stats = None
            ds = run_node(node, self.qctx, ectx, plan.space)
            us = int((time.perf_counter() - t0) * 1e6)
            ectx.set_result(node.output_var, ds)
            done[node.id] = ds
            if profile is not None:
                profile.record(node, us, len(ds.rows) if ds is not None else 0)
                ts = getattr(self.qctx, "last_tpu_stats", None)
                if ts is not None:
                    # device-plane profile fields (SURVEY §5 tracing):
                    # per-hop expansion sizes + kernel time + buckets
                    profile.per_node[node.id]["tpu"] = {
                        "device_s": round(ts.device_s, 6),
                        "hop_edges": ts.hop_edges,
                        "buckets": {"F": ts.f_cap, "EB": ts.e_cap},
                        "retries": ts.retries,
                    }
        return done[plan.root.id]
