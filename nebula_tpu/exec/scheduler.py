"""DAG scheduler: runs a plan's executors in dependency order.

Analog of the reference's AsyncMsgNotifyBasedScheduler (reference:
src/graph/scheduler [UNVERIFIED — empty mount, SURVEY §0]).  Plans are
in-process DAGs; each shared node runs exactly once, with per-node
timing/row stats for PROFILE.

Independent branches run CONCURRENTLY on a thread pool (ready-queue
dispatch, the notify-based scheduler's shape) whenever the plan actually
branches and the node work can overlap: cluster-mode executors block on
storage RPCs (socket waits release the GIL), and device-plane nodes
block in jax dispatch.  Chain-shaped plans use the sequential path.
PROFILE runs the SAME schedule as unprofiled runs (ISSUE 8: a profile
taken under a different concurrency regime is not a profile of the
production query) — `qctx.last_tpu_stats` is thread-local, so parallel
branches attribute device stats to their own node, and ProfileStats
writes are per-node-keyed dict inserts.  The `scheduler_threads` flag
bounds the pool; 0 forces sequential.

Every run also collects an always-on per-node profile (ProfileStats is
cheap: one dict insert per node) plus a per-node CostRecorder that the
RPC layer fills from reply-envelope cost records — the substrate the
flight recorder (utils/flight.py) and cluster-wide PROFILE read.
"""
from __future__ import annotations

import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Dict, List, Optional

from ..core.value import DataSet
from ..query.plan import ExecutionPlan, PlanNode
from .context import ExecutionContext, QueryContext
from .executors import run_node


class ProfileStats:
    """Per-plan-node execution stats.  Safe under the parallel schedule:
    each node runs exactly once, so concurrent record() calls write
    DISTINCT keys (single dict-item writes are atomic under the GIL).

    Besides wall time and rows, a node's row may carry:
      * `remote` — aggregated reply-envelope cost records from every
        RPC the node issued (`remote_us`, `rows`, `bytes_*`,
        `wal_fsyncs`, `dedup_hits`, per-part call counts) — the
        cluster-wide half of PROFILE;
      * `tpu` — the device-plane phase breakdown, plus per-SEGMENT
        rows for fused TpuMatchPipeline nodes (each segment's op,
        wall µs and device dispatch µs individually, not one opaque
        fused node)."""

    def __init__(self):
        self.per_node: Dict[int, Dict] = {}
        self.work = None          # the statement's WorkCounters (engine)

    def record(self, node: PlanNode, us: int, rows: int):
        self.per_node[node.id] = {"kind": node.kind, "exec_us": us, "rows": rows}

    def operators(self) -> List[Dict]:
        """Flight-recorder form: per-operator dicts, plan order not
        guaranteed (keyed rows carry the node id)."""
        return [dict(st, id=nid)
                for nid, st in sorted(self.per_node.items())]

    def describe(self, plan: ExecutionPlan) -> str:
        lines = []

        def visit(n: PlanNode, depth: int):
            st = self.per_node.get(n.id)
            extra = ""
            if st:
                extra = f"  [rows={st['rows']} time={st['exec_us']}us]"
                if "remote" in st:
                    rc = st["remote"]
                    parts = " ".join(f"{k}={rc[k]}" for k in sorted(rc))
                    extra += f" remote={{{parts}}}"
                if "tpu" in st:
                    extra += f" tpu={st['tpu']}"
            lines.append("  " * depth + f"{n.kind}#{n.id}{extra}")
            if st and "segments" in st:
                for seg in st["segments"]:
                    lines.append("  " * (depth + 1)
                                 + f"segment:{seg['op']}"
                                 f"  [rows={seg.get('rows', 0)}"
                                 f" time={seg['us']}us"
                                 f" device={seg.get('device_us', 0)}us]")
            for d in n.deps:
                visit(d, depth + 1)

        visit(plan.root, 0)
        return "\n".join(lines)


class Scheduler:
    def __init__(self, qctx: QueryContext):
        self.qctx = qctx

    def run(self, plan: ExecutionPlan, ectx: Optional[ExecutionContext] = None,
            profile: Optional[ProfileStats] = None) -> DataSet:
        ectx = ectx if ectx is not None else ExecutionContext()
        done: Dict[int, DataSet] = {}
        order: List[PlanNode] = []
        seen = set()

        def topo(n: PlanNode):
            if n.id in seen:
                return
            seen.add(n.id)
            for d in n.deps:
                topo(d)
            order.append(n)

        topo(plan.root)

        # snapshot the submitting thread's trace context once: parallel
        # branches run exec_one on pool threads, which must attribute
        # their spans and work counts to the SAME statement
        from ..utils import cancel as _cancel
        from ..utils import trace
        from ..utils.stats import use_work
        tctx = trace.current_ctx()
        # snapshot the statement's cancel context once, like the trace
        # context: parallel branches run on pool threads, and their RPC
        # hops must clamp to the SAME deadline budget
        c_kill = _cancel.current_kill()
        c_dl = _cancel.current_deadline()

        from ..utils.failpoints import fail as _fail
        from ..utils.workload import use_live
        live = getattr(ectx, "live", None)
        # snapshot the statement's read-consistency override too
        # (ISSUE 11): a parallel branch's storage reads must run at the
        # same level the submitting thread's use_consistency() installed
        from ..utils import consistency as _consistency
        c_lvl = _consistency.current_override()

        def exec_one(node: PlanNode):
            kill = getattr(ectx, "kill_event", None)
            if kill is not None and kill.is_set():
                from .executors import ExecError
                raise ExecError("query was killed")
            t0 = time.perf_counter()
            # snapshot the thread-local device-stats slot by IDENTITY:
            # a node that dispatched installs a fresh TraverseStats, so
            # `is not prev` attributes it to this node — without
            # clearing the slot, which external consumers (bench, the
            # device-engagement tests) read after the statement
            prev_ts = getattr(self.qctx, "last_tpu_stats", None) \
                if profile is not None else None
            # per-node cost sink: the RPC client folds reply-envelope
            # cost records (and its own call/byte counts) into this
            # while the node's executor runs — even when the node fails,
            # the costs collected so far reach the flight recorder
            from ..utils.stats import CostRecorder, use_cost
            node_cost = CostRecorder() if profile is not None else None
            try:
                with trace.use_ctx(tctx), \
                        _cancel.use_cancel(kill=c_kill, deadline=c_dl), \
                        use_work(getattr(ectx, "work", None)), \
                        use_cost(node_cost), \
                        use_live(live), \
                        _consistency.use_consistency(c_lvl), \
                        trace.span(f"exec:{node.kind}", node=node.id) as rec:
                    # deadline check between plan nodes: a budget spent
                    # in an earlier node must not start the next one
                    _cancel.check()
                    if live is not None:
                        # live workload row (ISSUE 9): SHOW QUERIES
                        # shows WHICH plan node is running right now
                        live.node_start(node.kind, node.id)
                    # failpoint: delay/fail any statement at a chosen
                    # plan-node kind (stall-watchdog and live-progress
                    # tests arm `exec:node` with key=<kind>)
                    _fail.hit("exec:node", key=node.kind)
                    ds = run_node(node, self.qctx, ectx, plan.space)
                    if rec is not None and ds is not None:
                        # len(ds), not len(ds.rows): a ColumnarDataSet
                        # answers len() from its column buffers without
                        # materializing per-row Python lists (the lazy
                        # result boundary PR4 built)
                        rec.setdefault("attrs", {})["rows"] = len(ds)
            except BaseException:
                if profile is not None:
                    us = int((time.perf_counter() - t0) * 1e6)
                    profile.record(node, us, 0)
                    if node_cost:
                        profile.per_node[node.id]["remote"] = \
                            node_cost.as_dict()
                raise
            us = int((time.perf_counter() - t0) * 1e6)
            ectx.set_result(node.output_var, ds)
            done[node.id] = ds
            if live is not None:
                live.node_done(len(ds) if ds is not None else 0)
            if profile is not None:
                profile.record(node, us, len(ds) if ds is not None else 0)
                if node_cost:
                    profile.per_node[node.id]["remote"] = \
                        node_cost.as_dict()
                ts = getattr(self.qctx, "last_tpu_stats", None)
                if ts is not None and ts is not prev_ts:
                    # device-plane profile fields (SURVEY §5 tracing):
                    # per-hop expansion sizes + kernel time + buckets
                    profile.per_node[node.id]["tpu"] = {
                        "device_s": round(ts.device_s, 6),
                        "queue_s": round(getattr(ts, "queue_s", 0.0), 6),
                        "put_s": round(ts.put_s, 6),
                        "fetch_s": round(ts.fetch_s, 6),
                        "mat_s": round(ts.mat_s, 6),
                        "hop_edges": ts.hop_edges,
                        "buckets": {"EB": ts.e_cap},
                        "retries": ts.retries,
                        "compiles": getattr(ts, "compiles", 0),
                        "hbm_bytes": getattr(ts, "hbm_bytes", 0),
                    }
                    segs = getattr(ts, "segments", None)
                    if segs:
                        # fused TpuMatchPipeline: each segment's cost
                        # individually, not one opaque node (ISSUE 8)
                        profile.per_node[node.id]["segments"] = segs

        threads = self._pool_size()
        branchy = any(len(n.deps) > 1 for n in order)
        # Sequence nodes order side effects by DFS position only (no DAG
        # edge between prev and next subtrees) — parallel dispatch would
        # break them, so such plans stay sequential
        has_seq = any(n.kind == "Sequence" for n in order)
        if threads > 1 and branchy and not has_seq:
            # PROFILE runs take this path too (ISSUE 8): the profile
            # must record the schedule real runs use
            from ..utils.stats import stats as _metrics
            _metrics().inc("scheduler_parallel_plans")
            self._run_parallel(order, exec_one, threads)
        else:
            for node in order:
                exec_one(node)
        return done[plan.root.id]

    @staticmethod
    def _pool_size() -> int:
        from ..utils.config import get_config
        try:
            return int(get_config().get("scheduler_threads"))
        except Exception:  # noqa: BLE001 — config not initialized
            return 4

    @staticmethod
    def _run_parallel(order: List[PlanNode], exec_one, threads: int):
        """Ready-queue dispatch: a node is submitted the moment its last
        dependency finishes; independent branches overlap."""
        node_by_id = {n.id: n for n in order}
        # Argument nodes read their producer BY NAME (from_var) with no
        # DAG edge — sequential topo order satisfies it implicitly, the
        # ready-queue must make the edge explicit or the Argument can
        # dispatch before its variable exists
        producer = {n.output_var: n.id for n in order}
        dep_ids: Dict[int, set] = {}
        for n in order:
            ids = {d.id for d in n.deps}
            fv = n.args.get("from_var") if n.args else None
            if fv in producer and producer[fv] != n.id:
                ids.add(producer[fv])
            dep_ids[n.id] = ids
        remaining = {n.id: len(dep_ids[n.id]) for n in order}
        dependents: Dict[int, List[int]] = {n.id: [] for n in order}
        for n in order:
            for d in dep_ids[n.id]:
                dependents[d].append(n.id)
        ready = [n for n in order if remaining[n.id] == 0]
        with ThreadPoolExecutor(max_workers=threads) as pool:
            futures = {pool.submit(exec_one, n): n for n in ready}
            while futures:
                finished, _ = wait(futures, return_when=FIRST_COMPLETED)
                for fut in finished:
                    node = futures.pop(fut)
                    fut.result()        # re-raise executor errors
                    for did in dependents[node.id]:
                        remaining[did] -= 1
                        if remaining[did] == 0:
                            futures[pool.submit(
                                exec_one, node_by_id[did])] = node_by_id[did]
