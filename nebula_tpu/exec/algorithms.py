"""Host graph algorithms: FIND PATH (shortest/all/noloop) + GET SUBGRAPH.

Analog of the reference's algo executors (BFSShortestPathExecutor /
AllPathsExecutor / SubgraphExecutor; reference: src/graph/executor/algo
[UNVERIFIED — empty mount, SURVEY §0]).  These are the CPU oracles; the
device variants (parent-array BFS over sharded CSR) live in nebula_tpu.tpu.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.expr import DictContext, Expr, to_bool3
from ..core.value import DataSet, Edge, Path, Step, Vertex, hashable_key, is_null
from .context import ExecutionContext, QueryContext, RowContext


def _vids_from(a, key_vids, key_ref, ectx: ExecutionContext) -> List[Any]:
    out: List[Any] = []
    if a.get(key_ref):
        ref = a[key_ref]
        ds = None
        if ref.startswith("$"):
            var = ref[1:].split(".")[0]
            ds = ectx.get_result(f"${var}")
            ref = ref.split(".")[1]
        else:
            # piped input: stored under the plan's input var by the scheduler
            ds = ectx.get_result(a.get("__input_var", ""))
        if ds is None or not ds.column_names:
            return []
        ci = ds.col_index(ref)
        out = [r[ci] for r in ds.rows]
    else:
        for ve in a.get(key_vids) or []:
            out.append(ve.eval(DictContext()) if isinstance(ve, Expr) else ve)
    uniq, seen = [], set()
    for v in out:
        if isinstance(v, Vertex):
            v = v.vid
        if is_null(v):
            continue
        k = hashable_key(v)
        if k not in seen:
            seen.add(k)
            uniq.append(v)
    return uniq


def _neighbors(qctx: QueryContext, space: str, vid: Any, etypes: List[str],
               direction: str, etype_ids: Dict[str, int],
               edge_filter: Optional[Expr]):
    for (s, et, rank, other, props, sd) in qctx.store.get_neighbors(
            space, [vid], etypes, direction):
        e = Edge(s, other, et, rank, dict(props),
                 etype=etype_ids[et] if sd > 0 else -etype_ids[et])
        if edge_filter is not None:
            rc = RowContext(qctx, space, {"_src": s, "_edge": e, "_dst": other})
            if to_bool3(edge_filter.eval(rc)) is not True:
                continue
        yield e, other


def make_vertex_fn(qctx: QueryContext, space: str, with_prop: bool):
    """Path-endpoint vertex builder — SHARED with the device path
    (tpu/paths.py) so host/device rows stay byte-identical."""
    def mk_vertex(vid):
        if with_prop:
            v = qctx.build_vertex(space, vid)
            return v if v is not None else Vertex(vid)
        return Vertex(vid)
    return mk_vertex


def make_path_fn(mk_vertex):
    def path_of(vchain: List[Any], echain: List[Edge]) -> Path:
        p = Path(mk_vertex(vchain[0]))
        for v, e in zip(vchain[1:], echain):
            p.steps.append(Step(mk_vertex(v), e.name, e.ranking, e.props,
                                e.etype))
        return p
    return path_of


def sort_path_rows(rows: List[List[Any]]):
    """Canonical FIND PATH result order (row-parity contract)."""
    rows.sort(key=lambda r: (r[0].length(),
                             [str(v.vid) for v in r[0].nodes()]))


def find_path_host(node, qctx: QueryContext, ectx: ExecutionContext) -> DataSet:
    a = node.args
    space = a["space"]
    etypes = a["edge_types"]
    etype_ids = {e: qctx.store.catalog.get_edge(space, e).edge_type for e in etypes}
    direction = a["direction"]
    upto = a["upto"]
    kind = a["kind"]
    filt = a.get("filter")
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    srcs = _vids_from(a, "src_vids", "src_ref", ectx)
    dsts = _vids_from(a, "dst_vids", "dst_ref", ectx)
    dst_set = {hashable_key(d) for d in dsts}

    col = node.col_names[0]
    rows: List[List[Any]] = []
    mk_vertex = make_vertex_fn(qctx, space, bool(a.get("with_prop")))
    path_of = make_path_fn(mk_vertex)

    if kind == "shortest":
        # level-synchronous BFS per source with multi-parent tracking —
        # yields ALL shortest paths per (src, dst) pair.
        for s in srcs:
            parents: Dict[Any, List[Tuple[Any, Edge]]] = {}
            depth: Dict[Any, int] = {hashable_key(s): 0}
            frontier = [s]
            found_at: Dict[Any, int] = {}
            for level in range(1, upto + 1):
                nxt: List[Any] = []
                nxt_seen: Set = set()
                for u in frontier:
                    for e, w in _neighbors(qctx, space, u, etypes, direction,
                                           etype_ids, filt):
                        kw = hashable_key(w)
                        if kw in depth and depth[kw] < level:
                            continue
                        if kw not in depth:
                            depth[kw] = level
                        if depth[kw] == level:
                            parents.setdefault(kw, []).append((u, e))
                            if kw not in nxt_seen:
                                nxt_seen.add(kw)
                                nxt.append(w)
                        if kw in dst_set and kw not in found_at:
                            found_at[kw] = level
                frontier = nxt
                if not frontier:
                    break

            def all_paths_to(vid, kv) -> List[Tuple[List[Any], List[Edge]]]:
                if depth.get(kv, -1) == 0:
                    return [([vid], [])]
                out = []
                for (u, e) in parents.get(kv, []):
                    for (vc, ec) in all_paths_to(u, hashable_key(u)):
                        out.append((vc + [vid], ec + [e]))
                return out

            for d in dsts:
                kd = hashable_key(d)
                if hashable_key(s) == kd:
                    continue
                if kd in found_at:
                    for (vc, ec) in all_paths_to(d, kd):
                        rows.append([path_of(vc, ec)])
    else:
        noloop = kind == "noloop"
        tracker = getattr(ectx, "tracker", None)
        pending = 0
        for s in srcs:
            stack: List[Tuple[Any, List[Any], List[Edge], Set]] = [
                (s, [s], [], set())]
            while stack:
                cur, vchain, echain, eseen = stack.pop()
                if len(echain) >= upto:
                    continue
                for e, w in _neighbors(qctx, space, cur, etypes, direction,
                                       etype_ids, filt):
                    ek = e.key()
                    if ek in eseen:
                        continue
                    if noloop and any(hashable_key(w) == hashable_key(v)
                                      for v in vchain):
                        continue
                    nvc, nec = vchain + [w], echain + [e]
                    if hashable_key(w) in dst_set:
                        rows.append([path_of(nvc, nec)])
                    stack.append((w, nvc, nec, eseen | {ek}))
                    # ALL PATHS is the worst allocator in the engine:
                    # charge the search state as it grows, not after
                    pending += 96 * (len(nvc) + len(eseen))
                    if tracker is not None and pending > (1 << 20):
                        tracker.charge(pending)
                        pending = 0
        if tracker is not None and pending:
            tracker.charge(pending)
    sort_path_rows(rows)
    return DataSet([col], rows)


def subgraph_host(node, qctx: QueryContext, ectx: ExecutionContext) -> DataSet:
    a = node.args
    space = a["space"]
    cat = qctx.store.catalog
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    starts = _vids_from(a, "vids", "src_ref", ectx)
    steps = a["steps"]
    filt = a.get("filter")

    specs: List[Tuple[str, str]] = []   # (etype, direction)
    for e in a.get("out_edges") or []:
        specs.append((e, "out"))
    for e in a.get("in_edges") or []:
        specs.append((e, "in"))
    for e in a.get("both_edges") or []:
        specs.append((e, "both"))
    etype_ids = {e: cat.get_edge(space, e).edge_type for e, _ in specs}

    def mk_vertex(vid):
        if a.get("with_prop"):
            v = qctx.build_vertex(space, vid)
            return v if v is not None else Vertex(vid)
        return Vertex(vid)

    visited: Set = {hashable_key(s) for s in starts}
    frontier = list(starts)
    level_vertices: List[List[Any]] = [[mk_vertex(s) for s in starts]]
    level_edges: List[List[Edge]] = []
    seen_edges: Set = set()

    for step in range(steps):
        nxt, nxt_seen = [], set()
        edges_here: List[Edge] = []
        for u in frontier:
            for et, d in specs:
                for e, w in _neighbors(qctx, space, u, [et], d,
                                       {et: etype_ids[et]}, filt):
                    if e.key() in seen_edges:
                        continue
                    seen_edges.add(e.key())
                    edges_here.append(e)
                    kw = hashable_key(w)
                    if kw not in visited:
                        visited.add(kw)
                        if kw not in nxt_seen:
                            nxt_seen.add(kw)
                            nxt.append(w)
        level_edges.append(edges_here)
        frontier = nxt
        level_vertices.append([mk_vertex(v) for v in nxt])
        if not frontier:
            break

    # final round: edges among the last-level vertices (reference behavior:
    # the subgraph includes edges between step-N vertices)
    edges_final: List[Edge] = []
    last_set = {hashable_key(v) for lvl in level_vertices for v in
                [x.vid for x in lvl]}
    for u in frontier:
        for et, d in specs:
            for e, w in _neighbors(qctx, space, u, [et], d,
                                   {et: etype_ids[et]}, filt):
                if e.key() in seen_edges:
                    continue
                if hashable_key(w) in last_set:
                    seen_edges.add(e.key())
                    edges_final.append(e)
    if edges_final:
        if len(level_edges) >= steps:
            level_edges.append(edges_final)
        else:
            level_edges[-1].extend(edges_final)

    yield_spec = a.get("yield") or ["vertices", "edges"]
    cols = node.col_names
    rows = []
    n_levels = max(len(level_vertices), len(level_edges))
    for i in range(n_levels):
        vs = level_vertices[i] if i < len(level_vertices) else []
        es = level_edges[i] if i < len(level_edges) else []
        if not vs and not es:
            continue
        row = []
        for spec in yield_spec:
            row.append(vs if spec == "vertices" else es)
        rows.append(row)
    return DataSet(list(cols), rows)
