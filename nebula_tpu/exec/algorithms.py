"""Host graph algorithms: FIND PATH (shortest/all/noloop) + GET SUBGRAPH.

Analog of the reference's algo executors (BFSShortestPathExecutor /
AllPathsExecutor / SubgraphExecutor; reference: src/graph/executor/algo
[UNVERIFIED — empty mount, SURVEY §0]).  These are the CPU oracles; the
device variants (parent-array BFS over sharded CSR) live in nebula_tpu.tpu.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.expr import DictContext, Expr, to_bool3
from ..core.value import DataSet, Edge, Path, Step, Vertex, hashable_key, is_null
from .context import ExecutionContext, QueryContext, RowContext


def _vids_from(a, key_vids, key_ref, ectx: ExecutionContext) -> List[Any]:
    out: List[Any] = []
    if a.get(key_ref):
        ref = a[key_ref]
        ds = None
        if ref.startswith("$"):
            var = ref[1:].split(".")[0]
            ds = ectx.get_result(f"${var}")
            ref = ref.split(".")[1]
        else:
            # piped input: stored under the plan's input var by the scheduler
            ds = ectx.get_result(a.get("__input_var", ""))
        if ds is None or not ds.column_names:
            return []
        ci = ds.col_index(ref)
        out = [r[ci] for r in ds.rows]
    else:
        for ve in a.get(key_vids) or []:
            out.append(ve.eval(DictContext()) if isinstance(ve, Expr) else ve)
    uniq, seen = [], set()
    for v in out:
        if isinstance(v, Vertex):
            v = v.vid
        if is_null(v):
            continue
        k = hashable_key(v)
        if k not in seen:
            seen.add(k)
            uniq.append(v)
    return uniq


def _neighbors(qctx: QueryContext, space: str, vid: Any, etypes: List[str],
               direction: str, etype_ids: Dict[str, int],
               edge_filter: Optional[Expr]):
    for (s, et, rank, other, props, sd) in qctx.store.get_neighbors(
            space, [vid], etypes, direction):
        e = Edge(s, other, et, rank, dict(props),
                 etype=etype_ids[et] if sd > 0 else -etype_ids[et])
        if edge_filter is not None:
            rc = RowContext(qctx, space, {"_src": s, "_edge": e, "_dst": other})
            if to_bool3(edge_filter.eval(rc)) is not True:
                continue
        yield e, other


def make_vertex_fn(qctx: QueryContext, space: str, with_prop: bool):
    """Path-endpoint vertex builder — SHARED with the device path
    (tpu/paths.py) so host/device rows stay byte-identical."""
    def mk_vertex(vid):
        if with_prop:
            v = qctx.build_vertex(space, vid)
            return v if v is not None else Vertex(vid)
        return Vertex(vid)
    return mk_vertex


def make_path_fn(mk_vertex):
    def path_of(vchain: List[Any], echain: List[Edge]) -> Path:
        p = Path(mk_vertex(vchain[0]))
        for v, e in zip(vchain[1:], echain):
            p.steps.append(Step(mk_vertex(v), e.name, e.ranking, e.props,
                                e.etype))
        return p
    return path_of


def sort_path_rows(rows: List[List[Any]]):
    """Canonical FIND PATH result order (row-parity contract)."""
    rows.sort(key=lambda r: (r[0].length(),
                             [str(v.vid) for v in r[0].nodes()]))


def find_path_host(node, qctx: QueryContext, ectx: ExecutionContext) -> DataSet:
    a = node.args
    space = a["space"]
    etypes = a["edge_types"]
    etype_ids = {e: qctx.store.catalog.get_edge(space, e).edge_type for e in etypes}
    direction = a["direction"]
    upto = a["upto"]
    kind = a["kind"]
    filt = a.get("filter")
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    srcs = _vids_from(a, "src_vids", "src_ref", ectx)
    dsts = _vids_from(a, "dst_vids", "dst_ref", ectx)
    dst_set = {hashable_key(d) for d in dsts}

    col = node.col_names[0]
    rows: List[List[Any]] = []
    mk_vertex = make_vertex_fn(qctx, space, bool(a.get("with_prop")))
    path_of = make_path_fn(mk_vertex)

    if kind == "shortest":
        # level-synchronous BFS per source with multi-parent tracking —
        # yields ALL shortest paths per (src, dst) pair.
        for s in srcs:
            parents: Dict[Any, List[Tuple[Any, Edge]]] = {}
            depth: Dict[Any, int] = {hashable_key(s): 0}
            frontier = [s]
            found_at: Dict[Any, int] = {}
            for level in range(1, upto + 1):
                nxt: List[Any] = []
                nxt_seen: Set = set()
                for u in frontier:
                    for e, w in _neighbors(qctx, space, u, etypes, direction,
                                           etype_ids, filt):
                        kw = hashable_key(w)
                        if kw in depth and depth[kw] < level:
                            continue
                        if kw not in depth:
                            depth[kw] = level
                        if depth[kw] == level:
                            parents.setdefault(kw, []).append((u, e))
                            if kw not in nxt_seen:
                                nxt_seen.add(kw)
                                nxt.append(w)
                        if kw in dst_set and kw not in found_at:
                            found_at[kw] = level
                frontier = nxt
                if not frontier:
                    break

            def all_paths_to(vid, kv) -> List[Tuple[List[Any], List[Edge]]]:
                if depth.get(kv, -1) == 0:
                    return [([vid], [])]
                out = []
                for (u, e) in parents.get(kv, []):
                    for (vc, ec) in all_paths_to(u, hashable_key(u)):
                        out.append((vc + [vid], ec + [e]))
                return out

            for d in dsts:
                kd = hashable_key(d)
                if hashable_key(s) == kd:
                    continue
                if kd in found_at:
                    for (vc, ec) in all_paths_to(d, kd):
                        rows.append([path_of(vc, ec)])
    else:
        def neighbors_of(cur, depth):
            for e, w in _neighbors(qctx, space, cur, etypes, direction,
                                   etype_ids, filt):
                yield e, w, w

        rows.extend(_path_dfs(
            srcs, lambda s: s, upto, neighbors_of, dst_set,
            kind == "noloop", path_of, getattr(ectx, "tracker", None)))
    sort_path_rows(rows)
    return DataSet([col], rows)


def _device_frames(qctx, space: str, starts, etypes, direction: str,
                   hops: int, filt: Optional[Expr]):
    """Shared device-driver gate for frame-replay executors (subgraph /
    all-paths): runtime + flag checks, dense-store probe, compilable
    split, the batched `traverse_hops` expansion with fallback-cause
    recording, and the host re-check closure for non-compilable
    filters.  -> (frames, edge_ok, sd) or None (take the host path)."""
    rt = getattr(qctx, "tpu_runtime", None)
    if rt is None:
        return None
    from ..utils.config import get_config
    if not get_config().get("tpu_match_device"):
        return None
    store = qctx.store
    try:
        sd = store.space(space)
        sd.dense_id
    except AttributeError:
        return None
    from ..tpu.device import TpuUnavailable
    from ..tpu.exprjit import CannotCompile, compilable
    from ..tpu.traverse import _JAX_RT_ERRORS
    dev_pred = filt if (filt is not None
                        and compilable(filt, etypes)) else None
    try:
        frames, stats = rt.traverse_hops(store, space, starts, etypes,
                                         direction, hops,
                                         edge_filter=dev_pred)
    except (CannotCompile, TpuUnavailable) + _JAX_RT_ERRORS as ex:
        qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"
        return None
    qctx.last_tpu_stats = stats
    host_check = filt is not None and dev_pred is None

    def edge_ok(e: Edge) -> bool:
        if not host_check:
            return True
        rc = RowContext(qctx, space,
                        {"_src": e.src, "_edge": e, "_dst": e.dst})
        return to_bool3(filt.eval(rc)) is True

    return frames, edge_ok, sd


def _path_dfs(srcs, src_handle, upto, neighbors_of, dst_set, noloop,
              path_of, tracker) -> List[List[Any]]:
    """The ALL/NOLOOP PATH DFS, defined ONCE for both drivers (host
    `_neighbors` scans and device hop frames): stack order, per-path
    edge dedup, NOLOOP vertex check, dst-set row emission, and memory
    charging.  neighbors_of(handle, depth) yields (Edge, next_handle,
    w_vid) with any edge filter already applied."""
    rows: List[List[Any]] = []
    pending = 0
    for s in srcs:
        h0 = src_handle(s)
        if h0 is None:
            continue
        stack: List[Tuple[Any, List[Any], List[Edge], Set]] = [
            (h0, [s], [], set())]
        while stack:
            cur, vchain, echain, eseen = stack.pop()
            if len(echain) >= upto:
                continue
            for e, nh, w in neighbors_of(cur, len(echain)):
                ek = e.key()
                if ek in eseen:
                    continue
                if noloop and any(hashable_key(w) == hashable_key(v)
                                  for v in vchain):
                    continue
                nvc, nec = vchain + [w], echain + [e]
                if hashable_key(w) in dst_set:
                    rows.append([path_of(nvc, nec)])
                stack.append((nh, nvc, nec, eseen | {ek}))
                # ALL PATHS is the worst allocator in the engine:
                # charge the search state as it grows, not after
                pending += 96 * (len(nvc) + len(eseen))
                if tracker is not None and pending > (1 << 20):
                    tracker.charge(pending)
                    pending = 0
    if tracker is not None and pending:
        tracker.charge(pending)
    return rows


def find_path_device(node, qctx: QueryContext,
                     ectx: ExecutionContext) -> Optional[DataSet]:
    """FIND ALL/NOLOOP PATH on the device plane (SURVEY §2 row 23
    AllPathsExecutor).

    One batched `traverse_hops` to `upto` captures each depth's edge
    frame (the device frontier keeps walk-reachable vertices: no global
    visited set in capture mode, so frame d holds every edge a
    depth-d walk can take); _path_dfs then replays the shared DFS over
    the in-memory frames instead of per-vertex storage scans.  Returns
    None to take the host path."""
    a = node.args
    if a["kind"] == "shortest" or a["upto"] < 1:
        return None
    space = a["space"]
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    srcs = _vids_from(a, "src_vids", "src_ref", ectx)
    dsts = _vids_from(a, "dst_vids", "dst_ref", ectx)
    if not srcs or not dsts:
        return None
    got = _device_frames(qctx, space, srcs, a["edge_types"],
                         a["direction"], a["upto"], a.get("filter"))
    if got is None:
        return None
    frames, edge_ok, sd = got

    def neighbors_of(cur, depth):
        fr = frames[depth]
        for idx in fr.out_edges(cur):
            e = fr.edges[idx]
            if edge_ok(e):
                yield e, int(fr.dst[idx]), e.dst

    mk_vertex = make_vertex_fn(qctx, space, bool(a.get("with_prop")))
    rows = _path_dfs(
        srcs, lambda s: (sd.dense_id(s) if sd.dense_id(s) >= 0 else None),
        a["upto"], neighbors_of, {hashable_key(d) for d in dsts},
        a["kind"] == "noloop", make_path_fn(mk_vertex),
        getattr(ectx, "tracker", None))
    sort_path_rows(rows)
    return DataSet([node.col_names[0]], rows)


def _subgraph_specs(a) -> List[Tuple[str, str]]:
    """(etype, direction) pairs from the plan args — ONE decoder for
    both subgraph drivers so they can never disagree on the edge set."""
    specs: List[Tuple[str, str]] = []
    for e in a.get("out_edges") or []:
        specs.append((e, "out"))
    for e in a.get("in_edges") or []:
        specs.append((e, "in"))
    for e in a.get("both_edges") or []:
        specs.append((e, "both"))
    return specs


def _subgraph_assemble(node, starts_vertices, frontier0, steps,
                       edges_of, vertex_of, yield_spec) -> DataSet:
    """The GET SUBGRAPH BFS replay, defined ONCE for both drivers (host
    `_neighbors` scans and device hop frames) so their row-identity
    contract cannot drift: frontier discovery order, cross-level
    seen-edge dedup, the final round of edges from the last level back
    into the visited set, and per-level row assembly.

    edges_of(u, step) yields (Edge, w) with any edge filter already
    applied; u/w are hashable node handles (vids on the host driver,
    dense ids on the device driver); edges_of must be callable for
    step == steps (the final round)."""
    visited = set(frontier0)
    frontier = list(frontier0)
    level_vertices: List[List[Any]] = [starts_vertices]
    level_edges: List[List[Edge]] = []
    seen_edges: Set = set()

    for step in range(steps):
        nxt, nxt_seen, edges_here = [], set(), []
        for u in frontier:
            for e, w in edges_of(u, step):
                if e.key() in seen_edges:
                    continue
                seen_edges.add(e.key())
                edges_here.append(e)
                if w not in visited:
                    visited.add(w)
                    if w not in nxt_seen:
                        nxt_seen.add(w)
                        nxt.append(w)
        level_edges.append(edges_here)
        frontier = nxt
        level_vertices.append([vertex_of(w) for w in nxt])
        if not frontier:
            break

    # final round (reference behavior): edges from the last-level
    # vertices back into the subgraph
    edges_final: List[Edge] = []
    for u in frontier:
        for e, w in edges_of(u, steps):
            if e.key() in seen_edges:
                continue
            if w in visited:
                seen_edges.add(e.key())
                edges_final.append(e)
    if edges_final:
        if len(level_edges) >= steps:
            level_edges.append(edges_final)
        else:
            level_edges[-1].extend(edges_final)

    cols = node.col_names
    rows = []
    n_levels = max(len(level_vertices), len(level_edges))
    for i in range(n_levels):
        vs = level_vertices[i] if i < len(level_vertices) else []
        es = level_edges[i] if i < len(level_edges) else []
        if not vs and not es:
            continue
        rows.append([vs if spec == "vertices" else es
                     for spec in yield_spec])
    return DataSet(list(cols), rows)


def subgraph_device(node, qctx: QueryContext,
                    ectx: ExecutionContext) -> Optional[DataSet]:
    """GET SUBGRAPH on the device plane (SURVEY §2 row 23 SubgraphExecutor).

    One batched `traverse_hops` expansion to steps+1 captures every
    hop's edge frame; _subgraph_assemble then replays the shared BFS
    over the frames — per-source CSR edge order matches the host
    get_neighbors iteration (HopFrame contract), so rows are
    byte-identical to the host path.  Returns None to take the host
    path (no runtime / flag off / mixed per-etype directions /
    non-devicable store)."""
    a = node.args
    space = a["space"]
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    starts = _vids_from(a, "vids", "src_ref", ectx)
    steps = a["steps"]
    if not starts or steps < 1:
        return None
    filt = a.get("filter")

    specs = _subgraph_specs(a)
    dirs = {d for _, d in specs}
    if len(dirs) != 1:
        return None          # mixed per-etype directions: host path
    direction = dirs.pop()
    etypes = [e for e, _ in specs]

    got = _device_frames(qctx, space, starts, etypes, direction,
                         steps + 1, filt)
    if got is None:
        return None
    frames, edge_ok, sd = got
    mk_vertex = make_vertex_fn(qctx, space, a.get("with_prop"))
    dense0 = [sd.dense_id(v) for v in starts]

    def edges_of(u, step):
        fr = frames[step]
        for idx in fr.out_edges(u):
            e = fr.edges[idx]
            if edge_ok(e):
                yield e, int(fr.dst[idx])

    return _subgraph_assemble(
        node, [mk_vertex(s) for s in starts],
        [d for d in dense0 if d >= 0], steps, edges_of,
        lambda w: mk_vertex(sd.vid_of_dense(w)),
        a.get("yield") or ["vertices", "edges"])


def subgraph_host(node, qctx: QueryContext, ectx: ExecutionContext) -> DataSet:
    a = node.args
    space = a["space"]
    cat = qctx.store.catalog
    if node.input_vars:
        a = dict(a)
        a["__input_var"] = node.input_vars[0]
    starts = _vids_from(a, "vids", "src_ref", ectx)
    steps = a["steps"]
    filt = a.get("filter")

    specs = _subgraph_specs(a)
    etype_ids = {e: cat.get_edge(space, e).edge_type for e, _ in specs}

    mk_vertex = make_vertex_fn(qctx, space, a.get("with_prop"))

    def edges_of(u, step):
        for et, d in specs:
            yield from _neighbors(qctx, space, u, [et], d,
                                  {et: etype_ids[et]}, filt)

    return _subgraph_assemble(
        node, [mk_vertex(s) for s in starts], list(starts), steps,
        edges_of, mk_vertex, a.get("yield") or ["vertices", "edges"])
