"""Schema catalog: spaces, tags, edge types — versioned, like the
reference's meta schema processors (reference: src/meta/processors/schema/
+ src/common/meta [UNVERIFIED — empty mount, SURVEY §0]).

A Space is the top container (graph + partition count + vid type).  Tags and
edge types carry typed, defaultable, nullable, TTL-able property columns and
are versioned: altering a schema appends a new version; rows remember the
version they were written with.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

from ..core.value import (NULL, Date, DateTime, Duration, Time, is_null)


class PropType(Enum):
    BOOL = "bool"
    INT64 = "int64"
    INT32 = "int32"
    INT16 = "int16"
    INT8 = "int8"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"
    FIXED_STRING = "fixed_string"
    TIMESTAMP = "timestamp"
    DATE = "date"
    TIME = "time"
    DATETIME = "datetime"
    DURATION = "duration"
    GEOGRAPHY = "geography"

    @classmethod
    def parse(cls, s: str) -> "PropType":
        s = s.strip().lower()
        alias = {"int": "int64", "integer": "int64", "str": "string"}
        s = alias.get(s, s)
        if s.startswith("fixed_string"):
            return cls.FIXED_STRING
        return cls(s)


_INT_TYPES = (PropType.INT64, PropType.INT32, PropType.INT16, PropType.INT8,
              PropType.TIMESTAMP)
_FLOAT_TYPES = (PropType.FLOAT, PropType.DOUBLE)


def check_type(t: PropType, v: Any) -> bool:
    if is_null(v):
        return True
    if t == PropType.BOOL:
        return isinstance(v, bool)
    if t in _INT_TYPES:
        return isinstance(v, int) and not isinstance(v, bool)
    if t in _FLOAT_TYPES:
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if t in (PropType.STRING, PropType.FIXED_STRING):
        return isinstance(v, str)
    if t == PropType.DATE:
        return isinstance(v, Date)
    if t == PropType.TIME:
        return isinstance(v, Time)
    if t == PropType.DATETIME:
        return isinstance(v, DateTime)
    if t == PropType.DURATION:
        return isinstance(v, Duration)
    if t == PropType.GEOGRAPHY:
        from ..core.geo import Geography
        return isinstance(v, Geography)
    return True


def coerce(t: PropType, v: Any) -> Any:
    """Insert-time coercion (int→float for double columns; WKT text for
    geography columns)."""
    if is_null(v):
        return v
    if t in _FLOAT_TYPES and isinstance(v, int) and not isinstance(v, bool):
        return float(v)
    if t == PropType.GEOGRAPHY and isinstance(v, str):
        from ..core.geo import GeoError, from_wkt
        try:
            return from_wkt(v)
        except GeoError:
            return v            # check_type rejects with a clean error
    return v


@dataclass
class PropDef:
    name: str
    ptype: PropType
    nullable: bool = True
    default: Any = None          # None = no default
    has_default: bool = False
    fixed_len: int = 0           # FIXED_STRING length
    comment: str = ""

    def to_dict(self):
        return {"name": self.name, "type": self.ptype.value,
                "nullable": self.nullable, "default": self.default,
                "has_default": self.has_default, "fixed_len": self.fixed_len}


@dataclass
class SchemaVersion:
    version: int
    props: List[PropDef]
    ttl_col: str = ""
    ttl_duration: int = 0

    def prop(self, name: str) -> Optional[PropDef]:
        for p in self.props:
            if p.name == name:
                return p
        return None

    def prop_names(self) -> List[str]:
        return [p.name for p in self.props]


@dataclass
class TagSchema:
    name: str
    tag_id: int
    versions: List[SchemaVersion] = field(default_factory=list)

    @property
    def latest(self) -> SchemaVersion:
        return self.versions[-1]


@dataclass
class EdgeSchema:
    name: str
    edge_type: int               # positive id; -id is the reversed direction
    versions: List[SchemaVersion] = field(default_factory=list)

    @property
    def latest(self) -> SchemaVersion:
        return self.versions[-1]


class SchemaError(Exception):
    pass


@dataclass
class SpaceDesc:
    name: str
    space_id: int
    partition_num: int = 8
    replica_factor: int = 1
    vid_type: str = "FIXED_STRING(32)"  # or "INT64"
    comment: str = ""

    def vid_is_int(self) -> bool:
        return self.vid_type.strip().upper().startswith("INT")

    def check_vid(self, vid) -> None:
        """Write-path vid conformance (reference semantics: a write with
        the wrong vid type is an error, never a silent coercion)."""
        if self.vid_is_int():
            if not isinstance(vid, int) or isinstance(vid, bool):
                raise SchemaError(
                    f"vid {vid!r} does not match vid_type "
                    f"{self.vid_type}")
            return
        if not isinstance(vid, str):
            raise SchemaError(
                f"vid {vid!r} does not match vid_type {self.vid_type}")
        vt = self.vid_type.strip().upper()
        if vt.startswith("FIXED_STRING(") and vt.endswith(")"):
            try:
                cap = int(vt[len("FIXED_STRING("):-1])
            except ValueError:
                return
            if len(vid.encode()) > cap:
                raise SchemaError(
                    f"vid {vid!r} exceeds {self.vid_type}")


ROLES = ("GOD", "ADMIN", "DBA", "USER", "GUEST")
ROLE_RANK = {r: i for i, r in enumerate(reversed(ROLES))}


def hash_password(pw: str) -> str:
    import hashlib
    return hashlib.sha256(("nebula::" + pw).encode()).hexdigest()


class UserDesc:
    """One account: password hash + per-space role grants.  The root
    account carries the global GOD role (space key "")."""
    __slots__ = ("name", "pwd_hash", "roles")

    def __init__(self, name: str, pwd_hash: str,
                 roles: Optional[Dict[str, str]] = None):
        self.name = name
        self.pwd_hash = pwd_hash
        self.roles = dict(roles or {})

    def check_password(self, pw: str) -> bool:
        return self.pwd_hash == hash_password(pw)


class Catalog:
    """Space/tag/edge/user catalog — the metad schema plane,
    single-process form.

    The cluster metad (nebula_tpu.cluster.meta) wraps this with Raft +
    heartbeat distribution; executors always read through this interface.
    User/role management mirrors the reference's meta user plane
    (PermissionManager's backing store; reference: src/meta processors
    + src/graph/service/PermissionManager [UNVERIFIED — empty mount,
    SURVEY §2 row 26]).
    """

    def __init__(self):
        self.spaces: Dict[str, SpaceDesc] = {}
        self._tags: Dict[int, Dict[str, TagSchema]] = {}      # space_id →
        self._edges: Dict[int, Dict[str, EdgeSchema]] = {}
        self._indexes: Dict[int, Dict[str, "IndexDesc"]] = {}
        self._ft_indexes: Dict[int, Dict[str, "IndexDesc"]] = {}
        self._listeners: Dict[int, List[List[str]]] = {}  # [type, endpoint]
        self._next_space = 1
        self._next_schema_id: Dict[int, int] = {}
        self.version = 0   # bumped on every DDL; clients use it for cache TTL
        self.users: Dict[str, UserDesc] = {
            "root": UserDesc("root", hash_password("nebula"), {"": "GOD"})}

    # -- users / roles --
    def create_user(self, name: str, password: str,
                    if_not_exists=False) -> UserDesc:
        if name in self.users:
            if if_not_exists:
                return self.users[name]
            raise SchemaError(f"user `{name}' already exists")
        u = UserDesc(name, hash_password(password))
        self.users[name] = u
        self.version += 1
        return u

    def create_user_hashed(self, name: str, pwd_hash: str,
                           if_not_exists=False) -> UserDesc:
        """Replay/replication form: the hash IS the payload, so durable
        logs (standalone journal, metad raft WAL) never see plaintext."""
        if name in self.users:
            if if_not_exists:
                return self.users[name]
            raise SchemaError(f"user `{name}' already exists")
        u = UserDesc(name, pwd_hash)
        self.users[name] = u
        self.version += 1
        return u

    def set_password_hash(self, name: str, pwd_hash: str):
        self.get_user(name).pwd_hash = pwd_hash
        self.version += 1

    def change_password_hashed(self, name: str, old_hash: str,
                               new_hash: str):
        """CHANGE PASSWORD's check-and-set, replayed INSIDE the state
        machine: validating the old password against a client's cached
        catalog would let a stale (already-rotated) credential authorize
        the change."""
        u = self.get_user(name)
        if u.pwd_hash != old_hash:
            raise SchemaError("old password mismatch")
        u.pwd_hash = new_hash
        self.version += 1

    def drop_user(self, name: str, if_exists=False):
        if name == "root":
            raise SchemaError("the root user cannot be dropped")
        if name not in self.users:
            if if_exists:
                return
            raise SchemaError(f"user `{name}' not found")
        del self.users[name]
        self.version += 1

    def get_user(self, name: str) -> UserDesc:
        u = self.users.get(name)
        if u is None:
            raise SchemaError(f"user `{name}' not found")
        return u

    def alter_user(self, name: str, password: str):
        self.get_user(name).pwd_hash = hash_password(password)
        self.version += 1

    def change_password(self, name: str, old: str, new: str):
        u = self.get_user(name)
        if not u.check_password(old):
            raise SchemaError("old password mismatch")
        u.pwd_hash = hash_password(new)
        self.version += 1

    def grant_role(self, user: str, space: str, role: str):
        role = role.upper()
        if role not in ROLES or role == "GOD":
            raise SchemaError(f"role `{role}' cannot be granted")
        self.get_space(space)
        self.get_user(user).roles[space] = role
        self.version += 1

    def revoke_role(self, user: str, space: str, role: Optional[str] = None):
        u = self.get_user(user)
        cur = u.roles.get(space)
        if cur is None:
            raise SchemaError(
                f"user `{user}' has no role on space `{space}'")
        if role is not None and cur != role.upper():
            raise SchemaError(
                f"user `{user}' holds `{cur}' on `{space}', not "
                f"`{role.upper()}'")
        del u.roles[space]
        self.version += 1

    def role_of(self, user: str, space: Optional[str]) -> Optional[str]:
        u = self.users.get(user)
        if u is None:
            return None
        if u.roles.get("") == "GOD":
            return "GOD"
        return u.roles.get(space) if space else None

    # -- spaces --
    def create_space(self, name: str, partition_num=8, replica_factor=1,
                     vid_type="FIXED_STRING(32)", if_not_exists=False) -> SpaceDesc:
        if name in self.spaces:
            if if_not_exists:
                return self.spaces[name]
            raise SchemaError(f"space `{name}' already exists")
        sp = SpaceDesc(name, self._next_space, partition_num, replica_factor, vid_type)
        self._next_space += 1
        self.spaces[name] = sp
        self._tags[sp.space_id] = {}
        self._edges[sp.space_id] = {}
        self._indexes[sp.space_id] = {}
        self._ft_indexes[sp.space_id] = {}
        self._listeners[sp.space_id] = []
        self._next_schema_id[sp.space_id] = 2  # 1 reserved
        self.version += 1
        return sp

    def drop_space(self, name: str, if_exists=False) -> Optional[SpaceDesc]:
        sp = self.spaces.pop(name, None)
        if sp is None:
            if if_exists:
                return None
            raise SchemaError(f"space `{name}' not found")
        self._tags.pop(sp.space_id, None)
        self._edges.pop(sp.space_id, None)
        self._indexes.pop(sp.space_id, None)
        self._ft_indexes.pop(sp.space_id, None)
        self._listeners.pop(sp.space_id, None)
        for u in self.users.values():
            u.roles.pop(name, None)
        self.version += 1
        return sp

    def get_space(self, name: str) -> SpaceDesc:
        sp = self.spaces.get(name)
        if sp is None:
            raise SchemaError(f"space `{name}' not found")
        return sp

    # -- tags / edges --
    def _alloc_id(self, space_id: int) -> int:
        i = self._next_schema_id[space_id]
        self._next_schema_id[space_id] = i + 1
        return i

    def create_tag(self, space: str, name: str, props: List[PropDef],
                   if_not_exists=False, ttl_col="", ttl_duration=0) -> TagSchema:
        sp = self.get_space(space)
        tags = self._tags[sp.space_id]
        if name in tags:
            if if_not_exists:
                return tags[name]
            raise SchemaError(f"tag `{name}' already exists")
        if name in self._edges[sp.space_id]:
            raise SchemaError(f"`{name}' conflicts with an edge type")
        t = TagSchema(name, self._alloc_id(sp.space_id),
                      [SchemaVersion(0, props, ttl_col, ttl_duration)])
        tags[name] = t
        self.version += 1
        return t

    def create_edge(self, space: str, name: str, props: List[PropDef],
                    if_not_exists=False, ttl_col="", ttl_duration=0) -> EdgeSchema:
        sp = self.get_space(space)
        edges = self._edges[sp.space_id]
        if name in edges:
            if if_not_exists:
                return edges[name]
            raise SchemaError(f"edge `{name}' already exists")
        if name in self._tags[sp.space_id]:
            raise SchemaError(f"`{name}' conflicts with a tag")
        e = EdgeSchema(name, self._alloc_id(sp.space_id),
                       [SchemaVersion(0, props, ttl_col, ttl_duration)])
        edges[name] = e
        self.version += 1
        return e

    def alter_tag(self, space: str, name: str, props: List[PropDef],
                  ttl_col=None, ttl_duration=None) -> TagSchema:
        t = self.get_tag(space, name)
        last = t.latest
        t.versions.append(SchemaVersion(
            last.version + 1, props,
            last.ttl_col if ttl_col is None else ttl_col,
            last.ttl_duration if ttl_duration is None else ttl_duration))
        self.version += 1
        return t

    def alter_edge(self, space: str, name: str, props: List[PropDef],
                   ttl_col=None, ttl_duration=None) -> EdgeSchema:
        e = self.get_edge(space, name)
        last = e.latest
        e.versions.append(SchemaVersion(
            last.version + 1, props,
            last.ttl_col if ttl_col is None else ttl_col,
            last.ttl_duration if ttl_duration is None else ttl_duration))
        self.version += 1
        return e

    def drop_tag(self, space: str, name: str, if_exists=False):
        sp = self.get_space(space)
        if self._tags[sp.space_id].pop(name, None) is None and not if_exists:
            raise SchemaError(f"tag `{name}' not found")
        self.version += 1

    def drop_edge(self, space: str, name: str, if_exists=False):
        sp = self.get_space(space)
        if self._edges[sp.space_id].pop(name, None) is None and not if_exists:
            raise SchemaError(f"edge `{name}' not found")
        self.version += 1

    def get_tag(self, space: str, name: str) -> TagSchema:
        sp = self.get_space(space)
        t = self._tags[sp.space_id].get(name)
        if t is None:
            raise SchemaError(f"tag `{name}' not found in space `{space}'")
        return t

    def get_edge(self, space: str, name: str) -> EdgeSchema:
        sp = self.get_space(space)
        e = self._edges[sp.space_id].get(name)
        if e is None:
            raise SchemaError(f"edge `{name}' not found in space `{space}'")
        return e

    def tags(self, space: str) -> List[TagSchema]:
        return list(self._tags[self.get_space(space).space_id].values())

    def edges(self, space: str) -> List[EdgeSchema]:
        return list(self._edges[self.get_space(space).space_id].values())

    def edge_by_type(self, space: str, etype: int) -> EdgeSchema:
        for e in self.edges(space):
            if e.edge_type == abs(etype):
                return e
        raise SchemaError(f"edge type {etype} not found")

    # -- secondary indexes --
    def create_index(self, space: str, index_name: str, schema_name: str,
                     fields: List[str], is_edge: bool, if_not_exists=False,
                     field_lens: Optional[List[int]] = None) -> "IndexDesc":
        sp = self.get_space(space)
        idxs = self._indexes[sp.space_id]
        if index_name in idxs:
            if if_not_exists:
                return idxs[index_name]
            raise SchemaError(f"index `{index_name}' already exists")
        # validate target schema + fields exist
        schema = (self.get_edge(space, schema_name) if is_edge
                  else self.get_tag(space, schema_name))
        lens = list(field_lens) if field_lens else [0] * len(fields)
        if len(lens) != len(fields):
            raise SchemaError("index field/length count mismatch")
        for f, ln in zip(fields, lens):
            p = schema.latest.prop(f)
            if p is None:
                raise SchemaError(f"prop `{f}' not in `{schema_name}'")
            if ln:
                if p.ptype not in (PropType.STRING, PropType.FIXED_STRING):
                    raise SchemaError(
                        f"prefix length only applies to string props "
                        f"(`{f}' is {p.ptype.value})")
        d = IndexDesc(index_name, schema_name, list(fields), is_edge,
                      index_id=self._alloc_id(sp.space_id),
                      field_lens=lens)
        idxs[index_name] = d
        self.version += 1
        return d

    def drop_index(self, space: str, index_name: str, if_exists=False):
        sp = self.get_space(space)
        if self._indexes[sp.space_id].pop(index_name, None) is None and not if_exists:
            raise SchemaError(f"index `{index_name}' not found")
        self.version += 1

    def indexes(self, space: str) -> List["IndexDesc"]:
        return list(self._indexes[self.get_space(space).space_id].values())

    def indexes_for(self, space: str, schema_name: str, is_edge: bool) -> List["IndexDesc"]:
        return [d for d in self.indexes(space)
                if d.schema_name == schema_name and d.is_edge == is_edge]

    # -- full-text indexes + listeners (SURVEY §2 row 10 Listener; the
    # reference's ES-backed text-search plane) --
    def create_fulltext_index(self, space: str, index_name: str,
                              schema_name: str, field: str, is_edge: bool,
                              if_not_exists=False) -> "IndexDesc":
        sp = self.get_space(space)
        idxs = self._ft_indexes.setdefault(sp.space_id, {})
        if index_name in idxs:
            if if_not_exists:
                return idxs[index_name]
            raise SchemaError(f"fulltext index `{index_name}' already exists")
        schema = (self.get_edge(space, schema_name) if is_edge
                  else self.get_tag(space, schema_name))
        p = schema.latest.prop(field)
        if p is None:
            raise SchemaError(f"prop `{field}' not in `{schema_name}'")
        if p.ptype not in (PropType.STRING, PropType.FIXED_STRING):
            raise SchemaError(
                f"fulltext index requires a string prop; `{field}' "
                f"is {p.ptype.value}")
        d = IndexDesc(index_name, schema_name, [field], is_edge,
                      index_id=self._alloc_id(sp.space_id), fulltext=True)
        idxs[index_name] = d
        self.version += 1
        return d

    def drop_fulltext_index(self, space: str, index_name: str,
                            if_exists=False):
        sp = self.get_space(space)
        idxs = self._ft_indexes.setdefault(sp.space_id, {})
        if idxs.pop(index_name, None) is None and not if_exists:
            raise SchemaError(f"fulltext index `{index_name}' not found")
        self.version += 1

    def fulltext_indexes(self, space: str) -> List["IndexDesc"]:
        sid = self.get_space(space).space_id
        return list(self._ft_indexes.get(sid, {}).values())

    def fulltext_indexes_for(self, space: str, schema_name: str,
                             is_edge: bool) -> List["IndexDesc"]:
        return [d for d in self.fulltext_indexes(space)
                if d.schema_name == schema_name and d.is_edge == is_edge]

    def add_listener(self, space: str, ltype: str, endpoint: str):
        sid = self.get_space(space).space_id
        ls = self._listeners.setdefault(sid, [])
        if any(t == ltype for t, _ in ls):
            raise SchemaError(f"listener {ltype} already added")
        ls.append([ltype, endpoint])
        self.version += 1

    def remove_listener(self, space: str, ltype: str):
        sid = self.get_space(space).space_id
        ls = self._listeners.setdefault(sid, [])
        keep = [x for x in ls if x[0] != ltype]
        if len(keep) == len(ls):
            raise SchemaError(f"no {ltype} listener on `{space}'")
        self._listeners[sid] = keep
        self.version += 1

    def listeners(self, space: str) -> List[List[str]]:
        return list(self._listeners.get(
            self.get_space(space).space_id, []))


@dataclass
class IndexDesc:
    name: str
    schema_name: str
    fields: List[str]
    is_edge: bool
    # unique per creation: DROP + re-CREATE with the same name/fields must
    # NOT resurrect the old entries (the store compares this id)
    index_id: int = 0
    # full-text (ES-listener-backed in the reference) vs secondary B-tree
    fulltext: bool = False
    # per-field string prefix length, 0 = full value (reference:
    # CREATE TAG INDEX i ON t(name(10)) truncates the key)
    field_lens: List[int] = field(default_factory=list)


def fill_row(sv: SchemaVersion, row: Dict[str, Any]) -> Dict[str, Any]:
    """Read-side schema upgrade: a row written before ALTER ... ADD is
    served with the latest version's defaults (or NULL) for the added
    props — the reference's versioned RowReader fallback (SURVEY §2
    row 9).  Returns a copy only when something is missing."""
    missing = [p for p in sv.props if p.name not in row]
    if not missing:
        return row
    out = dict(row)
    for p in missing:
        if p.has_default:
            # coerce exactly like insert-time apply_defaults, so an
            # upgraded row is type-identical to a fresh one (e.g. a
            # double default written as int, a geography as WKT text)
            try:
                out[p.name] = coerce(p.ptype, p.default)
            except Exception:  # noqa: BLE001 — malformed default:
                out[p.name] = NULL   # degrade exactly like the device
                # column encode (csr.py), keeping host/device parity
        else:
            out[p.name] = NULL
    return out


def apply_defaults(sv: SchemaVersion, props: Dict[str, Any],
                   insert_names: Optional[List[str]] = None) -> Dict[str, Any]:
    """Fill defaults / validate nullability for an insert row."""
    out: Dict[str, Any] = {}
    for p in sv.props:
        if p.name in props:
            v = coerce(p.ptype, props[p.name])
            if is_null(v) and not p.nullable:
                raise SchemaError(
                    f"prop `{p.name}' is NOT NULL")
            if not check_type(p.ptype, v):
                raise SchemaError(
                    f"prop `{p.name}' expects {p.ptype.value}, got {type(v).__name__}")
            out[p.name] = v
        elif p.has_default:
            out[p.name] = coerce(p.ptype, p.default)
        elif p.nullable:
            out[p.name] = NULL
        else:
            raise SchemaError(f"prop `{p.name}' is NOT NULL and has no default")
    if insert_names:
        for n in insert_names:
            if sv.prop(n) is None:
                raise SchemaError(f"unknown prop `{n}'")
    return out
