"""CSR snapshot builder: the device-resident serving copy of a space.

This is the TPU-build replacement for the reference's per-request RocksDB
prefix scans (GetNeighborsProcessor's vid-prefix iteration; reference:
src/storage/query + src/storage/exec [UNVERIFIED — empty mount, SURVEY §0]):
instead of decoding rows per request, each partition's adjacency and
property columns are exported ONCE per epoch as static-shaped arrays that
get pinned into TPU HBM (one partition per chip / mesh slot).

Layout decisions (SURVEY §7):
  * One CSR block per (edge type, direction): type-filtered traversal
    selects a block — the EP analog, no routing overhead.
  * All parts padded to common shapes (Vmax rows, Emax edges) so the whole
    snapshot is a single (P, ...) array stack that `shard_map` splits over
    the 'part' mesh axis with NO per-part recompilation.
  * Dense vids encode their partition: owner(d) = d % P, local(d) = d // P.
  * Strings dict-encoded against a per-space pool → int32 codes; predicates
    on strings become int compares on device.
  * NULL sentinels inside columns: int → INT64_MIN, float → NaN,
    string-code → -1.  (Filter semantics drop non-true rows, so sentinel
    compares naturally evaluate not-true.)

Row order inside a block matches GraphStore.get_neighbors exactly
(src local-idx, then (rank, neighbor)) — the parity contract between the
host oracle and the device path.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.value import Date, DateTime, Time, is_null
from .schema import PropType, SchemaVersion
from .store import GraphStore, SpaceData, _nbr_key

INT_NULL = np.iinfo(np.int64).min
CODE_NULL = -1


class StringPool:
    """Per-space string dictionary: str ↔ int32 code."""

    def __init__(self):
        self.strings: List[str] = []
        self.codes: Dict[str, int] = {}

    def encode(self, s: str) -> int:
        c = self.codes.get(s)
        if c is None:
            c = len(self.strings)
            self.strings.append(s)
            self.codes[s] = c
        return c

    def lookup(self, s: str) -> int:
        """Encode WITHOUT inserting (query-time constant); -2 if absent
        (matches nothing, unlike the null sentinel -1)."""
        return self.codes.get(s, -2)

    def obj_array(self) -> "np.ndarray":
        """Cached object-dtype view of the dictionary for batched decode
        (rebuilding it per decode call would be O(|pool|) per query)."""
        arr = getattr(self, "_obj_arr", None)
        if arr is None or len(arr) != len(self.strings):
            arr = np.asarray(self.strings, dtype=object)
            self._obj_arr = arr
        return arr

    def decode(self, c: int) -> Optional[str]:
        if 0 <= c < len(self.strings):
            return self.strings[c]
        return None

    def __len__(self):
        return len(self.strings)


def _col_dtype(pt: PropType):
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        return np.float64
    return np.int64  # ints, bools, strings (codes), temporal (encoded)


def _encode_default(pd, pool: StringPool):
    """Encoded, coerced schema default for pre-ALTER rows (fill_row
    parity), or None when there is no usable default — shared by the
    edge-block and tag-table builders."""
    if not pd.has_default:
        return None
    try:
        from .schema import coerce
        return encode_prop(pd.ptype, coerce(pd.ptype, pd.default), pool)
    except Exception:  # noqa: BLE001 — malformed default → NULL, same
        return None    # degradation as host fill_row


def encode_prop(pt: PropType, v: Any, pool: StringPool) -> Any:
    if is_null(v):
        return np.nan if pt in (PropType.FLOAT, PropType.DOUBLE) else INT_NULL
    if pt in (PropType.STRING, PropType.FIXED_STRING):
        return pool.encode(v)
    if pt == PropType.GEOGRAPHY:
        return pool.encode(v.wkt())     # dictionary-encoded WKT
    if pt == PropType.BOOL:
        return int(v)
    if pt == PropType.DATE:
        return v.days_since_epoch()
    if pt == PropType.DATETIME:
        # epoch-microseconds computed from calendar fields: lossless AND
        # monotonic across the epoch (to_timestamp() truncates toward zero,
        # which mis-encodes pre-1970 values)
        import datetime as _dt
        delta = (_dt.datetime(v.year, v.month, v.day, v.hour, v.minute,
                              v.sec, v.microsec, tzinfo=_dt.timezone.utc)
                 - _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc))
        return ((delta.days * 86400 + delta.seconds) * 1_000_000
                + delta.microseconds)
    if pt == PropType.TIME:
        return ((v.hour * 60 + v.minute) * 60 + v.sec) * 1_000_000 + v.microsec
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        return float(v)
    return int(v)


def decode_prop_column(pt: PropType, raw: "np.ndarray",
                       pool: StringPool) -> List[Any]:
    """Batched decode of a whole property column (same semantics as
    decode_prop per element, ~20× faster than calling it in a loop —
    the TPU materialization path decodes hundreds of thousands of final
    edges per query)."""
    from ..core.value import NULL
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        a = raw.astype(np.float64)
        if not np.isnan(a).any():       # no-null fast path: one C tolist
            return a.tolist()
        return [NULL if x != x else x for x in a.tolist()]
    av = raw.astype(np.int64)
    if pt in (PropType.STRING, PropType.FIXED_STRING):
        strings = pool.strings
        ns = len(strings)
        if av.size and ((av >= 0) & (av < ns)).all():
            return pool.obj_array()[av].tolist()
        vals = av.tolist()
        return [strings[r] if 0 <= r < ns else NULL for r in vals]
    vals = av.tolist()
    if pt == PropType.BOOL:
        return [NULL if r == INT_NULL else bool(r) for r in vals]
    if pt in (PropType.DATE, PropType.DATETIME, PropType.TIME,
              PropType.DURATION, PropType.GEOGRAPHY):
        return [decode_prop(pt, r, pool) for r in vals]
    if not (av == INT_NULL).any():      # no-null fast path
        return vals
    return [NULL if r == INT_NULL else r for r in vals]


def decode_prop_column_np(pt: PropType, raw: "np.ndarray",
                          pool: StringPool) -> "np.ndarray":
    """decode_prop_column, columnar: returns a numpy array — native
    numeric dtype on the null-free fast paths, object dtype otherwise —
    creating NO per-element Python objects on the fast paths.  Feeds the
    ColumnarDataSet result handle (device results stay columnar until
    the wire/print boundary)."""
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        a = raw.astype(np.float64)
        if not np.isnan(a).any():
            return a
    elif pt in (PropType.STRING, PropType.FIXED_STRING):
        av = raw.astype(np.int64)
        ns = len(pool.strings)
        if av.size == 0 or ((av >= 0) & (av < ns)).all():
            return pool.obj_array()[av]
    elif pt not in (PropType.BOOL, PropType.DATE, PropType.DATETIME,
                    PropType.TIME, PropType.DURATION, PropType.GEOGRAPHY):
        av = raw.astype(np.int64)
        if not (av == INT_NULL).any():
            return av
    out = np.empty(len(raw), dtype=object)
    out[:] = decode_prop_column(pt, raw, pool)
    return out


def decode_prop(pt: PropType, raw: Any, pool: StringPool) -> Any:
    """Exact inverse of encode_prop (sentinels → NULL)."""
    import datetime as _dt

    from ..core.value import NULL
    if pt in (PropType.FLOAT, PropType.DOUBLE):
        f = float(raw)
        return NULL if np.isnan(f) else f
    r = int(raw)
    if r == INT_NULL:
        return NULL
    if pt in (PropType.STRING, PropType.FIXED_STRING):
        s = pool.decode(r)
        return NULL if s is None else s
    if pt == PropType.BOOL:
        return bool(r)
    if pt == PropType.DATE:
        d = _dt.date(1970, 1, 1) + _dt.timedelta(days=r)
        return Date(d.year, d.month, d.day)
    if pt == PropType.DATETIME:
        ts, us = divmod(r, 1_000_000)
        d = _dt.datetime.fromtimestamp(ts, _dt.timezone.utc)
        return DateTime(d.year, d.month, d.day, d.hour, d.minute, d.second, us)
    if pt == PropType.TIME:
        us = r % 1_000_000
        sec = r // 1_000_000
        return Time(sec // 3600, (sec // 60) % 60, sec % 60, us)
    if pt == PropType.GEOGRAPHY:
        from ..core.geo import from_wkt
        s = pool.decode(r)
        return NULL if s is None else from_wkt(s)
    return r


@dataclass
class CsrBlock:
    """One (edge-type, direction) adjacency across ALL parts, padded.

    indptr : (P, Vmax+1) int32 — per part, CSR row pointers over local idx
    nbr    : (P, Emax) int32   — dense id of neighbor (dst for out, src for in)
    rank   : (P, Emax) int32
    props  : name → (P, Emax) int64/float64 — edge property columns
    """
    etype: str
    direction: str               # "out" | "in"
    indptr: np.ndarray
    nbr: np.ndarray
    rank: np.ndarray
    props: Dict[str, np.ndarray] = field(default_factory=dict)
    prop_types: Dict[str, PropType] = field(default_factory=dict)

    @property
    def num_parts(self) -> int:
        return self.indptr.shape[0]

    def edges_of_part(self, p: int) -> int:
        return int(self.indptr[p, -1])

    def total_edges(self) -> int:
        return int(self.indptr[:, -1].sum())


@dataclass
class TagTable:
    """Vertex property columns for one tag, aligned to local idx per part.

    present: (P, Vmax) bool; props: name → (P, Vmax).
    """
    tag: str
    present: np.ndarray
    props: Dict[str, np.ndarray] = field(default_factory=dict)
    prop_types: Dict[str, PropType] = field(default_factory=dict)


@dataclass
class CsrSnapshot:
    """Epoch-tagged, device-shippable snapshot of one space."""
    space: str
    epoch: int
    num_parts: int
    vmax: int                               # padded local-vertex count
    num_vertices: np.ndarray                # (P,) actual local counts
    blocks: Dict[Tuple[str, str], CsrBlock] = field(default_factory=dict)
    tags: Dict[str, TagTable] = field(default_factory=dict)
    pool: StringPool = field(default_factory=StringPool)
    dense_to_vid: List[Any] = field(default_factory=list)
    # degree_split(): dense ids of supernodes whose adjacency is split
    # across parts as H extra "hub rows" per block (None = unsplit)
    hub_dense: Optional[np.ndarray] = None

    def block(self, etype: str, direction: str = "out") -> CsrBlock:
        return self.blocks[(etype, direction)]

    def owner(self, dense: int) -> int:
        return dense % self.num_parts

    def local(self, dense: int) -> int:
        return dense // self.num_parts

    def dense(self, local: int, part: int) -> int:
        return local * self.num_parts + part

    def hbm_bytes(self) -> int:
        total = self.num_vertices.nbytes
        for b in self.blocks.values():
            total += b.indptr.nbytes + b.nbr.nbytes + b.rank.nbytes
            total += sum(a.nbytes for a in b.props.values())
        for t in self.tags.values():
            total += t.present.nbytes + sum(a.nbytes for a in t.props.values())
        return total


def build_snapshot(store: GraphStore, space: str,
                   edge_types: Optional[List[str]] = None,
                   tags: Optional[List[str]] = None,
                   directions: Tuple[str, ...] = ("out", "in"),
                   edge_props: Optional[Dict[str, List[str]]] = None,
                   tag_props: Optional[Dict[str, List[str]]] = None,
                   vmax_extra: int = 0) -> CsrSnapshot:
    """Export a space into a CsrSnapshot (numpy; device transfer in tpu/).

    edge_props / tag_props restrict which property columns are exported
    (None = all): the HBM-budget knob.  vmax_extra reserves extra padded
    local rows (ISSUE 19: the delta plane places freshly inserted
    vertices into the slack instead of forcing a full re-pin).
    """
    sd: SpaceData = store.space(space)
    with sd.lock:
        P = sd.num_parts
        vmax = max(sd.part_counts) if sd.part_counts else 0
        vmax = max(vmax, 1) + max(int(vmax_extra), 0)
        snap = CsrSnapshot(space=space, epoch=sd.epoch, num_parts=P, vmax=vmax,
                           num_vertices=np.asarray(sd.part_counts, np.int32),
                           dense_to_vid=list(sd.dense_to_vid))
        etypes = edge_types
        if etypes is None:
            etypes = sorted(e.name for e in store.catalog.edges(space))
        tag_names = tags
        if tag_names is None:
            tag_names = sorted(t.name for t in store.catalog.tags(space))

        for et in etypes:
            sv = store.catalog.get_edge(space, et).latest
            want = None if edge_props is None else edge_props.get(et, [])
            for direction in directions:
                snap.blocks[(et, direction)] = _build_block(
                    sd, et, direction, sv, snap.pool, vmax, want)

        for tg in tag_names:
            sv = store.catalog.get_tag(space, tg).latest
            want = None if tag_props is None else tag_props.get(tg, [])
            snap.tags[tg] = _build_tag_table(sd, tg, sv, snap.pool, vmax, want)
        return snap


def _build_block(sd: SpaceData, etype: str, direction: str,
                 sv: SchemaVersion, pool: StringPool, vmax: int,
                 want_props: Optional[List[str]]) -> CsrBlock:
    """COO collection (one pass over the plane dicts) + the native
    COO→padded-CSR kernel (nebula_tpu.native; NumPy fallback inside) —
    sort order (local, rank, dst per _nbr_key) matches get_neighbors."""
    from ..native.kernels import build_coo_csr, dst_sort_key
    P = sd.num_parts
    plane_attr = "out_edges" if direction == "out" else "in_edges"
    prop_defs = [p for p in sv.props
                 if want_props is None or p.name in want_props]

    import time as _time

    from .store import ttl_expired
    now = _time.time()
    has_ttl = bool(sv.ttl_col) and sv.ttl_duration > 0
    src_dense: List[int] = []
    dst_dense: List[int] = []
    ranks: List[int] = []
    dst_vids: List[Any] = []
    rows: List[Dict[str, Any]] = []
    for p in range(P):
        plane = getattr(sd.parts[p], plane_attr)
        for vid, per in plane.items():
            em = per.get(etype)
            if not em:
                continue
            sdense = sd.vid_to_dense[vid]
            for (rk, other), row in em.items():
                if has_ttl and ttl_expired(sv, row, now):
                    continue        # device parity with host read filter
                src_dense.append(sdense)
                dst_dense.append(sd.vid_to_dense.get(other, -1))
                ranks.append(rk)
                dst_vids.append(other)
                rows.append(row)

    indptr, nbr, rank, perm, emax = build_coo_csr(
        np.asarray(src_dense, np.int64), np.asarray(dst_dense, np.int64),
        np.asarray(ranks, np.int64), dst_sort_key(dst_vids), P, vmax)

    props: Dict[str, np.ndarray] = {}
    ptypes: Dict[str, PropType] = {}
    valid = perm >= 0
    safe_perm = np.where(valid, perm, 0)
    for pd in prop_defs:
        dt = _col_dtype(pd.ptype)
        fill = np.nan if dt == np.float64 else INT_NULL
        # rows written before ALTER ... ADD lack the new key: encode the
        # latest schema's default (read-side fill_row parity — the host
        # serves the default, so the device column must too), coerced
        # like insert-time defaults (a geography default is WKT text)
        a = _encode_default(pd, pool)
        absent = fill if a is None else a
        if rows:
            coo = np.fromiter(
                (absent if (v := row.get(pd.name)) is None
                 else encode_prop(pd.ptype, v, pool) for row in rows),
                dtype=dt, count=len(rows))
            col = np.where(valid, coo[safe_perm], fill).astype(dt)
        else:
            col = np.full((P, emax), fill, dt)
        props[pd.name] = col
        ptypes[pd.name] = pd.ptype

    return CsrBlock(etype=etype, direction=direction,
                    indptr=indptr, nbr=nbr, rank=rank,
                    props=props, prop_types=ptypes)


def _build_tag_table(sd: SpaceData, tag: str, sv: SchemaVersion,
                     pool: StringPool, vmax: int,
                     want_props: Optional[List[str]]) -> TagTable:
    P = sd.num_parts
    prop_defs = [p for p in sv.props
                 if want_props is None or p.name in want_props]
    present = np.zeros((P, vmax), bool)
    props: Dict[str, np.ndarray] = {}
    ptypes: Dict[str, PropType] = {}
    absents: Dict[str, Any] = {}
    for pd in prop_defs:
        dt = _col_dtype(pd.ptype)
        fill = np.nan if dt == np.float64 else INT_NULL
        props[pd.name] = np.full((P, vmax), fill, dt)
        ptypes[pd.name] = pd.ptype
        # encoded default for pre-ALTER rows, hoisted out of the row
        # loop (identical for every row); None = leave the NULL fill
        absents[pd.name] = _encode_default(pd, pool)

    import time as _time

    from .store import ttl_expired
    now = _time.time()
    for p in range(P):
        part = sd.parts[p]
        for li in range(sd.part_counts[p]):
            vid = sd.dense_to_vid[li * P + p]
            tv = part.vertices.get(vid)
            if not tv or tag not in tv:
                continue
            if ttl_expired(sv, tv[tag][1], now):
                continue
            present[p, li] = True
            _, row = tv[tag]
            for pd in prop_defs:
                v = row.get(pd.name)
                if v is None:
                    a = absents[pd.name]   # pre-ALTER row: serve default
                    if a is not None:
                        props[pd.name][p, li] = a
                    continue
                props[pd.name][p, li] = encode_prop(pd.ptype, v, pool)

    return TagTable(tag=tag, present=present, props=props, prop_types=ptypes)


# --------------------------------------------------------------------------
# Host-side reference ops over a snapshot (oracles for the TPU kernels)
# --------------------------------------------------------------------------


def neighbors_of(snap: CsrSnapshot, block: CsrBlock, dense_src: int) -> np.ndarray:
    if snap.hub_dense is not None:
        hi_ = np.searchsorted(snap.hub_dense, dense_src)
        if hi_ < len(snap.hub_dense) and snap.hub_dense[hi_] == dense_src:
            # degree-split hub: its owner-local row is empty — the
            # adjacency lives as chunk rows vmax+hi_ across ALL parts
            row = snap.vmax + int(hi_)
            return np.concatenate(
                [block.nbr[p, int(block.indptr[p, row]):
                           int(block.indptr[p, row + 1])]
                 for p in range(snap.num_parts)])
    p = snap.owner(dense_src)
    li = snap.local(dense_src)
    lo, hi = int(block.indptr[p, li]), int(block.indptr[p, li + 1])
    return block.nbr[p, lo:hi]


def expand_frontier_host(snap: CsrSnapshot, block: CsrBlock,
                         frontier: np.ndarray) -> np.ndarray:
    """Reference one-hop expansion: all neighbors of `frontier` (dense ids),
    deduplicated + sorted. The oracle the TPU hop kernel is tested against."""
    outs = [neighbors_of(snap, block, int(d)) for d in frontier]
    if not outs:
        return np.zeros(0, np.int32)
    cat = np.concatenate(outs) if outs else np.zeros(0, np.int32)
    cat = cat[cat >= 0]
    return np.unique(cat).astype(np.int32)


def degree_split(snap: CsrSnapshot, threshold: int,
                 max_hubs: int = 1024) -> CsrSnapshot:
    """Split supernode adjacency across parts (SURVEY §7 hard-part #4's
    degree-split option).

    A vertex whose degree exceeds `threshold` in ANY block becomes a
    hub: each block's edge arrays are rebuilt so the hub's adjacency is
    divided into P contiguous chunks, chunk k living in part k as one
    of H extra "hub rows" appended after the vmax local rows (the hub's
    original local row becomes empty).  Every part then expands ~1/P of
    a hub's edges per hop instead of the owner expanding all of them —
    the per-part expansion ceiling (which sizes the padded edge budget
    EB) drops toward the mean, and supernode hops parallelize across
    the mesh instead of serializing on the owner chip.

    The transform is a pure layout change: same edges, same properties,
    host mirror identical to the device copy (eidx decode just works).
    Returns a NEW snapshot (hub_dense set); the input is not modified.
    Vertex ownership — frontier bitmap, marks, dist arrays — is
    untouched: only EXPANSION rows are added.
    """
    P, vmax = snap.num_parts, snap.vmax
    # deg[local*P + p] == deg.reshape(vmax, P)[local, p] — one
    # vectorized elementwise max per block, no scatter
    deg2d = np.zeros((vmax, P), np.int64)
    for b in snap.blocks.values():
        lens = b.indptr[:, 1:] - b.indptr[:, :-1]        # (P, vmax)
        np.maximum(deg2d, lens.T, out=deg2d)
    deg = deg2d.reshape(-1)
    hubs = np.nonzero(deg > threshold)[0]
    if hubs.size == 0:
        return snap
    if hubs.size > max_hubs:
        hubs = hubs[np.argsort(deg[hubs])[::-1][:max_hubs]]
    hubs = np.sort(hubs).astype(np.int64)
    H = int(hubs.size)
    ho, hl = (hubs % P).astype(np.int64), (hubs // P).astype(np.int64)

    def split_block(b: CsrBlock) -> CsrBlock:
        lens = b.indptr[:, 1:] - b.indptr[:, :-1]
        # per-hub chunk bounds into the OWNER part's edge range
        bounds = []
        for i in range(H):
            s = int(b.indptr[ho[i], hl[i]])
            e = int(b.indptr[ho[i], hl[i] + 1])
            bounds.append(s + (e - s) * np.arange(P + 1) // P)
        new_lens, new_cols = [], {"nbr": [], "rank": []}
        for n in b.props:
            new_cols[("prop", n)] = []
        for p in range(P):
            ep = int(b.indptr[p, -1])
            keep = np.ones(ep, bool)
            base = lens[p].astype(np.int64).copy()
            for i in range(H):
                if ho[i] == p:
                    keep[int(b.indptr[p, hl[i]]):
                         int(b.indptr[p, hl[i] + 1])] = False
                    base[hl[i]] = 0
            hub_lens = np.asarray(
                [bounds[i][p + 1] - bounds[i][p] for i in range(H)],
                np.int64)
            new_lens.append(np.concatenate([base, hub_lens]))

            def build(src_arr, out_key):
                parts = [src_arr[p, :ep][keep]]
                for i in range(H):
                    parts.append(src_arr[ho[i],
                                         bounds[i][p]:bounds[i][p + 1]])
                new_cols[out_key].append(np.concatenate(parts))
            build(b.nbr, "nbr")
            build(b.rank, "rank")
            for n in b.props:
                build(b.props[n], ("prop", n))
        emax = max(int(x.size) for x in new_cols["nbr"])

        def pad(rows, fill=0):
            out = np.full((P, emax), fill, rows[0].dtype)
            for p, r in enumerate(rows):
                out[p, :r.size] = r
            return out
        indptr = np.zeros((P, vmax + H + 1), b.indptr.dtype)
        for p in range(P):
            indptr[p, 1:] = np.cumsum(new_lens[p])
        return CsrBlock(etype=b.etype, direction=b.direction,
                        indptr=indptr, nbr=pad(new_cols["nbr"]),
                        rank=pad(new_cols["rank"]),
                        props={n: pad(new_cols[("prop", n)])
                               for n in b.props},
                        prop_types=dict(b.prop_types))

    out = CsrSnapshot(space=snap.space, epoch=snap.epoch, num_parts=P,
                      vmax=vmax, num_vertices=snap.num_vertices,
                      blocks={k: split_block(b)
                              for k, b in snap.blocks.items()},
                      tags=snap.tags, pool=snap.pool,
                      dense_to_vid=snap.dense_to_vid,
                      hub_dense=hubs)
    return out
