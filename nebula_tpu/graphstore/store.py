"""Partitioned host graph store — the storaged data plane, in-process form.

Redesign of the reference's storage stack (NebulaStore/RocksEngine +
query/mutate processors; reference: src/kvstore + src/storage [UNVERIFIED —
empty mount, SURVEY §0]) for the TPU-first architecture:

  * The graph is hash-partitioned by VID into ``partition_num`` parts
    (reference: part map in metad + NebulaKeyUtils key prefixes).
  * Each part keeps vertices and both edge directions in host dicts — the
    mutable, source-of-truth plane (the RocksDB analog; pluggable to a
    persistent KV in cluster mode).
  * Every vid gets a *dense id* encoding its partition: the i-th vid of
    part p gets ``dense = i * P + p`` so ``owner(dense) == dense % P`` is a
    single cheap op on device — this replaces the reference's
    hash-route-to-leader logic with arithmetic the TPU can do inline.
  * Mutations bump an epoch; device CSR snapshots are epoch-tagged derived
    data (see csr.py) — the serving copy the hot path reads.

Edge identity follows the reference: (src, edge_type, rank, dst); an edge is
written to the src part (out-direction) and dst part (in-direction), the
TOSS chain-write analog (single-process: both writes in one call).
"""
from __future__ import annotations

import hashlib
import itertools
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core.value import NULL, is_null
from .schema import (Catalog, EdgeSchema, PropDef, SchemaError, SpaceDesc,
                     TagSchema, apply_defaults, fill_row)


def ttl_expired(sv, row: Dict[str, Any], now: float) -> bool:
    """TTL check (the reference's compaction-filter + read-filter
    semantics): a row whose ttl_col value + ttl_duration is in the past
    is invisible; missing/null ttl values never expire."""
    if not sv.ttl_col or sv.ttl_duration <= 0:
        return False
    v = row.get(sv.ttl_col)
    if v is None or is_null(v) or not isinstance(v, (int, float)):
        return False
    return v + sv.ttl_duration < now


def stable_vid_hash(vid: Any) -> int:
    """Process-independent hash used for partitioning (NOT Python hash())."""
    if isinstance(vid, int):
        return vid & 0x7FFFFFFFFFFFFFFF
    if isinstance(vid, str):
        return int.from_bytes(hashlib.md5(vid.encode()).digest()[:8], "little") & 0x7FFFFFFFFFFFFFFF
    raise TypeError(f"unsupported vid type {type(vid).__name__}")


#: per-part exactly-once dedup window size (ISSUE 5): (writer, seq)
#: records evicted in insertion order — DETERMINISTIC, because eviction
#: happens inside raft apply, so every replica evicts identically
DEDUP_WINDOW = 1024


class Partition:
    """One shard: vertices + out/in adjacency, dict-backed."""

    __slots__ = ("part_id", "vertices", "out_edges", "in_edges",
                 "pending_chains", "applied_writes")

    def __init__(self, part_id: int):
        self.part_id = part_id
        # vid → {tag_name: (schema_version, {prop: value})}
        self.vertices: Dict[Any, Dict[str, Tuple[int, Dict[str, Any]]]] = {}
        # src_vid → {etype_name: {(rank, dst): {prop: value}}}
        self.out_edges: Dict[Any, Dict[str, Dict[Tuple[int, Any], Dict[str, Any]]]] = {}
        # dst_vid → {etype_name: {(rank, src): {prop: value}}}
        self.in_edges: Dict[Any, Dict[str, Dict[Tuple[int, Any], Dict[str, Any]]]] = {}
        # TOSS resume journal: chain_id → {"cmd": [in-half cmd], "ts": t}
        # (the out-half part remembers the in-half it owes the dst part
        # until the chain is confirmed — SURVEY §2 row 14)
        self.pending_chains: Dict[str, Dict[str, Any]] = {}
        # exactly-once dedup window (ISSUE 5): (writer_id, seq) →
        # {"n": cmd count, "err": first apply error or None}.  Written
        # ONLY inside raft apply (dbatch), so it is replicated state —
        # a re-proposed request is recognized on every replica and on
        # any post-failover leader.  Part of the part-state snapshot.
        self.applied_writes: "OrderedDict[Tuple[str, int], Dict[str, Any]]" \
            = OrderedDict()

    def edge_count(self) -> int:
        return sum(len(m) for per in self.out_edges.values() for m in per.values())


class SpaceData:
    """All partitions + vid dictionary of one space."""

    _uid_counter = itertools.count(1)

    def __init__(self, desc: SpaceDesc):
        self.desc = desc
        self.parts = [Partition(p) for p in range(desc.partition_num)]
        self.vid_to_dense: Dict[Any, int] = {}
        self.dense_to_vid: List[Any] = []
        self.part_counts = [0] * desc.partition_num
        self.epoch = 0
        # process-unique id: distinguishes same-named spaces of DIFFERENT
        # stores (or a dropped+recreated space) in the TpuRuntime's
        # per-space snapshot cache, where (name, epoch) alone can collide
        self.uid = next(SpaceData._uid_counter)
        from ..utils.racecheck import make_lock
        self.lock = make_lock("space_data")
        self.index_data: Dict[str, Any] = {}   # index name → IndexData
        self.ft_data: Dict[str, Any] = {}      # name → FulltextIndexData

    @property
    def num_parts(self) -> int:
        return self.desc.partition_num

    def part_of(self, vid: Any) -> int:
        return stable_vid_hash(vid) % self.num_parts

    def part_for(self, vid: Any) -> "Partition":
        """Coherent lock-free part lookup: ONE read of the parts list,
        modulus from that snapshot's own length — a racing REPARTITION
        swap yields a stale-but-coherent partition (transient miss),
        never an IndexError.  Write paths under sd.lock (which the swap
        also holds) keep using part_of()."""
        parts = self.parts
        return parts[stable_vid_hash(vid) % len(parts)]

    def dense_id(self, vid: Any, create: bool = False) -> int:
        d = self.vid_to_dense.get(vid)
        if d is not None:
            return d
        if not create:
            return -1
        p = self.part_of(vid)
        d = self.part_counts[p] * self.num_parts + p
        self.part_counts[p] += 1
        self.vid_to_dense[vid] = d
        # dense ids are not contiguous globally; keep a map-backed list
        need = d + 1 - len(self.dense_to_vid)
        if need > 0:
            self.dense_to_vid.extend([None] * need)
        self.dense_to_vid[d] = vid
        return d

    def vid_of_dense(self, dense: int) -> Any:
        if 0 <= dense < len(self.dense_to_vid):
            return self.dense_to_vid[dense]
        return None

    def install_dense(self, mapping: Dict[Any, int]):
        """Merge a part's dense-id slice (part-state install / CSR
        export assembly — one merge loop for every consumer)."""
        for v, d in mapping.items():
            self.vid_to_dense[v] = d
            need = d + 1 - len(self.dense_to_vid)
            if need > 0:
                self.dense_to_vid.extend([None] * need)
            self.dense_to_vid[d] = v


def _dnote(sd: "SpaceData", key: tuple) -> None:
    """Record a dirty key on the space's device delta log, when one is
    watching (ISSUE 19).  Keys carry identity only — the apply step
    re-reads authoritative rows — so every write path's hook is one
    line beside its epoch bump, under the same sd.lock."""
    log = getattr(sd, "delta_log", None)
    if log is not None:
        log.note(key)


def _dbreak(sd: "SpaceData") -> None:
    """Mark the delta log broken: dense-id layout changed (REPARTITION,
    part install/clear) — the next device pin must full-rebuild."""
    log = getattr(sd, "delta_log", None)
    if log is not None:
        log.note_break()


class StoreError(Exception):
    pass


class GraphStore:
    """The single-process storage service: catalog + all spaces' data.

    Mirrors the operation set of storage.thrift (getNeighbors, getProps,
    scanVertex/scanEdge, addVertices/addEdges, delete/update) — SURVEY §2
    row 12/13 — as Python methods; the cluster storaged wraps this per-host.
    """

    def __init__(self, catalog: Optional[Catalog] = None,
                 data_dir: Optional[str] = None):
        self.catalog = catalog or Catalog()
        self.data: Dict[int, SpaceData] = {}
        self._engine = None
        self._ft_listener = None     # started on first fulltext index
        self._ft_reg_lock = threading.Lock()
        # (space_id, schema, is_edge) → (catalog_version, descs)
        self._ft_memo: Dict[Tuple[int, str, bool], Tuple[int, list]] = {}
        if data_dir is not None:
            # durable standalone engine (SURVEY §2 row 10): recover from
            # checkpoint + journal, then resume journaling every mutation
            from .engine import DurableEngine, JournalingCatalog
            eng = DurableEngine(data_dir)
            eng.recover_into(self)
            self._engine = eng
            self.catalog = JournalingCatalog(self.catalog, eng)

    def _log(self, *cmd):
        if self._engine is not None:
            self._engine.log(cmd)

    def compact_journal(self) -> int:
        """Checkpoint + journal truncation (SUBMIT JOB COMPACT's
        durability leg); no-op without an engine."""
        if self._engine is None:
            return 0
        # checkpoint() reads through the JournalingCatalog proxy — hand
        # it the raw catalog object for serialization
        return self._engine.compact(self)

    def close(self):
        if self._engine is not None:
            self._engine.close()
        if self._ft_listener is not None:
            self._ft_listener.stop()
            self._ft_listener = None

    @property
    def ft_listener(self):
        """The full-text replication sink (SURVEY §2 row 10 Listener),
        started lazily — stores with no fulltext index never pay for the
        thread."""
        if self._ft_listener is None:
            from .fulltext import FulltextListener
            self._ft_listener = FulltextListener()
        return self._ft_listener

    # ---- space lifecycle ----
    def create_space(self, name: str, **kw) -> SpaceDesc:
        sp = self.catalog.create_space(name, **kw)
        if sp.space_id not in self.data:
            self.data[sp.space_id] = SpaceData(sp)
        self._log("create_space", name, kw)
        return sp

    def drop_space(self, name: str, if_exists=False):
        sp = self.catalog.drop_space(name, if_exists=if_exists)
        if sp is not None:
            self.data.pop(sp.space_id, None)
        self._log("drop_space", name)

    def repartition(self, name: str, new_parts: int, cancel=None) -> int:
        """SUBMIT JOB REPARTITION <n>: rebuild the space's hash
        partitioning in place — the part split/merge analog for a
        hash-partitioned store (SURVEY §2 row 16: the reference's
        AdminTaskManager task family).  Every vertex row (raw
        version+row, so read-side schema upgrade semantics survive) and
        both edge planes re-home to vid_hash % new_parts; dense ids,
        secondary indexes and fulltext indexes are rebuilt; the epoch
        bump re-pins any device snapshot.

        Stop-the-world under the space lock (an admin job, like the
        reference's blocking leader tasks); `cancel` (threading.Event)
        is checked between source partitions and aborts BEFORE the
        swap — a cancelled repartition leaves the space untouched.
        Returns the number of vertices moved."""
        sd = self.space(name)
        with sd.lock:
            desc = sd.desc
            if new_parts == desc.partition_num:
                return 0
            if new_parts < 1:
                raise StoreError(f"bad partition count {new_parts}")
            if any(p.pending_chains for p in sd.parts):
                raise StoreError(
                    "repartition with pending TOSS chains; retry after "
                    "chain resume settles")
            old_parts = sd.parts
            # phase 1: build the new layout fully off to the side
            P2 = new_parts
            parts2 = [Partition(p) for p in range(P2)]
            counts2 = [0] * P2
            v2d: Dict[Any, int] = {}
            d2v: List[Any] = []

            def dense2(vid):
                d = v2d.get(vid)
                if d is None:
                    p = stable_vid_hash(vid) % P2
                    d = counts2[p] * P2 + p
                    counts2[p] += 1
                    v2d[vid] = d
                    need = d + 1 - len(d2v)
                    if need > 0:
                        d2v.extend([None] * need)
                    d2v[d] = vid
                return d

            moved = 0
            for p in old_parts:
                if cancel is not None and cancel.is_set():
                    return -1            # aborted; nothing swapped
                for vid, tv in p.vertices.items():
                    dense2(vid)
                    parts2[stable_vid_hash(vid) % P2].vertices[vid] = \
                        {t: (ver, dict(row)) for t, (ver, row) in tv.items()}
                    moved += 1
                for src, per in p.out_edges.items():
                    dense2(src)
                    tgt = parts2[stable_vid_hash(src) % P2].out_edges
                    tgt[src] = {et: dict(em) for et, em in per.items()}
                for dst, per in p.in_edges.items():
                    dense2(dst)
                    tgt = parts2[stable_vid_hash(dst) % P2].in_edges
                    tgt[dst] = {et: dict(em) for et, em in per.items()}
            # phase 2: the swap.  Writers are excluded by sd.lock, but
            # READ paths are lock-free — order the assignments so a
            # racing reader can transiently MISS but never index past a
            # list's end: growing, install the bigger parts list before
            # the partition count that routes into its tail; shrinking,
            # shrink the count first.
            if P2 >= desc.partition_num:
                sd.parts = parts2
                sd.part_counts = counts2
                sd.vid_to_dense = v2d
                sd.dense_to_vid = d2v
                desc.partition_num = P2
            else:
                desc.partition_num = P2
                sd.parts = parts2
                sd.part_counts = counts2
                sd.vid_to_dense = v2d
                sd.dense_to_vid = d2v
            sd.index_data = {}
            sd.ft_data = {}
            sd.epoch += 1
            _dbreak(sd)
        # derived state: rebuild every index against the new layout
        for d in self.catalog.indexes(name):
            self.rebuild_index(name, d.name)
        for d in self.catalog.fulltext_indexes(name):
            self.rebuild_fulltext_index(name, d.name)
        self._log("repartition", name, new_parts)
        return moved

    def clear_space(self, name: str, if_exists=False):
        """CLEAR SPACE: wipe every partition's data (vertices, edges,
        derived indexes, TOSS chains, the dense-id dictionary) while
        keeping the schema catalog — the reference's admin statement for
        re-ingesting a space without re-issuing DDL."""
        from .schema import SchemaError
        try:
            self.catalog.get_space(name)
        except SchemaError:
            if if_exists:
                return
            raise
        sd = self.space(name)
        for pid in range(sd.num_parts):
            self.clear_part(name, pid)
        self._log("clear_space", name)

    def space(self, name: str) -> SpaceData:
        sp = self.catalog.get_space(name)
        sd = self.data.get(sp.space_id)
        if sd is None:
            sd = self.data[sp.space_id] = SpaceData(sp)
        return sd

    # ---- device delta feed (ISSUE 19) ----
    # The TpuRuntime attaches a dirty-key log BEFORE exporting a
    # snapshot; every write path notes its key under sd.lock, so a key
    # recorded after the watch but before the export is merely re-read
    # at apply time (idempotent) — no lost-write window.

    def delta_watch(self, space: str, cap: int = 65536) -> int:
        from .delta import DeltaLog
        sd = self.space(space)
        with sd.lock:
            log = getattr(sd, "delta_log", None)
            if log is None or log.broken:
                # an unbroken log keeps watching across re-watches: a
                # compaction build must not reset the floor (or drop
                # keys) out from under the still-serving snapshot —
                # stale keys are harmless, apply re-reads per key
                sd.delta_log = DeltaLog(floor_epoch=sd.epoch, cap=cap)
            return sd.epoch

    def delta_records(self, space: str):
        """-> (dirty keys, target epoch, log floor epoch), or None when
        no log is watching / the log broke (caller full-rebuilds)."""
        sd = self.space(space)
        with sd.lock:
            log = getattr(sd, "delta_log", None)
            if log is None or log.broken:
                return None
            return list(log.keys), sd.epoch, log.floor_epoch

    def delta_trim(self, space: str, keys) -> None:
        sd = self.space(space)
        with sd.lock:
            log = getattr(sd, "delta_log", None)
            if log is not None:
                log.trim(keys)

    def delta_reader(self, space: str):
        from .delta import LocalStoreReader
        return LocalStoreReader(self, space)

    # ---- secondary index maintenance (SURVEY §2 row 15) ----
    # Hooks called from every write path (rich and raw-apply) so cluster
    # replicas maintain identical index state; CREATE INDEX starts empty
    # (reference semantics) — rebuild_index() backfills.

    def _make_index_data(self, space: str, d, num_parts: int):
        """IndexData for a descriptor; a single-column index over a
        GEOGRAPHY prop is automatically cell-token-keyed (GeoIndexData) —
        the reference keys geo index records by S2 cell with no separate
        DDL spelling (SURVEY §2 row 15)."""
        from .index import GeoIndexData, IndexData
        from .schema import PropType
        cls = IndexData
        if len(d.fields) == 1:
            try:
                sv = (self.catalog.get_edge(space, d.schema_name).latest
                      if d.is_edge else
                      self.catalog.get_tag(space, d.schema_name).latest)
                p = sv.prop(d.fields[0])
                if p is not None and p.ptype == PropType.GEOGRAPHY:
                    cls = GeoIndexData
            except SchemaError:
                pass
        return cls(d.name, d.fields, d.is_edge, num_parts, d.index_id,
                   field_lens=getattr(d, "field_lens", None))

    def _index_list(self, sd: SpaceData, space: str, schema: str,
                    is_edge: bool):
        descs = self.catalog.indexes_for(space, schema, is_edge)
        out = []
        for d in descs:
            idx = sd.index_data.get(d.name)
            if idx is None or idx.fields != d.fields or \
                    idx.index_id != d.index_id:
                # new creation (possibly after a DROP of a same-named
                # index) — starts empty, never resurrects old entries
                idx = sd.index_data[d.name] = self._make_index_data(
                    space, d, sd.num_parts)
            out.append(idx)
        return out

    def _index_vertex(self, sd, space, vid, tag, old_row, new_row):
        part = sd.part_of(vid)
        idxs = self._index_list(sd, space, tag, False)
        if idxs:
            # index keys must match what READS serve: rows stored before
            # an ALTER ... ADD are keyed with the filled default, same
            # as fill_row'd scans/rebuilds (else remove() misses)
            sv = self.catalog.get_tag(space, tag).latest
            old_f = fill_row(sv, old_row) if old_row is not None else None
            new_f = fill_row(sv, new_row) if new_row is not None else None
            for idx in idxs:
                if old_f is not None:
                    idx.remove(part, old_f, vid)
                if new_f is not None:
                    idx.add(part, new_f, vid)
        self._ft_enqueue(sd, space, tag, False, part, vid, old_row,
                         new_row)

    def _index_edge(self, sd, space, src, etype, dst, rank, old_row,
                    new_row):
        part = sd.part_of(src)
        ent = (src, rank, dst)
        idxs = self._index_list(sd, space, etype, True)
        if idxs:
            sv = self.catalog.get_edge(space, etype).latest
            old_f = fill_row(sv, old_row) if old_row is not None else None
            new_f = fill_row(sv, new_row) if new_row is not None else None
            for idx in idxs:
                if old_f is not None:
                    idx.remove(part, old_f, ent)
                if new_f is not None:
                    idx.add(part, new_f, ent)
        self._ft_enqueue(sd, space, etype, True, part, ent, old_row,
                         new_row)

    # ---- full-text plane (SURVEY §2 row 10 Listener) ----

    def _ft_list(self, sd: SpaceData, space: str, schema: str,
                 is_edge: bool):
        from .fulltext import FulltextIndexData
        # per-write fast path: catalog lookups + drop-GC run only when
        # the catalog version moved, not on every mutation
        ver = self.catalog.version
        mkey = (sd.desc.space_id, schema, is_edge)
        memo = self._ft_memo.get(mkey)
        if memo is None or memo[0] != ver:
            with self._ft_reg_lock:
                if sd.ft_data:
                    # GC incarnations the catalog no longer lists (DROP
                    # FULLTEXT INDEX must release the corpus, not strand
                    # it until a same-name re-CREATE)
                    live = {d.name: d.index_id
                            for d in self.catalog.fulltext_indexes(space)}
                    for name in list(sd.ft_data):
                        if live.get(name) != sd.ft_data[name].index_id:
                            del sd.ft_data[name]
                            if self._ft_listener is not None:
                                self._ft_listener.unregister(space, name)
                descs = self.catalog.fulltext_indexes_for(space, schema,
                                                          is_edge)
            self._ft_memo[mkey] = memo = (ver, descs)
        descs = memo[1]
        if not descs:
            return ()
        out = []
        # registry mutation is serialized: a concurrent first touch from
        # a search thread and a write thread must agree on ONE
        # FulltextIndexData (a split brain here would send all listener
        # applies to an object searches never read)
        with self._ft_reg_lock:
            for d in descs:
                ft = sd.ft_data.get(d.name)
                if ft is None or ft.index_id != d.index_id:
                    ft = sd.ft_data[d.name] = FulltextIndexData(
                        d.name, d.schema_name, d.fields[0], d.is_edge,
                        sd.num_parts, d.index_id)
                    self.ft_listener.register(space, ft)
                out.append(ft)
        return out

    def _ft_enqueue(self, sd, space, schema, is_edge, part, entity,
                    old_row, new_row):
        """Replicate one committed mutation to the text sink — enqueue
        only; the listener thread applies (base writes never block on
        the text index, matching the reference's one-way Listener)."""
        for ft in self._ft_list(sd, space, schema, is_edge):
            lsn = self.ft_listener
            if old_row is not None:
                lsn.enqueue("remove", space, ft.name, part, entity=entity,
                            gen=ft.index_id)
            if new_row is not None:
                v = new_row.get(ft.field)
                if isinstance(v, str):
                    lsn.enqueue("add", space, ft.name, part, v, entity,
                                gen=ft.index_id)

    def rebuild_fulltext_index(self, space: str, index_name: str,
                               parts: Optional[List[int]] = None) -> int:
        """Clear + re-replicate one text index from base data."""
        sd = self.space(space)
        d = next((x for x in self.catalog.fulltext_indexes(space)
                  if x.name == index_name), None)
        if d is None:
            raise StoreError(f"fulltext index `{index_name}' not found")
        fts = self._ft_list(sd, space, d.schema_name, d.is_edge)
        ft = next(x for x in fts if x.name == index_name)
        lsn = self.ft_listener
        if parts is not None:
            lsn.drain()     # settle before reading values[] below
        with sd.lock:
            part_ids = list(parts) if parts is not None \
                else list(range(sd.num_parts))
            if parts is None:
                lsn.enqueue("clear", space, index_name, gen=ft.index_id)
            for pid in part_ids:
                if parts is not None:
                    with ft.lock:
                        ents = list(ft.values[pid])
                    for ent in ents:
                        lsn.enqueue("remove", space, index_name, pid,
                                    entity=ent, gen=ft.index_id)
                p = sd.parts[pid]
                if d.is_edge:
                    for src, per in p.out_edges.items():
                        em = per.get(d.schema_name)
                        if em:
                            for (rank, dst), row in em.items():
                                v = row.get(d.fields[0])
                                if isinstance(v, str):
                                    lsn.enqueue("add", space, index_name,
                                                pid, v, (src, rank, dst),
                                                gen=ft.index_id)
                else:
                    for vid, tv in p.vertices.items():
                        if d.schema_name in tv:
                            v = tv[d.schema_name][1].get(d.fields[0])
                            if isinstance(v, str):
                                lsn.enqueue("add", space, index_name,
                                            pid, v, vid,
                                            gen=ft.index_id)
        lsn.drain()
        return sum(len(ft.values[pid]) for pid in part_ids)

    def fulltext_search(self, space: str, index_name: str, op: str,
                        pattern: str,
                        parts: Optional[List[int]] = None) -> List[Any]:
        """Serve a LOOKUP text predicate.  Drains the listener first —
        read-your-writes instead of the reference's ES eventual
        consistency (documented deviation, keeps results deterministic)."""
        sd = self.space(space)
        d = next((x for x in self.catalog.fulltext_indexes(space)
                  if x.name == index_name), None)
        if d is None:
            raise StoreError(f"fulltext index `{index_name}' not found")
        fts = self._ft_list(sd, space, d.schema_name, d.is_edge)
        ft = next(x for x in fts if x.name == index_name)
        self.ft_listener.drain()
        return ft.search(op, pattern, parts)

    def rebuild_index(self, space: str, index_name: str,
                      parts: Optional[List[int]] = None) -> int:
        """Clear + backfill one index from the base data. Returns entry
        count (this process's parts)."""
        sd = self.space(space)
        descs = {d.name: d for d in self.catalog.indexes(space)}
        d = descs.get(index_name)
        if d is None:
            raise StoreError(f"index `{index_name}' not found")
        if parts is None:
            self._log("rebuild_index", space, index_name)
        idx = sd.index_data.get(index_name)
        if idx is None or idx.fields != d.fields or \
                idx.index_id != d.index_id:
            idx = sd.index_data[index_name] = self._make_index_data(
                space, d, sd.num_parts)
        sv = (self.catalog.get_edge(space, d.schema_name).latest
              if d.is_edge else
              self.catalog.get_tag(space, d.schema_name).latest)
        with sd.lock:
            part_ids = list(parts) if parts is not None \
                else list(range(sd.num_parts))
            for pid in part_ids:
                idx.parts[pid].clear()
                p = sd.parts[pid]
                if d.is_edge:
                    for src, per in p.out_edges.items():
                        em = per.get(d.schema_name)
                        if em:
                            for (rank, dst), row in em.items():
                                idx.add(pid, fill_row(sv, row),
                                        (src, rank, dst))
                else:
                    for vid, tv in p.vertices.items():
                        if d.schema_name in tv:
                            idx.add(pid,
                                    fill_row(sv, tv[d.schema_name][1]),
                                    vid)
            return sum(len(idx.parts[pid]) for pid in part_ids)

    def index_scan(self, space: str, index_name: str, eq_prefix: List[Any],
                   range_hint=None,
                   parts: Optional[List[int]] = None) -> List[Any]:
        """Entities (vids or (src, rank, dst)) matching the hints, in
        index order per part."""
        sd = self.space(space)
        idx = sd.index_data.get(index_name)
        d = next((x for x in self.catalog.indexes(space)
                  if x.name == index_name), None)
        if idx is None or d is None or idx.fields != d.fields or \
                idx.index_id != d.index_id:
            return []               # dropped/recreated → stale data is dead
        part_ids = list(parts) if parts is not None \
            else list(range(sd.num_parts))
        out: List[Any] = []
        for pid in part_ids:
            out.extend(idx.scan(pid, eq_prefix, range_hint))
        return out

    def index_scan_geo(self, space: str, index_name: str,
                       ranges: List[tuple],
                       parts: Optional[List[int]] = None) -> List[Any]:
        """Entities whose geography cell token falls in any of the
        inclusive (lo, hi) token ranges (covering_ranges output); the
        caller re-checks the exact ST_ predicate as a residual filter."""
        from .index import GeoIndexData
        sd = self.space(space)
        idx = sd.index_data.get(index_name)
        d = next((x for x in self.catalog.indexes(space)
                  if x.name == index_name), None)
        if idx is None or d is None or idx.fields != d.fields or \
                idx.index_id != d.index_id or \
                not isinstance(idx, GeoIndexData):
            return []               # dropped/recreated → stale data is dead
        part_ids = list(parts) if parts is not None \
            else list(range(sd.num_parts))
        out: List[Any] = []
        for pid in part_ids:
            out.extend(idx.scan_geo(pid, ranges))
        return out

    # ---- mutate ----
    def insert_vertex(self, space: str, vid: Any, tag: str,
                      props: Dict[str, Any], insert_names: Optional[List[str]] = None):
        sd = self.space(space)
        sd.desc.check_vid(vid)
        ts = self.catalog.get_tag(space, tag)
        sv = ts.latest
        row = apply_defaults(sv, props, insert_names)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            sd.dense_id(vid, create=True)
            old = p.vertices.get(vid, {}).get(tag)
            p.vertices.setdefault(vid, {})[tag] = (sv.version, row)
            self._index_vertex(sd, space, vid, tag,
                               old[1] if old else None, row)
            sd.epoch += 1
            _dnote(sd, ("v", vid))
            self._log("vertex", space, vid, tag, sv.version, row)

    def insert_edge(self, space: str, src: Any, etype: str, dst: Any,
                    rank: int, props: Dict[str, Any],
                    insert_names: Optional[List[str]] = None):
        sd = self.space(space)
        sd.desc.check_vid(src)
        sd.desc.check_vid(dst)
        es = self.catalog.get_edge(space, etype)
        sv = es.latest
        row = apply_defaults(sv, props, insert_names)
        with sd.lock:
            sd.dense_id(src, create=True)
            sd.dense_id(dst, create=True)
            # out-edge on src part, in-edge on dst part (TOSS chain analog)
            po = sd.parts[sd.part_of(src)]
            old = po.out_edges.get(src, {}).get(etype, {}).get((rank, dst))
            po.out_edges.setdefault(src, {}).setdefault(etype, {})[(rank, dst)] = row
            pi = sd.parts[sd.part_of(dst)]
            pi.in_edges.setdefault(dst, {}).setdefault(etype, {})[(rank, src)] = row
            self._index_edge(sd, space, src, etype, dst, rank, old, row)
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))
            self._log("edge_pair", space, src, etype, dst, rank, row)

    def delete_vertex(self, space: str, vid: Any, with_edges: bool = True):
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            tv = p.vertices.pop(vid, None)
            if tv:
                for t, (_, row) in tv.items():
                    self._index_vertex(sd, space, vid, t, row, None)
            if with_edges:
                out = p.out_edges.pop(vid, {})
                for etype, em in out.items():
                    for (rank, dst), row in list(em.items()):
                        pd = sd.parts[sd.part_of(dst)]
                        pd.in_edges.get(dst, {}).get(etype, {}).pop((rank, vid), None)
                        self._index_edge(sd, space, vid, etype, dst, rank,
                                         row, None)
                        _dnote(sd, ("e", etype, vid, dst, rank))
                inn = p.in_edges.pop(vid, {})
                for etype, em in inn.items():
                    for (rank, src) in list(em):
                        ps = sd.parts[sd.part_of(src)]
                        row = ps.out_edges.get(src, {}).get(etype, {}) \
                            .pop((rank, vid), None)
                        if row is not None:
                            self._index_edge(sd, space, src, etype, vid,
                                             rank, row, None)
                        _dnote(sd, ("e", etype, src, vid, rank))
            sd.epoch += 1
            _dnote(sd, ("v", vid))
            self._log("del_vertex_rich", space, vid, with_edges)

    def delete_tag(self, space: str, vid: Any, tags: List[str]):
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            tv = p.vertices.get(vid)
            if tv:
                for t in tags:
                    old = tv.pop(t, None)
                    if old is not None:
                        self._index_vertex(sd, space, vid, t, old[1], None)
                if not tv:
                    p.vertices.pop(vid, None)
            sd.epoch += 1
            _dnote(sd, ("v", vid))
            self._log("del_tag", space, vid, tags)

    def delete_edge(self, space: str, src: Any, etype: str, dst: Any, rank: int):
        sd = self.space(space)
        with sd.lock:
            ps = sd.parts[sd.part_of(src)]
            old = ps.out_edges.get(src, {}).get(etype, {}).pop((rank, dst), None)
            pd = sd.parts[sd.part_of(dst)]
            pd.in_edges.get(dst, {}).get(etype, {}).pop((rank, src), None)
            if old is not None:
                self._index_edge(sd, space, src, etype, dst, rank, old, None)
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))
            self._log("del_edge", space, src, etype, dst, rank)

    def update_vertex(self, space: str, vid: Any, tag: str,
                      updates: Dict[str, Any]) -> bool:
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            tv = p.vertices.get(vid, {}).get(tag)
            if tv is None:
                return False
            ver, row = tv
            sv = self.catalog.get_tag(space, tag).latest
            for k in updates:       # validate BEFORE mutating anything
                if sv.prop(k) is None:
                    raise SchemaError(f"unknown prop `{k}'")
            old = dict(row)
            row.update(updates)
            self._index_vertex(sd, space, vid, tag, old, row)
            sd.epoch += 1
            _dnote(sd, ("v", vid))
            self._log("upd_vertex", space, vid, tag, updates)
            return True

    def update_edge(self, space: str, src: Any, etype: str, dst: Any,
                    rank: int, updates: Dict[str, Any]) -> bool:
        sd = self.space(space)
        with sd.lock:
            ps = sd.parts[sd.part_of(src)]
            row = ps.out_edges.get(src, {}).get(etype, {}).get((rank, dst))
            if row is None:
                return False
            sv = self.catalog.get_edge(space, etype).latest
            for k in updates:       # validate BEFORE mutating anything
                if sv.prop(k) is None:
                    raise SchemaError(f"unknown prop `{k}'")
            old = dict(row)
            row.update(updates)
            self._index_edge(sd, space, src, etype, dst, rank, old, row)
            pd = sd.parts[sd.part_of(dst)]
            irow = pd.in_edges.get(dst, {}).get(etype, {}).get((rank, src))
            if irow is not None:
                irow.update({k: row[k] for k in updates})
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))
            self._log("upd_edge_pair", space, src, etype, dst, rank,
                      updates)
            return True

    # ---- raw part-local apply (cluster write path) ----
    # Schema defaults are resolved by the caller (graphd) before the op is
    # proposed to the part's raft group, so replica replay is
    # deterministic; each op touches exactly ONE part (edge writes are
    # split into out/in halves — the TOSS chain, SURVEY §2 row 14).

    def apply_vertex(self, space: str, vid: Any, tag: str, version: int,
                     row: Dict[str, Any]):
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            sd.dense_id(vid, create=True)
            old = p.vertices.get(vid, {}).get(tag)
            p.vertices.setdefault(vid, {})[tag] = (version, dict(row))
            self._index_vertex(sd, space, vid, tag,
                               old[1] if old else None, row)
            sd.epoch += 1
            _dnote(sd, ("v", vid))

    def apply_edge_half(self, space: str, src: Any, etype: str, dst: Any,
                        rank: int, row: Dict[str, Any], which: str):
        sd = self.space(space)
        with sd.lock:
            if which == "out":
                sd.dense_id(src, create=True)
                p = sd.parts[sd.part_of(src)]
                old = p.out_edges.get(src, {}).get(etype, {}).get((rank, dst))
                p.out_edges.setdefault(src, {}).setdefault(etype, {})[
                    (rank, dst)] = dict(row)
                self._index_edge(sd, space, src, etype, dst, rank, old, row)
            else:
                sd.dense_id(dst, create=True)
                p = sd.parts[sd.part_of(dst)]
                p.in_edges.setdefault(dst, {}).setdefault(etype, {})[
                    (rank, src)] = dict(row)
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))

    def apply_delete_vertex(self, space: str, vid: Any):
        """Remove the vertex row + its own adjacency planes (the caller
        deletes the mirror halves on other parts)."""
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[sd.part_of(vid)]
            tv = p.vertices.pop(vid, None)
            if tv:
                for t, (_, row) in tv.items():
                    self._index_vertex(sd, space, vid, t, row, None)
            out = p.out_edges.pop(vid, None)
            if out:
                for etype, em in out.items():
                    for (rank, dst), row in em.items():
                        self._index_edge(sd, space, vid, etype, dst, rank,
                                         row, None)
                        _dnote(sd, ("e", etype, vid, dst, rank))
            inn = p.in_edges.pop(vid, None)
            if inn:
                for etype, em in inn.items():
                    for (rank, src) in em:
                        _dnote(sd, ("e", etype, src, vid, rank))
            sd.epoch += 1
            _dnote(sd, ("v", vid))

    def apply_delete_edge_half(self, space: str, src: Any, etype: str,
                               dst: Any, rank: int, which: str):
        sd = self.space(space)
        with sd.lock:
            if which == "out":
                p = sd.parts[sd.part_of(src)]
                old = p.out_edges.get(src, {}).get(etype, {}) \
                    .pop((rank, dst), None)
                if old is not None:
                    self._index_edge(sd, space, src, etype, dst, rank,
                                     old, None)
            else:
                p = sd.parts[sd.part_of(dst)]
                p.in_edges.get(dst, {}).get(etype, {}).pop((rank, src), None)
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))

    def apply_update_vertex(self, space: str, vid: Any, tag: str,
                            updates: Dict[str, Any]) -> bool:
        sd = self.space(space)
        with sd.lock:
            tv = sd.parts[sd.part_of(vid)].vertices.get(vid, {}).get(tag)
            if tv is None:
                return False
            old = dict(tv[1])
            tv[1].update(updates)
            self._index_vertex(sd, space, vid, tag, old, tv[1])
            sd.epoch += 1
            _dnote(sd, ("v", vid))
            return True

    def apply_update_edge_half(self, space: str, src: Any, etype: str,
                               dst: Any, rank: int,
                               updates: Dict[str, Any], which: str) -> bool:
        sd = self.space(space)
        with sd.lock:
            if which == "out":
                row = sd.parts[sd.part_of(src)].out_edges.get(src, {}) \
                    .get(etype, {}).get((rank, dst))
            else:
                row = sd.parts[sd.part_of(dst)].in_edges.get(dst, {}) \
                    .get(etype, {}).get((rank, src))
            if row is None:
                return False
            old = dict(row)
            row.update(updates)
            if which == "out":
                self._index_edge(sd, space, src, etype, dst, rank, old, row)
            sd.epoch += 1
            _dnote(sd, ("e", etype, src, dst, rank))
            return True

    def apply_chain_mark(self, space: str, pid: int, chain_id: str,
                         entry: Dict[str, Any]):
        """Record the in-half a TOSS chain still owes (replicated with
        the out-half's part so a graphd crash between the two halves is
        recoverable by the part leader's resume loop).  entry:
        {"part": dst_pid, "cmd": [in-half cmd], "ts": float}."""
        sd = self.space(space)
        with sd.lock:
            sd.parts[pid].pending_chains[chain_id] = dict(entry)

    def apply_chain_done(self, space: str, pid: int, chain_id: str):
        sd = self.space(space)
        with sd.lock:
            sd.parts[pid].pending_chains.pop(chain_id, None)

    def pending_chains(self, space: str, pid: int) -> Dict[str, Dict[str, Any]]:
        sd = self.space(space)
        with sd.lock:
            return dict(sd.parts[pid].pending_chains)

    # ---- exactly-once write dedup (ISSUE 5) ----

    def dedup_seen(self, space: str, pid: int, writer: str,
                   seq: int) -> Optional[Dict[str, Any]]:
        """The recorded outcome of an already-applied (writer, seq)
        write request, or None.  Checked by the leader's rpc_write
        fast path AND by dbatch apply (the replicated, race-free
        gate)."""
        sd = self.space(space)
        with sd.lock:
            return sd.parts[pid].applied_writes.get((writer, int(seq)))

    def dedup_record(self, space: str, pid: int, writer: str, seq: int,
                     outcome: Dict[str, Any]):
        """Record a write request's outcome in the part's dedup window.
        Called ONLY from dbatch apply — replicas call it in identical
        commit order, so window contents and eviction are identical
        everywhere."""
        sd = self.space(space)
        with sd.lock:
            aw = sd.parts[pid].applied_writes
            aw[(writer, int(seq))] = outcome
            while len(aw) > DEDUP_WINDOW:
                aw.popitem(last=False)

    # ---- part state snapshot (raft snapshot + checkpoint payload) ----

    def part_state_payload(self, space: str, pid: int) -> Dict[str, Any]:
        """One partition's full state as a plain dict — THE part-state
        vocabulary, shared by the raft snapshot/checkpoint encoder
        (export_part_state) and the device-plane bulk CSR export RPC
        (storage_service.rpc_export_part): a field added here reaches
        both, so the formats cannot drift."""
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[pid]
            return {
                "vertices": p.vertices,
                "out_edges": p.out_edges,
                "in_edges": p.in_edges,
                "part_count": sd.part_counts[pid],
                "dense": {v: d for v, d in sd.vid_to_dense.items()
                          if d % sd.num_parts == pid},
                "chains": p.pending_chains,
                # ordered list form: JSON keys must be strings, and the
                # WINDOW ORDER (eviction order) is itself state
                "writes": [[w, s, rec]
                           for (w, s), rec in p.applied_writes.items()],
            }

    def export_part_state(self, space: str, pid: int) -> bytes:
        """Serialize one partition's full state (raft snapshot_cb /
        checkpoint file payload).  Includes the part's slice of the
        dense-id dictionary so replay-free restore keeps device ids
        stable.  Wire-JSON encoded: the payload crosses RPC as a raft
        snapshot, so it must never be pickle."""
        from ..core import wire
        return wire.dumps(self.part_state_payload(space, pid))

    def install_part_state(self, space: str, pid: int, data: bytes):
        from ..core import wire
        st = wire.loads(data)
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[pid]
            p.vertices = st["vertices"]
            p.out_edges = st["out_edges"]
            p.in_edges = st["in_edges"]
            p.pending_chains = st.get("chains", {})
            p.applied_writes = OrderedDict(
                ((w, int(s)), rec) for w, s, rec in st.get("writes", []))
            sd.part_counts[pid] = st["part_count"]
            sd.install_dense(st["dense"])
            sd.epoch += 1
            _dbreak(sd)
        # indexes are derived state: rebuild this part's slices
        for d in self.catalog.indexes(space):
            self.rebuild_index(space, d.name, parts=[pid])
        for d in self.catalog.fulltext_indexes(space):
            self.rebuild_fulltext_index(space, d.name, parts=[pid])

    def clear_part(self, space: str, pid: int):
        """Release one partition's state (the replica moved away under
        BALANCE DATA — this host no longer serves it).  The part's slice
        of the dense-id dictionary goes too: if the part later moves
        BACK, install_part_state installs the then-current map, and stale
        local entries would resurrect deleted vids in export/device
        snapshots."""
        sd = self.space(space)
        with sd.lock:
            p = sd.parts[pid]
            p.vertices = {}
            p.out_edges = {}
            p.in_edges = {}
            p.pending_chains = {}
            p.applied_writes = OrderedDict()
            sd.part_counts[pid] = 0
            for v, d in list(sd.vid_to_dense.items()):
                if d % sd.num_parts == pid:
                    del sd.vid_to_dense[v]
                    sd.dense_to_vid[d] = None
            sd.epoch += 1
            _dbreak(sd)
        for d in self.catalog.indexes(space):
            self.rebuild_index(space, d.name, parts=[pid])
        for d in self.catalog.fulltext_indexes(space):
            self.rebuild_fulltext_index(space, d.name, parts=[pid])

    # ---- checkpoint / restore (CREATE SNAPSHOT; SURVEY §5) ----

    def checkpoint(self, dirpath: str,
                   spaces: Optional[List[str]] = None) -> Dict[str, Any]:
        """Durable on-disk checkpoint: catalog + every part's state +
        manifest.  The reference hard-links RocksDB SSTs; here part
        states are written as files — same contract (point-in-time,
        restorable)."""
        import json
        import os

        from . import schema_wire
        os.makedirs(dirpath, exist_ok=True)
        names = spaces if spaces is not None else sorted(self.catalog.spaces)
        manifest: Dict[str, Any] = {"spaces": {}}
        raw_catalog = getattr(self.catalog, "_inner", self.catalog)
        with open(os.path.join(dirpath, "catalog.bin"), "wb") as f:
            f.write(schema_wire.dumps(raw_catalog))
        for name in names:
            sd = self.space(name)
            spdir = os.path.join(dirpath, f"space_{sd.desc.space_id}")
            os.makedirs(spdir, exist_ok=True)
            with sd.lock:
                for pid in range(sd.num_parts):
                    with open(os.path.join(spdir, f"part_{pid}.bin"),
                              "wb") as f:
                        f.write(self.export_part_state(name, pid))
                manifest["spaces"][name] = {
                    "space_id": sd.desc.space_id,
                    "partition_num": sd.num_parts,
                    "epoch": sd.epoch,
                }
        with open(os.path.join(dirpath, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        return manifest

    def restore_backup(self, dirpath: str) -> Dict[str, Any]:
        """RESTORE BACKUP: replace this store's catalog and every
        space's partition state with the backup's point-in-time state —
        the standalone analog of the reference's BR restore (which
        rewrites storaged/metad data dirs offline; here the swap is
        in-process: catalog replace, SpaceData cache reset, per-part
        install with derived-index rebuild).  On a durable store the
        restored state immediately becomes the on-disk checkpoint
        (journal truncated) so a restart boots the restored world, not
        a pre-restore journal replay.

        Every backup file is read and decoded BEFORE the live state is
        touched, and a failure mid-install rolls the catalog and space
        cache back — a corrupt backup must not destroy the store
        (code-review r4).  Queries racing the swap itself see either
        world per space (the reference's br requires stopped services;
        the statement form trades that for a brief per-space cut).
        Epochs stay monotonic across the swap so pinned device
        snapshots from the pre-restore world can never be mistaken for
        current (code-review r4)."""
        import json
        import os

        from . import schema_wire
        with open(os.path.join(dirpath, "manifest.json")) as f:
            manifest = json.load(f)
        with open(os.path.join(dirpath, "catalog.bin"), "rb") as f:
            newcat = schema_wire.loads(f.read())
        parts: List[Tuple[str, int, bytes]] = []
        for name, info in manifest["spaces"].items():
            spdir = os.path.join(dirpath, f"space_{info['space_id']}")
            for pid in range(info["partition_num"]):
                with open(os.path.join(spdir, f"part_{pid}.bin"),
                          "rb") as f:
                    blob = f.read()
                from ..core import wire
                wire.loads(blob)     # decode check up front
                parts.append((name, pid, blob))

        old_cat, old_data = self.catalog, self.data
        # device-snapshot cache keys on (space NAME, epoch): the
        # restored world must start ABOVE every epoch the old world
        # ever pinned
        epoch_floor = {sd.desc.name: sd.epoch for sd in old_data.values()}
        if self._engine is not None:
            from .engine import JournalingCatalog
            self.catalog = JournalingCatalog(newcat, self._engine)
        else:
            self.catalog = newcat
        self.data = {}               # SpaceData rebuilds from the catalog
        self._ft_memo.clear()
        try:
            for name, pid, blob in parts:
                sd = self.space(name)
                floor = epoch_floor.get(name)
                if floor is not None and sd.epoch <= floor:
                    sd.epoch = floor + 1
                self.install_part_state(name, pid, blob)
        except Exception:
            self.catalog, self.data = old_cat, old_data
            self._ft_memo.clear()
            raise
        if self._engine is not None:
            self.compact_journal()
        return {"spaces": sorted(manifest["spaces"])}

    @classmethod
    def from_checkpoint(cls, dirpath: str) -> "GraphStore":
        import json
        import os

        from . import schema_wire
        with open(os.path.join(dirpath, "catalog.bin"), "rb") as f:
            catalog = schema_wire.loads(f.read())
        store = cls(catalog=catalog)
        with open(os.path.join(dirpath, "manifest.json")) as f:
            manifest = json.load(f)
        for name, info in manifest["spaces"].items():
            spdir = os.path.join(dirpath, f"space_{info['space_id']}")
            for pid in range(info["partition_num"]):
                with open(os.path.join(spdir, f"part_{pid}.bin"),
                          "rb") as f:
                    store.install_part_state(name, pid, f.read())
        return store

    # ---- read: point / scan ----
    def get_vertex(self, space: str, vid: Any) -> Optional[Dict[str, Dict[str, Any]]]:
        """vid → {tag: props} or None (TTL-expired tags invisible)."""
        import time as _t
        sd = self.space(space)
        tv = sd.part_for(vid).vertices.get(vid)
        if tv is None:
            return None
        now = _t.time()
        out = {}
        for t, (_, row) in tv.items():
            try:
                sv = self.catalog.get_tag(space, t).latest
            except SchemaError:
                continue            # tag dropped: its rows are invisible
            if not ttl_expired(sv, row, now):
                out[t] = dict(fill_row(sv, row))
        return out if out else None

    def get_edge(self, space: str, src: Any, etype: str, dst: Any,
                 rank: int = 0) -> Optional[Dict[str, Any]]:
        import time as _t
        sd = self.space(space)
        row = sd.part_for(src).out_edges.get(src, {}).get(etype, {}) \
            .get((rank, dst))
        if row is None:
            return None
        sv = self.catalog.get_edge(space, etype).latest
        if ttl_expired(sv, row, _t.time()):
            return None
        return dict(fill_row(sv, row))

    def scan_vertices(self, space: str, tag: Optional[str] = None,
                      parts: Optional[Iterable[int]] = None):
        """Yields (vid, tag, props)."""
        import time as _t
        sd = self.space(space)
        plist = sd.parts                 # one snapshot: repartition-safe
        part_ids = range(len(plist)) if parts is None else parts
        svs = {t.name: t.latest for t in self.catalog.tags(space)}
        now = _t.time()
        for pid in part_ids:
            if pid >= len(plist):
                continue
            for vid, tv in plist[pid].vertices.items():
                for t, (_, row) in tv.items():
                    if t not in svs:
                        continue    # tag dropped: rows invisible
                    if (tag is None or t == tag) and \
                            not ttl_expired(svs[t], row, now):
                        yield vid, t, fill_row(svs[t], row)

    def scan_edges(self, space: str, etype: Optional[str] = None,
                   parts: Optional[Iterable[int]] = None):
        """Yields (src, etype, rank, dst, props) from the out-plane."""
        import time as _t
        sd = self.space(space)
        plist = sd.parts                 # one snapshot: repartition-safe
        part_ids = range(len(plist)) if parts is None else parts
        svs = {e.name: e.latest for e in self.catalog.edges(space)}
        now = _t.time()
        for pid in part_ids:
            if pid >= len(plist):
                continue
            for src, per in plist[pid].out_edges.items():
                for et, em in per.items():
                    if etype is not None and et != etype:
                        continue
                    sv = svs.get(et)
                    if sv is None:
                        continue    # edge type dropped: rows invisible
                    for (rank, dst), row in em.items():
                        if not ttl_expired(sv, row, now):
                            yield src, et, rank, dst, fill_row(sv, row)

    # ---- read: getNeighbors (the hot-path op, host oracle form) ----
    def get_neighbors(self, space: str, vids: List[Any],
                      edge_types: Optional[List[str]] = None,
                      direction: str = "out",
                      edge_filter=None, limit_per_src: Optional[int] = None):
        """Yields (src, etype_name, rank, dst, props, signed_dir).

        signed_dir is +1 for out-edges, -1 for in-edges (matching the
        reference's negative-EdgeType convention for reversed traversal).
        Row order is deterministic: input vid order, then etype name, then
        (rank, neighbor) — the CSR sort order (csr.py) matches this.

        edge_filter / limit_per_src are the storage-side pushdown stage
        (cluster mode runs them inside storaged; applying them here keeps
        standalone semantics identical).
        """
        if edge_filter is not None or limit_per_src is not None:
            from ..cluster.pushdown import apply_edge_filter
            etypes_f = edge_types or sorted(
                e.name for e in self.catalog.edges(space))
            etype_ids = {et: self.catalog.get_edge(space, et).edge_type
                         for et in etypes_f}
            yield from apply_edge_filter(
                self.get_neighbors(space, vids, edge_types, direction),
                space, edge_filter, etype_ids, limit_per_src)
            return
        import time as _t
        sd = self.space(space)
        etypes = edge_types
        if etypes is None:
            etypes = sorted(e.name for e in self.catalog.edges(space))
        svs = {et: self.catalog.get_edge(space, et).latest for et in etypes}
        now = _t.time()
        for vid in vids:
            p = sd.part_for(vid)
            if direction in ("out", "both"):
                per = p.out_edges.get(vid, {})
                for et in etypes:
                    em = per.get(et)
                    if em:
                        sv = svs[et]
                        for (rank, dst) in sorted(em, key=_nbr_key):
                            row = em[(rank, dst)]
                            if not ttl_expired(sv, row, now):
                                yield (vid, et, rank, dst,
                                       fill_row(sv, row), 1)
            if direction in ("in", "both"):
                per = p.in_edges.get(vid, {})
                for et in etypes:
                    em = per.get(et)
                    if em:
                        sv = svs[et]
                        for (rank, src) in sorted(em, key=_nbr_key):
                            row = em[(rank, src)]
                            if not ttl_expired(sv, row, now):
                                yield (vid, et, rank, src,
                                       fill_row(sv, row), -1)

    def compact(self, space: str) -> int:
        """Physically purge TTL-expired rows (the compaction-filter GC of
        the reference).  Returns rows removed."""
        import time as _t
        now = _t.time()
        removed = 0
        # collect first (can't mutate while scanning)
        dead_tags: List[Tuple[Any, str]] = []
        sd = self.space(space)
        for t in self.catalog.tags(space):
            sv = t.latest
            if not sv.ttl_col:
                continue
            for p in sd.parts:
                for vid, tv in p.vertices.items():
                    if t.name in tv and ttl_expired(sv, tv[t.name][1], now):
                        dead_tags.append((vid, t.name))
        dead_edges: List[Tuple[Any, str, Any, int]] = []
        for e in self.catalog.edges(space):
            sv = e.latest
            if not sv.ttl_col:
                continue
            for p in sd.parts:
                for src, per in p.out_edges.items():
                    em = per.get(e.name)
                    if em:
                        for (rank, dst), row in em.items():
                            if ttl_expired(sv, row, now):
                                dead_edges.append((src, e.name, dst, rank))
        for vid, tag in dead_tags:
            self.delete_tag(space, vid, [tag])
            removed += 1
        for src, et, dst, rank in dead_edges:
            self.delete_edge(space, src, et, dst, rank)
            removed += 1
        return removed

    def stats(self, space: str) -> Dict[str, Any]:
        sd = self.space(space)
        return {
            "space": space,
            "partition_num": sd.num_parts,
            "vertices": sum(len(p.vertices) for p in sd.parts),
            "edges": sum(p.edge_count() for p in sd.parts),
            "epoch": sd.epoch,
            "per_part_edges": [p.edge_count() for p in sd.parts],
        }

    def stats_detail(self, space: str,
                     parts: Optional[Iterable[int]] = None
                     ) -> Dict[str, Dict[str, int]]:
        """Per-tag / per-edge-type counts (reference: the STATS job's
        per-schema rows surfaced by SHOW STATS)."""
        sd = self.space(space)
        part_ids = range(sd.num_parts) if parts is None else parts
        tags: Dict[str, int] = {}
        edges: Dict[str, int] = {}
        vertices = 0
        with sd.lock:
            for pid in part_ids:
                p = sd.parts[pid]
                vertices += len(p.vertices)
                for tv in p.vertices.values():
                    for t in tv:
                        tags[t] = tags.get(t, 0) + 1
                for per in p.out_edges.values():
                    for et, em in per.items():
                        edges[et] = edges.get(et, 0) + len(em)
        # totals ride along so SHOW STATS is ONE scan/fan-out and the
        # per-schema rows agree with the Space totals (same snapshot)
        return {"tags": tags, "edges": edges, "vertices": vertices,
                "total_edges": sum(edges.values())}


def _nbr_key(k: Tuple[int, Any]):
    """Neighbor iteration order within one (vid, etype): rank, then
    neighbor — numerically for INT64 vid spaces, lexicographically for
    string spaces.  get_neighbors and the CSR builder both use this key;
    it IS the host/device row-order contract."""
    rank, other = k
    if isinstance(other, int):
        return (rank, 0, other, "")
    return (rank, 1, 0, str(other))
