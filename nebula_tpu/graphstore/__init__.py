"""Host storage plane: schema catalog, partitioned store, CSR snapshots."""
from .schema import (Catalog, EdgeSchema, IndexDesc, PropDef, PropType,
                     SchemaError, SchemaVersion, SpaceDesc, TagSchema,
                     apply_defaults, check_type)
from .store import GraphStore, Partition, SpaceData, StoreError, stable_vid_hash
from .csr import (CODE_NULL, INT_NULL, CsrBlock, CsrSnapshot, StringPool,
                  TagTable, build_snapshot, encode_prop,
                  expand_frontier_host, neighbors_of)
