"""Secondary indexes — sorted per-part key lists with prefix/range scan.

Analog of the reference's index kv records + IndexScanNode family
(reference: src/storage/index + index keys in src/codec [UNVERIFIED —
empty mount, SURVEY §0]).  An index over (f1..fn) keeps, per partition,
a sorted list of (normalized key tuple, entity) where entity is the vid
(tag index) or (src, rank, dst) (edge index).  Scans take an equality
prefix plus an optional range on the next column — exactly the column-
hint shape the reference's optimizer extracts from LOOKUP predicates.

Semantics match the reference: CREATE INDEX starts empty and indexes
only subsequent writes; REBUILD INDEX backfills existing rows.
"""
from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core.value import total_order_key


class _Sentinel:
    """MIN sorts below everything, MAX above (via reflected compares:
    tuple elements fall back to these __gt__/__lt__ when their own
    __lt__ returns NotImplemented)."""

    __slots__ = ("lo",)

    def __init__(self, lo: bool):
        self.lo = lo

    def __lt__(self, o):
        return self.lo

    def __gt__(self, o):
        return not self.lo

    def __repr__(self):
        return "-inf" if self.lo else "+inf"


MIN, MAX = _Sentinel(True), _Sentinel(False)


def norm(v: Any):
    """Index column normalization: total order incl. NULL-last."""
    if isinstance(v, _Sentinel):
        return v
    return total_order_key(v)


class IndexData:
    """One index's entries across the parts of a space.

    Stored items are (key_norm_tuple, entity_norm, entity); list order is
    (key, entity_norm).  Probes are 1-tuples (partial_key,) so tuple
    comparison gives prefix-range semantics directly.
    """

    __slots__ = ("name", "fields", "is_edge", "index_id", "parts", "lock",
                 "field_lens")

    def __init__(self, name: str, fields: List[str], is_edge: bool,
                 num_parts: int, index_id: int = 0,
                 field_lens: Optional[List[int]] = None):
        self.name = name
        self.fields = list(fields)
        self.is_edge = is_edge
        self.index_id = index_id
        self.field_lens = list(field_lens) if field_lens \
            else [0] * len(self.fields)
        self.parts: List[List[Tuple]] = [[] for _ in range(num_parts)]
        from ..utils.racecheck import make_lock
        self.lock = make_lock("index_data")

    def key_of(self, row: Dict[str, Any]) -> Tuple:
        out = []
        for f, ln in zip(self.fields, self.field_lens):
            v = row.get(f)
            if ln and isinstance(v, str):
                # string prefix index (reference: name(10) truncates the
                # key); the LOOKUP planner keeps the full predicate as a
                # residual for truncated indexes, so a shared prefix can
                # never surface a wrong row
                v = v[:ln]
            out.append(norm(v))
        return tuple(out)

    def add(self, part: int, row: Dict[str, Any], entity: Any):
        k = self.key_of(row)
        en = norm(entity)
        with self.lock:
            lst = self.parts[part]
            i = bisect.bisect_left(lst, (k, en))
            if i < len(lst) and lst[i][0] == k and lst[i][1] == en:
                lst[i] = (k, en, entity)   # idempotent overwrite
            else:
                lst.insert(i, (k, en, entity))

    def remove(self, part: int, row: Dict[str, Any], entity: Any):
        k = self.key_of(row)
        en = norm(entity)
        with self.lock:
            lst = self.parts[part]
            i = bisect.bisect_left(lst, (k, en))
            if i < len(lst) and lst[i][0] == k and lst[i][1] == en:
                del lst[i]

    def clear(self):
        with self.lock:
            for lst in self.parts:
                lst.clear()

    def count(self) -> int:
        with self.lock:
            return sum(len(p) for p in self.parts)

    def scan(self, part: int, eq_prefix: List[Any],
             range_hint: Optional[Tuple[Any, Any, bool, bool]] = None
             ) -> List[Any]:
        """Entities with key[:k] == eq_prefix, optionally key[k] in the
        (lo, hi, lo_incl, hi_incl) range.  MIN/MAX mark open ends."""
        pre = tuple(norm(v) for v in eq_prefix)
        if range_hint is None:
            lo_probe = (pre,)
            hi_probe = (pre + (MAX,),)
        else:
            lo, hi, lo_inc, hi_inc = range_hint
            lo_n, hi_n = norm(lo), norm(hi)
            lo_probe = ((pre + (lo_n,)),) if lo_inc \
                else ((pre + (lo_n, MAX)),)
            hi_probe = ((pre + (hi_n, MAX)),) if hi_inc \
                else ((pre + (hi_n,)),)
        with self.lock:
            lst = self.parts[part]
            i = bisect.bisect_left(lst, lo_probe)
            j = bisect.bisect_left(lst, hi_probe)
            return [lst[t][2] for t in range(i, j)]


class GeoIndexData(IndexData):
    """Geo index over ONE geography column (reference: S2-cell-keyed geo
    index records [UNVERIFIED — empty mount, SURVEY §0 row 15]).

    A point is keyed by its level-30 Morton cell token; a LINESTRING /
    POLYGON is keyed by EVERY cell of a capped covering of its bbox
    (one entry per cell, possibly coarse) — single-centroid keying would
    silently drop shapes whose centroid falls outside the query cover
    (code-review repro).  scan_geo matches a query range two ways:
    entries whose base token lies inside the range (equal-or-finer
    cells), plus exact probes at each ANCESTOR base of the range's low
    end (coarser covering cells; at most 31 probes).  Both directions
    may over-match (shared base tokens across levels, bbox covers) —
    callers re-check the exact ST_ predicate as a residual, so a false
    positive costs a filter eval, never a wrong row.  NULL /
    non-geography values are keyed by the plain normalized value — they
    sort outside every token probe and are never produced by scan_geo."""

    __slots__ = ()

    def _cells_of(self, row) -> Optional[List[int]]:
        from ..core.geo import Geography, cell_token, covering_cells
        v = row.get(self.fields[0])
        if isinstance(v, str):
            # geography columns accept WKT text on write; index the
            # same shape reads serve
            from ..core.geo import from_wkt
            try:
                v = from_wkt(v)
            except Exception:  # noqa: BLE001 — malformed stays unkeyed
                return None
        if not isinstance(v, Geography):
            return None
        if v.kind == "point":
            return [cell_token(v)]
        return [base for base, _lvl in covering_cells(v, max_cells=16)]

    def key_of(self, row):
        cells = self._cells_of(row)
        if cells is None:
            return (norm(row.get(self.fields[0])),)
        return (norm(cells[0]),)

    def add(self, part: int, row, entity: Any):
        cells = self._cells_of(row)
        if cells is None:
            super().add(part, row, entity)
            return
        en = norm(entity)
        with self.lock:
            lst = self.parts[part]
            for c in cells:
                k = (norm(c),)
                i = bisect.bisect_left(lst, (k, en))
                if i < len(lst) and lst[i][0] == k and lst[i][1] == en:
                    lst[i] = (k, en, entity)
                else:
                    lst.insert(i, (k, en, entity))

    def remove(self, part: int, row, entity: Any):
        cells = self._cells_of(row)
        if cells is None:
            super().remove(part, row, entity)
            return
        en = norm(entity)
        with self.lock:
            lst = self.parts[part]
            for c in cells:
                k = (norm(c),)
                i = bisect.bisect_left(lst, (k, en))
                if i < len(lst) and lst[i][0] == k and lst[i][1] == en:
                    del lst[i]

    def scan_geo(self, part: int, ranges: List[Tuple[int, int]]) -> List[Any]:
        """Entities with an entry cell overlapping any INCLUSIVE
        (lo, hi) token range (covering_ranges output), deduplicated
        (multi-cell shapes would otherwise emit duplicate rows)."""
        out: List[Any] = []
        seen = set()

        def emit(t):
            _k, en, ent = t
            if en not in seen:
                seen.add(en)
                out.append(ent)

        with self.lock:
            lst = self.parts[part]
            for lo, hi in ranges:
                i = bisect.bisect_left(lst, ((norm(lo),),))
                j = bisect.bisect_left(lst, ((norm(hi), MAX),))
                for t in range(i, j):
                    emit(lst[t])
                # coarser covering cells: every ancestor-aligned base of
                # `lo` (zeroing the low 2s bits) may key a cell that
                # contains this range
                for s in range(1, 32):
                    a = lo & ~((1 << (2 * s)) - 1)
                    if a == lo:
                        continue         # already covered by the bisect
                    k = (norm(a),)
                    i = bisect.bisect_left(lst, (k,))
                    while i < len(lst) and lst[i][0] == k:
                        emit(lst[i])
                        i += 1
        return out
