"""DurableEngine — log-structured persistence for a standalone store.

The reference's standalone storaged keeps every part in a RocksDB
instance: WAL for durability, memtable for serving, SST compaction for
bounded recovery (reference: src/kvstore [UNVERIFIED — empty mount,
SURVEY §2 row 10]).  This build's serving copy is the in-memory part
dict (feeding the device CSR snapshot), so the persistent engine keeps
the same LSM shape with those roles reassigned:

    WAL        → journal.wal: every mutation appended as the SAME
                 wire-encoded command tuple the cluster raft log carries
                 (resolved rows — defaults like now() never re-evaluate
                 on replay)
    memtable   → the live SpaceData parts
    SST + compaction → checkpoint/: a full store checkpoint written by
                 compact(), after which the journal truncates; recovery
                 cost is bounded by the data written since the last
                 compaction, not the store's lifetime

`GraphStore(data_dir=...)` recovers in place on open: checkpoint load,
then journal replay, then journaling resumes.  Cluster mode does NOT
use this engine — there, durability is each part's raft WAL + snapshot
(storage_service) — so the command vocabulary being shared is what
keeps the two paths semantically identical.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Optional, Tuple

from ..cluster.wal import Wal
from . import schema_wire

# catalog mutators journaled by the catalog proxy (DDL must replay too —
# a recovered store with data but no schema could not decode it)
CATALOG_MUTATORS = frozenset({
    "create_tag", "create_edge", "alter_tag", "alter_edge",
    "drop_tag", "drop_edge", "create_index", "drop_index",
    "create_fulltext_index", "drop_fulltext_index",
    "add_listener", "remove_listener",
    "create_user", "drop_user", "alter_user", "change_password",
    "grant_role", "revoke_role"})


class JournalingCatalog:
    """Catalog proxy: DDL mutations append to the journal after applying
    (same shape as the cluster's CatalogProxy, pointed at a WAL instead
    of metad).

    Credential ops are journaled in their HASHED form
    (create_user_hashed / set_password_hash) — plaintext passwords must
    never reach a durable log."""

    def __init__(self, inner, engine: "DurableEngine"):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_engine", engine)

    def __getattr__(self, name):
        inner = object.__getattribute__(self, "_inner")
        if name in CATALOG_MUTATORS:
            engine = object.__getattribute__(self, "_engine")

            def call(*args, _name=name, **kw):
                out = getattr(inner, _name)(*args, **kw)
                if _name in ("create_user", "alter_user",
                             "change_password"):
                    uname = args[0]
                    h = inner.get_user(uname).pwd_hash
                    if _name == "create_user":
                        engine.log(("catalog", "create_user_hashed",
                                    [uname, h], {"if_not_exists": True}))
                    else:
                        engine.log(("catalog", "set_password_hash",
                                    [uname, h], {}))
                else:
                    engine.log(("catalog", _name, list(args), kw))
                return out
            return call
        return getattr(inner, name)


class DurableEngine:
    def __init__(self, data_dir: str):
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.ckpt_dir = os.path.join(data_dir, "checkpoint")
        self.journal = Wal(os.path.join(data_dir, "journal.wal"), sync=True)
        from ..utils.racecheck import make_lock
        self.lock = make_lock("journal")
        self._replaying = False

    # -- write path --------------------------------------------------------

    def log(self, cmd: Tuple):
        if self._replaying:
            return
        with self.lock:
            self.journal.append(self.journal.last_index() + 1, 0,
                                schema_wire.dumps(list(cmd)))

    # -- recovery ----------------------------------------------------------

    def recover_into(self, store) -> int:
        """Checkpoint load + journal replay.  Returns #replayed.

        Crash-safety: the checkpoint carries the journal index it
        covers (journal_upto) — if the process died between writing the
        checkpoint and truncating the journal, the stale prefix is
        SKIPPED by index rather than double-applied (pre-checkpoint DDL
        would otherwise fail on the recovered catalog).  If a crash
        landed between the two checkpoint renames, the previous
        checkpoint survives as checkpoint.old and is used instead."""
        import json
        ckpt = self.ckpt_dir
        if not os.path.exists(os.path.join(ckpt, "manifest.json")) and                 os.path.exists(os.path.join(ckpt + ".old",
                                            "manifest.json")):
            ckpt = ckpt + ".old"
        upto = 0
        if os.path.exists(os.path.join(ckpt, "manifest.json")):
            with open(os.path.join(ckpt, "catalog.bin"), "rb") as f:
                store.catalog = schema_wire.loads(f.read())
            with open(os.path.join(ckpt, "manifest.json")) as f:
                manifest = json.load(f)
            upto = manifest.get("journal_upto", 0)
            for name in sorted(manifest["spaces"]):
                info = manifest["spaces"][name]
                spdir = os.path.join(ckpt, f"space_{info['space_id']}")
                for pid in range(info["partition_num"]):
                    with open(os.path.join(spdir, f"part_{pid}.bin"),
                              "rb") as f:
                        store.install_part_state(name, pid, f.read())
        n = 0
        self._replaying = True
        try:
            first = max(self.journal.first_index(), 1, upto + 1)
            from .schema import SchemaError
            for (idx, _term, data) in self.journal.read_range(
                    first, self.journal.last_index() + 1):
                if idx <= upto:
                    continue
                try:
                    self._apply(store, tuple(schema_wire.loads(data)))
                except SchemaError:
                    # Every journaled DDL op SUCCEEDED when it was
                    # logged; a SchemaError on replay can only mean its
                    # effect is already present — an entry logged while
                    # a concurrent compact() was serializing the catalog
                    # lands in BOTH the checkpoint and the surviving
                    # journal tail.  Skipping is the correct idempotent
                    # resolution (data ops never raise SchemaError).
                    pass
                n += 1
        finally:
            self._replaying = False
        return n

    def _apply(self, store, cmd: Tuple):
        op = cmd[0]
        if op == "catalog":
            _, method, args, kw = cmd
            getattr(store.catalog, method)(*args, **kw)
            return
        if op == "create_space":
            store.create_space(cmd[1], **cmd[2])
            return
        if op == "drop_space":
            store.drop_space(cmd[1], if_exists=True)
            return
        if op == "rebuild_index":
            store.rebuild_index(cmd[1], cmd[2])
            return
        if op == "del_vertex":
            store.apply_delete_vertex(cmd[1], cmd[2])
            return
        if op == "del_vertex_rich":
            store.delete_vertex(cmd[1], cmd[2], with_edges=cmd[3])
            return
        if op == "del_tag":
            store.delete_tag(cmd[1], cmd[2], cmd[3])
            return
        if op == "del_edge":
            store.delete_edge(cmd[1], *cmd[2:])
            return
        if op == "upd_vertex":
            store.apply_update_vertex(cmd[1], *cmd[2:])
            return
        if op == "upd_edge_half":
            store.apply_update_edge_half(cmd[1], *cmd[2:])
            return
        if op == "vertex":
            store.apply_vertex(cmd[1], *cmd[2:])
            return
        if op == "edge_half":
            store.apply_edge_half(cmd[1], *cmd[2:])
            return
        if op == "edge_pair":
            _, space, src_v, etype, dst, rank, row = cmd
            store.apply_edge_half(space, src_v, etype, dst, rank, row, "out")
            store.apply_edge_half(space, src_v, etype, dst, rank, row, "in")
            return
        if op == "upd_edge_pair":
            _, space, src_v, etype, dst, rank, updates = cmd
            store.apply_update_edge_half(space, src_v, etype, dst, rank,
                                         updates, "out")
            store.apply_update_edge_half(space, src_v, etype, dst, rank,
                                         updates, "in")
            return
        if op == "clear_part":
            store.clear_part(cmd[1], cmd[2])
            return
        if op == "clear_space":
            store.clear_space(cmd[1], if_exists=True)
            return
        if op == "repartition":
            store.repartition(cmd[1], cmd[2])
            return
        raise ValueError(f"unknown journal op {op!r}")

    # -- compaction ---------------------------------------------------------

    def compact(self, store) -> int:
        """Write a fresh checkpoint, then truncate the journal — the
        SST-compaction analog; bounds recovery replay.

        LOCK ORDER: writers hold sd.lock then take engine.lock (_log
        inside the mutation's critical section keeps journal order ==
        apply order), so compact must NOT hold engine.lock across
        checkpoint() (which takes sd.lock) — ABBA.  It takes engine.lock
        only for the index capture and the truncation; entries logged
        during the checkpoint keep indices > upto, stay in the journal,
        and re-apply in order on recovery — data ops idempotently, DDL
        ops via recover_into's SchemaError skip (the op's effect is
        already in the checkpoint)."""
        import json
        import shutil
        with self.lock:
            upto = self.journal.last_index()
        tmp = self.ckpt_dir + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        store.checkpoint(tmp)
        # stamp the journal position this checkpoint covers (recovery
        # skips <= upto even if the truncation below never happens)
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["journal_upto"] = upto
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
        old = self.ckpt_dir + ".old"
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(self.ckpt_dir):
            os.rename(self.ckpt_dir, old)
        os.rename(tmp, self.ckpt_dir)
        if os.path.isdir(old):
            shutil.rmtree(old)
        with self.lock:
            if upto:
                self.journal.compact_to(upto)
        return upto

    def close(self):
        self.journal.close()
