"""Device delta-CSR: host-side dirty-key log + padded delta mirror.

The LSM split applied to device memory (ISSUE 19): the pinned base CSR
stays immutable while group-committed writes land in a bounded, padded
delta buffer — inserts union into every kernel's frontier expansion,
tombstones mask base edges — so an epoch bump costs one small
device_put instead of a graph-sized re-pin.  The same MemTable→SST
lineage as the reference's storage plane, shrunk to one mutable level.

Two host-side pieces live here (device placement is tpu/'s job):

* ``DeltaLog`` — a bounded *dirty-key* log attached to a space.  Write
  paths record WHICH edge/vertex keys changed, never row payloads; the
  apply step re-reads authoritative store state per key, which makes
  application idempotent and order-free (applying a superset of keys,
  or the same key twice, converges to the same mirror).  Structural
  changes that invalidate dense-id layout (REPARTITION, part installs,
  restore) mark the log broken → the next pin takes the full-rebuild
  path.

* ``HostDelta`` — the numpy mirror of the device delta buffers for one
  pinned snapshot: per (block, part) insert rows + tombstoned base edge
  indices, an ``apply()`` that folds dirty keys in by re-reading the
  store, and array builders that emit the padded (P, Dcap)/(P, Tcap)
  arrays the kernels consume.  Row encoding mirrors
  ``csr._build_block`` exactly (defaults, NULL sentinels, shared string
  pool) so merged results stay byte-identical to a full rebuild.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .csr import (INT_NULL, CsrSnapshot, _col_dtype, _encode_default,
                  encode_prop)

MAXI = np.iinfo(np.int32).max


def pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class DeltaUnsupported(Exception):
    """This change set cannot ride the delta (unknown dense id, broken
    log, hub-split snapshot); the caller falls back to a full rebuild."""


class DeltaOverflow(Exception):
    """A (block, part) delta ran past its padded capacity (or a fresh
    vertex past the vmax slack); full rebuild folds everything in."""


class DeltaLog:
    """Bounded dirty-key log for one space.

    Keys are ``("e", etype, src_vid, dst_vid, rank)`` and
    ``("v", vid)`` — identity only, no payload.  ``note()`` is called
    by every write path while a device snapshot is watching; the store
    holds its own lock around calls, so the log needs none.
    """

    __slots__ = ("floor_epoch", "keys", "broken", "cap", "part_epochs")

    def __init__(self, floor_epoch: int = 0, cap: int = 65536):
        self.floor_epoch = int(floor_epoch)
        self.cap = int(cap)
        self.keys: Dict[tuple, None] = {}
        self.broken = False
        # cluster feed: highest store epoch seen in a write ack, per
        # part (the group-commit ack path carries it) — the coverage
        # check against live part stats at delta_records time
        self.part_epochs: Dict[int, int] = {}

    def note(self, key: tuple) -> None:
        if self.broken:
            return
        self.keys[key] = None
        if len(self.keys) > self.cap:
            self.broken = True

    def note_break(self) -> None:
        self.broken = True

    def note_epoch(self, pid: int, epoch: int) -> None:
        if epoch > self.part_epochs.get(pid, 0):
            self.part_epochs[pid] = epoch

    def trim(self, keys) -> None:
        """Drop keys a successful delta apply consumed."""
        for k in keys:
            self.keys.pop(k, None)


@dataclass
class DeltaChanges:
    """What one apply() touched — the runtime re-puts exactly this."""
    blocks: Set[Tuple[str, str]] = field(default_factory=set)
    tag_cols: Set[Tuple[str, str]] = field(default_factory=set)
    num_vertices: bool = False
    dense_to_vid: bool = False

    def any(self) -> bool:
        return bool(self.blocks or self.tag_cols or self.num_vertices
                    or self.dense_to_vid)


def _enc_eq(a: Any, b: Any) -> bool:
    if isinstance(a, float) and isinstance(b, float):
        return a == b or (a != a and b != b)       # NaN == NaN here
    return a == b


def _rows_eq(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    if a.keys() != b.keys():
        return False
    return all(_enc_eq(v, b[k]) for k, v in a.items())


class HostDelta:
    """Host mirror of the device delta buffers for one snapshot."""

    def __init__(self, snap: CsrSnapshot, dcap: int, tcap: int = 0):
        self.snap = snap
        self.dcap = pow2(dcap)
        self.tcap = pow2(tcap or dcap)
        P = snap.num_parts
        # (etype, dir) → per-part OrderedDict
        #   (local_src, nbr_dense, rank) → {prop: encoded}
        self.ins: Dict[tuple, List[Dict[tuple, Dict[str, Any]]]] = {
            bk: [dict() for _ in range(P)] for bk in snap.blocks}
        # (etype, dir) → per-part set of tombstoned base edge indices
        self.tomb: Dict[tuple, List[Set[int]]] = {
            bk: [set() for _ in range(P)] for bk in snap.blocks}
        # per (etype,) cached encoded ALTER defaults keyed by prop name
        self._defaults: Dict[tuple, Dict[str, Any]] = {}

    # -- occupancy -------------------------------------------------------

    def edges_per_part(self) -> List[int]:
        P = self.snap.num_parts
        out = [0] * P
        for per in self.ins.values():
            for p in range(P):
                out[p] += len(per[p])
        return out

    def tombs_per_part(self) -> List[int]:
        P = self.snap.num_parts
        out = [0] * P
        for per in self.tomb.values():
            for p in range(P):
                out[p] += len(per[p])
        return out

    def total_edges(self) -> int:
        return sum(self.edges_per_part())

    def total_tombs(self) -> int:
        return sum(self.tombs_per_part())

    def fill_ratio(self) -> float:
        """Worst (block, part) occupancy against the padded caps —
        the compaction watermark input."""
        worst = 0.0
        for bk in self.ins:
            for p in range(self.snap.num_parts):
                worst = max(worst,
                            len(self.ins[bk][p]) / self.dcap,
                            len(self.tomb[bk][p]) / self.tcap)
        return worst

    # -- encoding (parity with csr._build_block) -------------------------

    def _block_defaults(self, bk, sv) -> Dict[str, Any]:
        d = self._defaults.get(bk)
        if d is None:
            d = {}
            if sv is not None:
                for pd in sv.props:
                    d[pd.name] = _encode_default(pd, self.snap.pool)
            self._defaults[bk] = d
        return d

    def _encode_edge_row(self, bk, sv, row: Dict[str, Any]) -> Dict[str, Any]:
        blk = self.snap.blocks[bk]
        defaults = self._block_defaults(bk, sv)
        enc: Dict[str, Any] = {}
        for name, pt in blk.prop_types.items():
            fill = (np.nan if _col_dtype(pt) == np.float64 else INT_NULL)
            v = row.get(name)
            if v is None:
                a = defaults.get(name)
                enc[name] = fill if a is None else a
            else:
                enc[name] = encode_prop(pt, v, self.snap.pool)
        return enc

    def _base_eidx(self, bk, p: int, li: int, nbr_dense: int,
                   rank: int) -> Optional[int]:
        blk = self.snap.blocks[bk]
        if li + 1 >= blk.indptr.shape[1]:
            return None
        lo, hi = int(blk.indptr[p, li]), int(blk.indptr[p, li + 1])
        seg_n = blk.nbr[p, lo:hi]
        seg_r = blk.rank[p, lo:hi]
        w = np.nonzero((seg_n == nbr_dense) & (seg_r == rank))[0]
        return None if w.size == 0 else lo + int(w[0])

    def _base_row_eq(self, bk, p: int, eidx: int,
                     enc: Dict[str, Any]) -> bool:
        blk = self.snap.blocks[bk]
        for name, col in blk.props.items():
            if not _enc_eq(col[p, eidx].item(), enc[name]):
                return False
        return True

    # -- apply -----------------------------------------------------------

    def apply(self, reader, keys, changes: Optional[DeltaChanges] = None
              ) -> DeltaChanges:
        """Fold dirty keys into the mirror by re-reading store state
        through `reader` (edge_row / vertex_rows / dense_of).  Raises
        DeltaOverflow / DeltaUnsupported; the caller full-rebuilds."""
        ch = changes or DeltaChanges()
        if self.snap.hub_dense is not None:
            raise DeltaUnsupported("degree-split snapshot")
        for key in keys:
            if key[0] == "e":
                self._apply_edge(reader, key, ch)
            elif key[0] == "v":
                self._apply_vertex(reader, key[1], ch)
            else:
                raise DeltaUnsupported(f"unknown delta key {key[0]!r}")
        P = self.snap.num_parts
        for bk in self.ins:
            for p in range(P):
                if len(self.ins[bk][p]) > self.dcap or \
                        len(self.tomb[bk][p]) > self.tcap:
                    raise DeltaOverflow(f"{bk} part {p}")
        return ch

    def _apply_edge(self, reader, key, ch: DeltaChanges) -> None:
        _, etype, src, dst, rank = key
        row, sv = reader.edge_row(etype, src, dst, rank)
        sd_src = reader.dense_of(src)
        sd_dst = reader.dense_of(dst)
        if sd_src is None or sd_dst is None:
            if row is not None:
                raise DeltaUnsupported(f"no dense id for edge {key[1:]}")
            return                      # gone + never pinned: nothing to do
        P = self.snap.num_parts
        # an insert_edge can mint dense ids for endpoints that have no
        # vertex row (and thus no ("v",...) dirty key) — a rebuild would
        # still map them, so the mirror must too (materialize decodes
        # vids through snap.dense_to_vid)
        if row is not None:
            self._touch_dense(sd_src, src, ch)
            self._touch_dense(sd_dst, dst, ch)
        enc = None if row is None else \
            self._encode_edge_row((etype, "out"), sv, row)
        for (bk, p, li, nbr) in (
                ((etype, "out"), sd_src % P, sd_src // P, sd_dst),
                ((etype, "in"), sd_dst % P, sd_dst // P, sd_src)):
            if bk not in self.snap.blocks:
                continue                # edge type not exported: invisible
            if li >= self.snap.vmax:
                raise DeltaOverflow(f"local row {li} past vmax")
            if self._apply_half(bk, p, li, nbr, rank, enc):
                ch.blocks.add(bk)

    def _apply_half(self, bk, p: int, li: int, nbr: int, rank: int,
                    enc: Optional[Dict[str, Any]]) -> bool:
        ins = self.ins[bk][p]
        tomb = self.tomb[bk][p]
        k = (li, nbr, rank)
        base = self._base_eidx(bk, p, li, nbr, rank)
        changed = False
        if enc is None:                                     # edge absent
            if ins.pop(k, None) is not None:
                changed = True
            if base is not None and base not in tomb:
                tomb.add(base)
                changed = True
            return changed
        if base is not None and self._base_row_eq(bk, p, base, enc):
            # live base content already matches: drop any overrides
            # (covers tombstone-resurrect — delete then identical
            # re-insert unmask the base row instead of duplicating it)
            if ins.pop(k, None) is not None:
                changed = True
            if base in tomb:
                tomb.discard(base)
                changed = True
            return changed
        if base is not None and base not in tomb:
            tomb.add(base)
            changed = True
        cur = ins.get(k)
        if cur is None or not _rows_eq(cur, enc):
            ins[k] = enc
            changed = True
        return changed

    def _touch_dense(self, dense: int, vid, ch: DeltaChanges) -> None:
        """Make sure the snapshot maps `dense` → `vid` and the owning
        part's vertex count covers its local row (a rebuild would)."""
        snap = self.snap
        P = snap.num_parts
        p, li = dense % P, dense // P
        if li >= snap.vmax:
            raise DeltaOverflow(f"vertex local row {li} past vmax")
        changed = False
        if dense >= len(snap.dense_to_vid) or \
                snap.dense_to_vid[dense] is None:
            need = dense + 1 - len(snap.dense_to_vid)
            if need > 0:
                snap.dense_to_vid.extend([None] * need)
            snap.dense_to_vid[dense] = vid
            ch.dense_to_vid = True
            changed = True
        if li + 1 > int(snap.num_vertices[p]):
            snap.num_vertices[p] = li + 1
            ch.num_vertices = True
            changed = True
        if changed:
            self._kill_caches()

    def _kill_caches(self) -> None:
        # position/existence masks and the dense→vid decode array are
        # cached per snapshot object — a vertex change must kill them
        # (tpu/match_agg._exists_flat, runtime._d2v: the latter can go
        # stale WITHOUT a length change when a None slot gains a vid)
        for attr in ("_exists_flat", "_d2v_arr"):
            if hasattr(self.snap, attr):
                try:
                    delattr(self.snap, attr)
                except AttributeError:
                    pass

    def _apply_vertex(self, reader, vid, ch: DeltaChanges) -> None:
        snap = self.snap
        dense = reader.dense_of(vid)
        if dense is None:
            raise DeltaUnsupported(f"no dense id for vertex {vid!r}")
        P = snap.num_parts
        p, li = dense % P, dense // P
        self._touch_dense(dense, vid, ch)
        rows = reader.vertex_rows(vid)
        for tag, tt in snap.tags.items():
            row = rows.get(tag)
            sv = reader.tag_schema(tag)
            if row is None:
                if tt.present[p, li]:
                    tt.present[p, li] = False
                    ch.tag_cols.add((tag, "present"))
                for name, pt in tt.prop_types.items():
                    fill = (np.nan
                            if _col_dtype(pt) == np.float64 else INT_NULL)
                    if not _enc_eq(tt.props[name][p, li].item(), fill):
                        tt.props[name][p, li] = fill
                        ch.tag_cols.add((tag, name))
                continue
            if not tt.present[p, li]:
                tt.present[p, li] = True
                ch.tag_cols.add((tag, "present"))
            defaults = self._block_defaults(("tag", tag), sv)
            for name, pt in tt.prop_types.items():
                fill = (np.nan
                        if _col_dtype(pt) == np.float64 else INT_NULL)
                v = row.get(name)
                if v is None:
                    a = defaults.get(name)
                    env = fill if a is None else a
                else:
                    env = encode_prop(pt, v, snap.pool)
                if not _enc_eq(tt.props[name][p, li].item(), env):
                    tt.props[name][p, li] = env
                    ch.tag_cols.add((tag, name))
        self._kill_caches()

    # -- padded arrays (host copies; the runtime device_puts them) -------

    def block_arrays(self, bk) -> Dict[str, Any]:
        snap = self.snap
        P = snap.num_parts
        blk = snap.blocks[bk]
        d_src = np.zeros((P, self.dcap), np.int32)
        d_dst = np.zeros((P, self.dcap), np.int32)
        d_rank = np.zeros((P, self.dcap), np.int32)
        d_valid = np.zeros((P, self.dcap), bool)
        d_props: Dict[str, np.ndarray] = {}
        for name, pt in blk.prop_types.items():
            dt = _col_dtype(pt)
            fill = np.nan if dt == np.float64 else INT_NULL
            d_props[name] = np.full((P, self.dcap), fill, dt)
        d_tomb = np.full((P, self.tcap), MAXI, np.int32)
        for p in range(P):
            for j, ((li, nbr, rank), enc) in \
                    enumerate(self.ins[bk][p].items()):
                d_src[p, j] = li
                d_dst[p, j] = nbr
                d_rank[p, j] = rank
                d_valid[p, j] = True
                for name in d_props:
                    d_props[name][p, j] = enc[name]
            ts = sorted(self.tomb[bk][p])
            if ts:
                d_tomb[p, :len(ts)] = np.asarray(ts, np.int32)
        return {"d_src": d_src, "d_dst": d_dst, "d_rank": d_rank,
                "d_valid": d_valid, "d_tomb": d_tomb, "d_props": d_props}

    def nbytes(self) -> int:
        total = 0
        for bk in self.snap.blocks:
            blk = self.snap.blocks[bk]
            per_row = 4 * 3 + 1 + sum(
                np.dtype(_col_dtype(pt)).itemsize
                for pt in blk.prop_types.values())
            total += self.snap.num_parts * (
                self.dcap * per_row + self.tcap * 4)
        return total


class LocalStoreReader:
    """Re-read adapter over a single-process GraphStore (under sd.lock
    at the call site: apply runs with the gate's write side held, so
    reads here see a consistent post-commit state)."""

    def __init__(self, store, space: str):
        self.store = store
        self.space = space
        self.sd = store.space(space)
        import time as _t
        self.now = _t.time()

    def dense_of(self, vid) -> Optional[int]:
        d = self.sd.vid_to_dense.get(vid)
        return None if d is None else int(d)

    def edge_row(self, etype, src, dst, rank):
        from .schema import SchemaError
        from .store import ttl_expired
        try:
            sv = self.store.catalog.get_edge(self.space, etype).latest
        except SchemaError:
            return None, None           # dropped edge type: invisible
        row = self.sd.parts[self.sd.part_of(src)].out_edges \
            .get(src, {}).get(etype, {}).get((rank, dst))
        if row is None:
            return None, sv
        if sv.ttl_col and sv.ttl_duration > 0 and \
                ttl_expired(sv, row, self.now):
            return None, sv
        return row, sv

    def vertex_rows(self, vid) -> Dict[str, Dict[str, Any]]:
        from .store import ttl_expired
        tv = self.sd.parts[self.sd.part_of(vid)].vertices.get(vid) or {}
        out = {}
        for tag, (_ver, row) in tv.items():
            sv = self.tag_schema(tag)
            if sv is None:
                continue
            if sv.ttl_col and sv.ttl_duration > 0 and \
                    ttl_expired(sv, row, self.now):
                continue
            out[tag] = row
        return out

    def tag_schema(self, tag):
        from .schema import SchemaError
        try:
            return self.store.catalog.get_tag(self.space, tag).latest
        except SchemaError:
            return None
