"""JSON-safe (de)serialization of the schema catalog.

The meta plane replicates DDL commands and catalog snapshots through
raft and ships the catalog to clients (the meta.thrift struct analog;
reference: src/interface/meta.thrift [UNVERIFIED — empty mount,
SURVEY §0]).  These payloads cross process boundaries, so they use the
same JSON wire discipline as values (core/wire.py) instead of pickle —
an unpickler reachable from an RPC port is arbitrary code execution.

Tags used here ("propdef", "schemaver", ...) are disjoint from
core.wire's value tags; containers recurse through this module so
schema objects can appear anywhere inside a command's args/kw.
"""
from __future__ import annotations

import json
from typing import Any

from ..core import wire
from .schema import (Catalog, EdgeSchema, IndexDesc, PropDef, PropType,
                     SchemaVersion, SpaceDesc, TagSchema, UserDesc)


def to_jso(v: Any) -> Any:
    if isinstance(v, PropDef):
        return {"@t": "propdef", "n": v.name, "pt": v.ptype.value,
                "null": v.nullable, "d": wire.to_wire(v.default),
                "hd": v.has_default, "fl": v.fixed_len, "c": v.comment}
    if isinstance(v, SchemaVersion):
        return {"@t": "schemaver", "v": v.version,
                "p": [to_jso(p) for p in v.props],
                "tc": v.ttl_col, "td": v.ttl_duration}
    if isinstance(v, TagSchema):
        return {"@t": "tagschema", "n": v.name, "id": v.tag_id,
                "vs": [to_jso(x) for x in v.versions]}
    if isinstance(v, EdgeSchema):
        return {"@t": "edgeschema", "n": v.name, "id": v.edge_type,
                "vs": [to_jso(x) for x in v.versions]}
    if isinstance(v, SpaceDesc):
        return {"@t": "spacedesc", "n": v.name, "id": v.space_id,
                "pn": v.partition_num, "rf": v.replica_factor,
                "vt": v.vid_type, "c": v.comment}
    if isinstance(v, IndexDesc):
        return {"@t": "indexdesc", "n": v.name, "sn": v.schema_name,
                "f": list(v.fields), "e": v.is_edge, "id": v.index_id,
                "ft": v.fulltext, "fl": list(v.field_lens or [])}
    if isinstance(v, UserDesc):
        return {"@t": "userdesc", "n": v.name, "p": v.pwd_hash,
                "r": dict(v.roles)}
    if isinstance(v, Catalog):
        return {"@t": "catalog",
                "users": {n: to_jso(u) for n, u in v.users.items()},
                "spaces": {n: to_jso(sp) for n, sp in v.spaces.items()},
                "tags": [[sid, {n: to_jso(t) for n, t in d.items()}]
                         for sid, d in v._tags.items()],
                "edges": [[sid, {n: to_jso(e) for n, e in d.items()}]
                          for sid, d in v._edges.items()],
                "indexes": [[sid, {n: to_jso(i) for n, i in d.items()}]
                            for sid, d in v._indexes.items()],
                "ft_indexes": [[sid, {n: to_jso(i) for n, i in d.items()}]
                               for sid, d in v._ft_indexes.items()],
                "listeners": [[sid, [list(x) for x in ls]]
                              for sid, ls in v._listeners.items()],
                "next_space": v._next_space,
                "next_schema_id": [[sid, nid] for sid, nid
                                   in v._next_schema_id.items()],
                "version": v.version}
    if isinstance(v, (list, tuple)):
        return {"@t": "list", "v": [to_jso(x) for x in v]}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            return {"@t": "map", "v": {k: to_jso(x) for k, x in v.items()}}
        return {"@t": "kvmap",
                "v": [[to_jso(k), to_jso(x)] for k, x in v.items()]}
    return wire.to_wire(v)


def from_jso(j: Any) -> Any:
    if not isinstance(j, dict) or "@t" not in j:
        return wire.from_wire(j)
    t = j["@t"]
    if t == "propdef":
        return PropDef(j["n"], PropType(j["pt"]), j["null"],
                       wire.from_wire(j["d"]), j["hd"], j["fl"], j["c"])
    if t == "schemaver":
        return SchemaVersion(j["v"], [from_jso(p) for p in j["p"]],
                             j["tc"], j["td"])
    if t == "tagschema":
        return TagSchema(j["n"], j["id"], [from_jso(x) for x in j["vs"]])
    if t == "edgeschema":
        return EdgeSchema(j["n"], j["id"], [from_jso(x) for x in j["vs"]])
    if t == "spacedesc":
        return SpaceDesc(j["n"], j["id"], j["pn"], j["rf"], j["vt"], j["c"])
    if t == "indexdesc":
        return IndexDesc(j["n"], j["sn"], list(j["f"]), j["e"], j["id"],
                         j.get("ft", False), list(j.get("fl") or []))
    if t == "userdesc":
        return UserDesc(j["n"], j["p"], j["r"])
    if t == "catalog":
        c = Catalog()
        if "users" in j:        # pre-ACL snapshots keep the default root
            c.users = {n: from_jso(u) for n, u in j["users"].items()}
        c.spaces = {n: from_jso(sp) for n, sp in j["spaces"].items()}
        c._tags = {sid: {n: from_jso(t_) for n, t_ in d.items()}
                   for sid, d in j["tags"]}
        c._edges = {sid: {n: from_jso(e) for n, e in d.items()}
                    for sid, d in j["edges"]}
        c._indexes = {sid: {n: from_jso(i) for n, i in d.items()}
                      for sid, d in j["indexes"]}
        # pre-fulltext snapshots carry neither key
        c._ft_indexes = {sid: {n: from_jso(i) for n, i in d.items()}
                         for sid, d in j.get("ft_indexes", [])}
        c._listeners = {sid: [list(x) for x in ls]
                        for sid, ls in j.get("listeners", [])}
        c._next_space = j["next_space"]
        c._next_schema_id = {sid: nid for sid, nid in j["next_schema_id"]}
        c.version = j["version"]
        return c
    if t == "list":
        return [from_jso(x) for x in j["v"]]
    if t == "map":
        return {k: from_jso(x) for k, x in j["v"].items()}
    if t == "kvmap":
        out = {}
        for kj, xj in j["v"]:
            k = from_jso(kj)
            if isinstance(k, list):
                k = tuple(k)
            out[k] = from_jso(xj)
        return out
    return wire.from_wire(j)


def dumps(v: Any) -> bytes:
    return json.dumps(to_jso(v), separators=(",", ":")).encode()


def loads(data: bytes) -> Any:
    return from_jso(json.loads(data.decode()))
