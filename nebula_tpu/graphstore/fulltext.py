"""Full-text search plane: inverted index + async listener.

Reference architecture (SURVEY §2 row 10 `Listener`, §1 L4 [UNVERIFIED —
empty mount]): storaged replicates each part's committed raft log to an
external Elasticsearch sink via a `Listener` (a raft learner), and
LOOKUP's text predicates (PREFIX / WILDCARD / REGEXP / FUZZY) are served
from that sink, eventually-consistent with the base data.

This build keeps the same shape with the sink in-process:

  * every write-path mutation enqueues (never applies inline) to a
    `FulltextListener` — a single background thread that is the ONLY
    writer to the `FulltextIndexData` structures, mirroring the
    one-way replication of the reference (base writes never wait for
    the text index);
  * text LOOKUPs call `drain()` first, upgrading the reference's
    eventual consistency to read-your-writes — cheap in-process, and it
    keeps TCK scenarios deterministic (a documented deviation);
  * cluster replicas apply the same raw write commands through the same
    store hooks, so each replica maintains its own sink — the
    leader-local search result equals what the reference's shared ES
    cluster would return for that part.

Query semantics (value-level, matching the reference's LOOKUP text ops):
  PREFIX(tag.prop, "b")      — value starts with "b" (case-folded)
  WILDCARD(tag.prop, "*b?")  — fnmatch over the whole value (case-folded)
  REGEXP(tag.prop, "re")     — re.search over the raw value
  FUZZY(tag.prop, "word")    — some TOKEN within Levenshtein distance
                               (auto: 1 for len<6, else 2) of the query
The token inverted index accelerates FUZZY (vocabulary scan, not corpus
scan); the other ops scan per-part value maps, which are dicts small
enough that Python-loop cost matches the host parity plan everywhere
else in the engine.
"""
from __future__ import annotations

import fnmatch
import queue
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .index import norm


def analyze(text: str) -> List[str]:
    """Lowercased alphanumeric word tokens (the `standard` analyzer)."""
    return re.findall(r"[0-9a-z]+", text.lower())


def levenshtein_leq(a: str, b: str, k: int) -> bool:
    """Edit distance(a, b) <= k, banded (O(len*k))."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = max(1, i - k)
        hi = min(len(b), i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ca != b[j - 1]))
        if hi < len(b):
            cur[hi + 1:] = [k + 1] * (len(b) - hi)
        if min(cur) > k:
            return False
        prev = cur
    return prev[len(b)] <= k


class TextServiceRegistry:
    """SIGN IN/OUT TEXT SERVICE target list (the reference registers
    external Elasticsearch clients in metad; the in-process fulltext
    plane doesn't need one to FUNCTION, but the statement surface and
    SHOW TEXT SEARCH CLIENTS must reflect what an operator signed in).
    Process-local; the cluster graphd layer shares one process."""

    def __init__(self):
        self.clients: list = []     # [{"host", "port", "user"}]

    def sign_in(self, endpoints, user=None, password=None):
        for ep in endpoints:
            host, _, port = ep.partition(":")
            self.clients.append({"host": host,
                                 "port": int(port) if port else 9200,
                                 "user": user or "", "conn": "http"})

    def sign_out(self):
        if not self.clients:
            raise ValueError("no text service clients signed in")
        self.clients.clear()


def text_services(store) -> TextServiceRegistry:
    """The store's registry (created on demand) — store-scoped so every
    engine/test gets isolated sign-in state, like the rest of the
    catalog."""
    reg = getattr(store, "_text_services", None)
    if reg is None:
        reg = store._text_services = TextServiceRegistry()
    return reg


class FulltextIndexData:
    """One full-text index over one string field of one tag/edge.

    Per part: `values` entity→raw string (the scan corpus) and an
    inverted `tokens` token→set(entity) map (the FUZZY vocabulary).
    Single-writer: only the FulltextListener thread mutates these."""

    def __init__(self, name: str, schema_name: str, field: str,
                 is_edge: bool, num_parts: int, index_id: int,
                 analyzer: str = "standard"):
        self.name = name
        self.schema_name = schema_name
        self.field = field
        self.is_edge = is_edge
        self.index_id = index_id
        self.analyzer = analyzer
        # guards values/tokens: the listener thread writes while query
        # threads search — unsynchronized dict iteration would raise
        # "dictionary changed size during iteration" mid-LOOKUP
        from ..utils.racecheck import make_lock
        self.lock = make_lock("fulltext_data")
        self.values: List[Dict[Any, str]] = [dict()
                                             for _ in range(num_parts)]
        self.tokens: List[Dict[str, set]] = [dict()
                                             for _ in range(num_parts)]

    def add(self, part: int, text: str, entity: Any):
        with self.lock:
            self.values[part][entity] = text
            tm = self.tokens[part]
            for tok in set(analyze(text)):
                tm.setdefault(tok, set()).add(entity)

    def remove(self, part: int, entity: Any):
        with self.lock:
            text = self.values[part].pop(entity, None)
            if text is None:
                return
            tm = self.tokens[part]
            for tok in set(analyze(text)):
                s = tm.get(tok)
                if s is not None:
                    s.discard(entity)
                    if not s:
                        del tm[tok]

    def clear(self):
        with self.lock:
            for d in self.values:
                d.clear()
            for d in self.tokens:
                d.clear()

    def count(self) -> int:
        with self.lock:
            return sum(len(d) for d in self.values)

    # -- search ----------------------------------------------------------

    def search(self, op: str, pattern: str,
               parts: Optional[List[int]] = None) -> List[Any]:
        """Entities whose value matches, part-ordered then value-ordered
        (deterministic rows for the executor)."""
        op = op.upper()
        part_ids = parts if parts is not None \
            else range(len(self.values))
        out: List[Any] = []
        if op == "REGEXP":
            try:
                rx = re.compile(pattern)
            except re.error as ex:
                raise ValueError(f"bad REGEXP pattern: {ex}") from None
        elif op == "WILDCARD":
            rx = re.compile(fnmatch.translate(pattern.lower()))
        with self.lock:
            for pid in part_ids:
                vals = self.values[pid]
                if op == "PREFIX":
                    pat = pattern.lower()
                    hits = [e for e, v in vals.items()
                            if v.lower().startswith(pat)]
                elif op == "WILDCARD":
                    hits = [e for e, v in vals.items()
                            if rx.match(v.lower())]
                elif op == "REGEXP":
                    hits = [e for e, v in vals.items() if rx.search(v)]
                elif op == "FUZZY":
                    toks = analyze(pattern)
                    if not toks:
                        hits = []
                    else:
                        q = toks[0]
                        k = 1 if len(q) < 6 else 2
                        ents: set = set()
                        for tok, posting in self.tokens[pid].items():
                            if levenshtein_leq(tok, q, k):
                                ents |= posting
                        hits = list(ents)
                else:
                    raise ValueError(f"unknown text-search op `{op}'")
                hits.sort(key=lambda e: tuple(norm(x) for x in e)
                          if isinstance(e, tuple) else (norm(e),))
                out.extend(hits)
        return out


class FulltextListener:
    """The async replication thread feeding every full-text index of one
    store process (reference: one Listener replica per part shipping
    committed logs to ES; here one thread draining a queue of
    already-committed mutations).

    Single consumer; producers are the store's write paths.  `drain()`
    blocks until everything enqueued before the call has applied."""

    def __init__(self):
        self.q: "queue.Queue[Optional[Tuple]]" = queue.Queue()
        self.applied = 0
        self._lock = threading.Lock()
        self._targets: Dict[Tuple[str, str], FulltextIndexData] = {}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ft-listener")
        self._thread.start()

    def register(self, space: str, data: FulltextIndexData):
        with self._lock:
            self._targets[(space, data.name)] = data

    def unregister(self, space: str, name: str):
        with self._lock:
            self._targets.pop((space, name), None)

    def target(self, space: str, name: str) -> Optional[FulltextIndexData]:
        with self._lock:
            return self._targets.get((space, name))

    # -- producer side ---------------------------------------------------

    def enqueue(self, op: str, space: str, name: str, part: int = 0,
                text: str = "", entity: Any = None, gen: int = 0):
        """`gen` is the target index's index_id: ops in flight across a
        DROP + re-CREATE of the same name must NOT apply to the new
        incarnation (it starts empty until REBUILD)."""
        self.q.put((op, space, name, part, text, entity, gen))

    def drain(self, stall_timeout: float = 30.0):
        """Wait until the queue as of now is fully applied.

        The timeout is PROGRESS-aware, not absolute: a full-corpus
        REBUILD can legitimately take minutes, so only a listener that
        stops applying for `stall_timeout` seconds raises."""
        done = threading.Event()
        self.q.put(("__mark__", done))
        last, stalled_since = -1, time.monotonic()
        while not done.wait(0.2):
            now = time.monotonic()
            if self.applied != last:
                last, stalled_since = self.applied, now
            elif now - stalled_since > stall_timeout:
                raise TimeoutError("fulltext listener failed to drain")

    def lag(self) -> int:
        return self.q.qsize()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._targets)
        return {"type": "ELASTICSEARCH", "status": "ONLINE",
                "indexes": n, "applied": self.applied,
                "lag": self.lag()}

    def stop(self):
        self.q.put(None)
        self._thread.join(timeout=5)

    # -- consumer side ---------------------------------------------------

    def _run(self):
        while True:
            item = self.q.get()
            if item is None:
                return
            if item[0] == "__mark__":
                item[1].set()
                continue
            op, space, name, part, text, entity, gen = item
            tgt = self.target(space, name)
            if tgt is None or tgt.index_id != gen:
                continue        # index dropped/recreated with ops in flight
            try:
                if op == "add":
                    tgt.add(part, text, entity)
                elif op == "remove":
                    tgt.remove(part, entity)
                elif op == "clear":
                    tgt.clear()
            except Exception:       # a poison row must not kill the sink
                pass
            self.applied += 1
