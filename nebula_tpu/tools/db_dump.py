"""db-dump — decode and print the contents of a checkpoint directory.

The reference's db_dump decodes RocksDB SSTs/keys (src/tools/db-dump
[UNVERIFIED — empty mount, SURVEY §0]); ours decodes the on-disk
checkpoint format written by CREATE SNAPSHOT / GraphStore.checkpoint.

    python -m nebula_tpu.tools.db_dump <checkpoint_dir> \
        [--space NAME] [--mode stat|vertex|edge] [--limit N]
"""
from __future__ import annotations

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-db-dump")
    ap.add_argument("checkpoint", help="checkpoint directory")
    ap.add_argument("--space", default=None)
    ap.add_argument("--mode", choices=["stat", "vertex", "edge"],
                    default="stat")
    ap.add_argument("--limit", type=int, default=20)
    args = ap.parse_args(argv)

    from ..graphstore.store import GraphStore
    store = GraphStore.from_checkpoint(args.checkpoint)
    spaces = [args.space] if args.space else sorted(store.catalog.spaces)
    for name in spaces:
        st = store.stats(name)
        print(f"space `{name}': parts={st['partition_num']} "
              f"vertices={st['vertices']} edges={st['edges']} "
              f"epoch={st['epoch']}")
        if args.mode == "stat":
            print(f"  per-part edges: {st['per_part_edges']}")
            for t in store.catalog.tags(name):
                print(f"  tag {t.name}: "
                      f"{[p.name for p in t.latest.props]}")
            for e in store.catalog.edges(name):
                print(f"  edge {e.name}: "
                      f"{[p.name for p in e.latest.props]}")
            for d in store.catalog.indexes(name):
                kind = "edge" if d.is_edge else "tag"
                print(f"  {kind} index {d.name} ON "
                      f"{d.schema_name}{tuple(d.fields)}")
        elif args.mode == "vertex":
            _dump(store.scan_vertices(name),
                  lambda r: f"  {r[0]!r} :{r[1]} {r[2]}", args.limit)
        else:
            _dump(store.scan_edges(name),
                  lambda r: f"  {r[0]!r} -[:{r[1]}@{r[2]}]-> {r[3]!r} "
                            f"{r[4]}", args.limit)
    return 0


def _dump(rows, fmt, limit: int):
    for n, r in enumerate(rows):
        if n >= limit:
            print(f"  ... (limit {limit})")
            return
        print(fmt(r))


if __name__ == "__main__":
    sys.exit(main())
