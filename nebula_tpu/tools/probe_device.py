"""Bounded-timeout device probe with a STRUCTURED verdict (ISSUE 17).

The axon TPU tunnel can wedge: a hard-killed client leaves its chip
claim held and the next `jax.devices()` blocks forever inside backend
registration.  Every prior probe call-site (bench.py's startup guard,
tools_probe_tpu.sh's watch loop) re-implemented the same subprocess +
timeout + stdout-grep dance and each graded the outcome differently —
the watch loop once looped forever because its grep could never match
the tunnel's platform string.

This module is the ONE probe implementation.  It runs `jax.devices()`
in a THROWAWAY subprocess (the parent never imports jax, so a wedged
tunnel can hang only the child) under a hard deadline and returns a
machine-readable verdict:

    {"probe_status": "ok" | "timeout" | "no_devices" | "error",
     "platform":  "tpu" | "cpu" | ... | None,
     "n_devices": int,
     "rc":        child exit code (-1 on timeout),
     "detail":    last stderr/stdout fragment for the log line}

`probe_status` semantics — the bench `multichip` block embeds this
verdict verbatim, so a missing real-device A/B is always attributable:

  ok          a non-cpu accelerator platform answered within deadline
  no_devices  the child ran fine but only found host CPU devices
              (no tunnel configured, or tunnel resolves to cpu)
  timeout     the child exceeded the deadline — wedged tunnel
  error       the child exited non-zero (import error, claim refused)

CLI: ``python -m nebula_tpu.tools.probe_device [--timeout S] [--expect
tpu]`` prints the verdict as one JSON line and exits 0 on "ok",
2 on "no_devices", 3 on "timeout", 4 on "error" — script-friendly
(tools_probe_tpu.sh branches on the exit code, not on grep).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

# one parsable line; the sentinel prefix survives jax/absl WARNING noise
_SENTINEL = "NEBULA_PROBE:"
_CHILD = ("import jax, json; d = jax.devices(); "
          "print('" + _SENTINEL + "' + json.dumps("
          "{'platform': d[0].platform, 'n': len(d)}))")

DEFAULT_TIMEOUT_S = 150


def probe(timeout_s: Optional[float] = None,
          python: Optional[str] = None) -> dict:
    """Run the subprocess probe; never raises, never hangs past the
    deadline.  `timeout_s` defaults to $NEBULA_BENCH_PROBE_TIMEOUT or
    150 s (the bench startup guard's historical deadline)."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("NEBULA_BENCH_PROBE_TIMEOUT",
                                         DEFAULT_TIMEOUT_S))
    res = {"probe_status": "error", "platform": None, "n_devices": 0,
           "rc": -1, "detail": "", "timeout_s": timeout_s}

    def _txt(v) -> str:
        if isinstance(v, bytes):
            v = v.decode(errors="replace")
        return (v or "").strip()[-400:]

    try:
        out = subprocess.run(
            [python or sys.executable, "-c", _CHILD],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired as ex:
        res.update(probe_status="timeout",
                   detail=_txt(ex.stderr)
                   or "probe exceeded deadline (wedged device tunnel)")
        return res
    except OSError as ex:  # interpreter itself unrunnable
        res.update(probe_status="error", detail=repr(ex)[-400:])
        return res

    res["rc"] = out.returncode
    if out.returncode != 0:
        res.update(probe_status="error", detail=_txt(out.stderr))
        return res
    payload = None
    for line in out.stdout.splitlines():
        if line.startswith(_SENTINEL):
            try:
                payload = json.loads(line[len(_SENTINEL):])
            except ValueError:
                pass
    if payload is None:
        res.update(probe_status="error",
                   detail="no probe sentinel in child stdout: "
                          + _txt(out.stdout))
        return res
    res["platform"] = str(payload.get("platform"))
    res["n_devices"] = int(payload.get("n", 0))
    # any non-cpu platform counts as a live accelerator (the axon
    # tunnel reports "axon", real chips report "tpu" — the r4 probe
    # regression was grepping for one exact string)
    if res["platform"] and res["platform"] != "cpu":
        res["probe_status"] = "ok"
    else:
        res["probe_status"] = "no_devices"
    res["detail"] = _txt(out.stdout)
    return res


_EXIT = {"ok": 0, "no_devices": 2, "timeout": 3, "error": 4}


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="bounded-timeout accelerator probe (JSON verdict)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="probe deadline in seconds (default: "
                         "$NEBULA_BENCH_PROBE_TIMEOUT or 150)")
    args = ap.parse_args(argv)
    res = probe(timeout_s=args.timeout)
    print(json.dumps(res))
    return _EXIT.get(res["probe_status"], 4)


if __name__ == "__main__":
    sys.exit(main())
