"""write-bench — microbenchmark of the group-commit write path (ISSUE 3;
the write-path mirror of wire_bench.py).

A/B of per-command vs grouped raft proposals over an in-process raft
group with a SYNCHRONOUS WAL (identical durability on both sides — the
comparison is fsync/replication amortization, not fsync removal):

  per_command      propose() once per entry — one WAL sync + one
                   replication round each (the pre-ISSUE-3 rpc_write
                   loop shape)
  grouped@B        propose_batch() in chunks of B entries — one lock
                   hold, one (coalesced) fsync, one replication wake
                   per chunk, for B in 1/8/64/512

Also times the WAL legs in isolation (append-per-entry vs append_batch,
both fsynced) so a regression in the log layer shows up separately
from consensus.

    python -m nebula_tpu.tools.write_bench [--entries 384] [--nodes 3]
                                           [--payload 64] [--repeat 1]

Emits one JSON object on stdout (CI-diffable, like wire_bench);
bench.py folds the headline ratio into its `write_raft_toss` config.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

BATCH_SIZES = (1, 8, 64, 512)


def _mk_cluster(tmp: str, n_nodes: int):
    from ..cluster.raft import LoopbackTransport, RaftPart

    tr = LoopbackTransport()
    nodes = [f"n{i}" for i in range(n_nodes)]
    parts = []
    for nid in nodes:
        parts.append(RaftPart(
            "wb", nid, nodes, tr, os.path.join(tmp, nid),
            apply_cb=lambda i, d: None,
            election_timeout=(0.05, 0.12), heartbeat_interval=0.02,
            wal_sync=True))
    for p in parts:
        p.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leaders = [p for p in parts if p.is_leader()]
        if len(leaders) == 1:
            return parts, leaders[0]
        time.sleep(0.01)
    raise RuntimeError("no leader elected")


def _drive(parts, leader, payloads, batch: int) -> float:
    """Seconds to commit all payloads at the given proposal batch size
    (batch=0 → propose() per entry, the per-command baseline).  Retries
    against the current leader on deposal (the propose contract) — an
    election mid-run costs time, which is honest, not a crash."""
    def commit(chunk):
        nonlocal leader
        deadline = time.monotonic() + 60
        while True:
            r = (leader.propose(chunk[0], timeout=30.0) if batch == 0
                 else leader.propose_batch(chunk, timeout=30.0))
            if r:
                return
            if time.monotonic() > deadline:
                raise RuntimeError("no stable leader")
            leader = next((p for p in parts if p.is_leader()), leader)
            time.sleep(0.01)

    t0 = time.perf_counter()
    step = 1 if batch <= 1 else batch
    for lo in range(0, len(payloads), step):
        commit(payloads[lo:lo + step])
    return time.perf_counter() - t0


def _wal_legs(tmp: str, entries: int, payload: bytes) -> dict:
    from ..cluster.wal import Wal

    w1 = Wal(os.path.join(tmp, "percmd.wal"), sync=True)
    t0 = time.perf_counter()
    for i in range(1, entries + 1):
        w1.append(i, 1, payload)
    per_s = time.perf_counter() - t0
    w1.close()
    w2 = Wal(os.path.join(tmp, "batch.wal"), sync=True)
    t0 = time.perf_counter()
    w2.append_batch([(i, 1, payload) for i in range(1, entries + 1)])
    batch_s = time.perf_counter() - t0
    w2.close()
    return {
        "wal_append_per_entry_ms": round(per_s * 1e3, 2),
        "wal_append_batch_ms": round(batch_s * 1e3, 2),
        "wal_batch_speedup": round(per_s / batch_s, 1) if batch_s else None,
    }


def run(entries: int = 384, n_nodes: int = 3, payload: int = 64,
        repeat: int = 1, batch_sizes=BATCH_SIZES) -> dict:
    """One A/B pass; `repeat` keeps the best (min) wall time per mode —
    consensus timings on a shared VM are noisy upward only."""
    data = os.urandom(max(1, payload))
    payloads = [data] * entries
    out = {"entries": entries, "nodes": n_nodes, "payload_bytes": payload}

    def best(fn) -> float:
        return min(fn() for _ in range(max(1, repeat)))

    tmp = tempfile.mkdtemp(prefix="nebula_write_bench_")
    try:
        out.update(_wal_legs(tmp, entries, data))

        def timed(batch):
            d = tempfile.mkdtemp(dir=tmp)
            parts, leader = _mk_cluster(d, n_nodes)
            try:
                return _drive(parts, leader, payloads, batch)
            finally:
                for p in parts:
                    p.stop()

        per_cmd_s = best(lambda: timed(0))
        out["per_command_s"] = round(per_cmd_s, 3)
        out["per_command_eps"] = round(entries / per_cmd_s, 1)
        for b in batch_sizes:
            s = best(lambda b=b: timed(b))
            out[f"grouped_{b}_s"] = round(s, 3)
            out[f"grouped_{b}_eps"] = round(entries / s, 1)
            out[f"grouped_{b}_speedup"] = round(per_cmd_s / s, 2)
        out["headline_speedup_64"] = out.get("grouped_64_speedup")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--entries", type=int, default=384)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--payload", type=int, default=64)
    ap.add_argument("--repeat", type=int, default=1)
    args = ap.parse_args(argv)
    print(json.dumps(run(args.entries, args.nodes, args.payload,
                         args.repeat), indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
