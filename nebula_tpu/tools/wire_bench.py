"""wire-bench — microbenchmark of the columnar wire codec (ISSUE 2).

Times the three legs a columnar result pays between the engine and a
client, on synthetic data shaped like the north-star GO result (int64
dst + int64 w columns):

  encode      to_wire(ColumnarDataSet) — must be O(1) per numeric
              column (a memoryview of the numpy buffer, no copy)
  decode      from_wire of the encoded form (np.frombuffer, zero-copy)
  roundtrip   a real RPC round trip over localhost through the
              pipelined client (frame build, socket, recv_into, blob
              graft) — the `client_wire_ms` of bench.py config 6, in
              isolation

Also times the row-form DataSet columnar fast path (type-scan +
np.array) against the per-cell JSON encoding it replaces, so a
regression in either path shows up as a ratio, not a feeling.

    python -m nebula_tpu.tools.wire_bench [--rows 2000000] [--repeat 5]

Emits one JSON object on stdout (CI-diffable, like bench.py's
BENCH_DETAIL).
"""
from __future__ import annotations

import argparse
import json
import statistics
import time


def _t(fn, repeat: int) -> float:
    """Median seconds of fn() over `repeat` runs (first run warms)."""
    fn()
    lat = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        lat.append(time.perf_counter() - t0)
    return statistics.median(lat)


def run(rows: int, repeat: int) -> dict:
    import numpy as np

    from ..cluster.rpc import RpcClient, RpcServer
    from ..core import wire
    from ..core.value import ColumnarDataSet, DataSet

    d = (np.arange(rows, dtype=np.int64) * 2654435761) & 0x7FFFFFFF
    w = np.arange(rows, dtype=np.int64) % 100
    cds = ColumnarDataSet(["d", "w"], [d, w])
    nbytes = int(d.nbytes + w.nbytes)

    enc_s = _t(lambda: wire.to_wire(
        ColumnarDataSet(["d", "w"], [d, w])), repeat)
    encoded = wire.to_wire(cds)
    dec_s = _t(lambda: wire.from_wire(encoded), repeat)

    srv = RpcServer()
    srv.register("result", lambda p: {"data": wire.to_wire(
        ColumnarDataSet(["d", "w"], [d, w]))})
    srv.start()
    cl = RpcClient(srv.host, srv.port, timeout=120.0)
    try:
        rt_s = _t(lambda: wire.from_wire(cl.call("result")["data"]),
                  repeat)
    finally:
        cl.close()
        srv.stop()

    # row-form fast path vs the per-cell encoding it replaces
    row_rows = min(rows, 200_000)
    ds_rows = [[int(a), int(b)] for a, b in
               zip(d[:row_rows].tolist(), w[:row_rows].tolist())]
    rowds = DataSet(["d", "w"], ds_rows)
    col_s = _t(lambda: wire.to_wire(rowds), repeat)
    percell_s = _t(lambda: {"@t": "dataset", "cols": ["d", "w"],
                            "rows": [[wire.to_wire(c) for c in r]
                                     for r in ds_rows]}, repeat)

    got = wire.from_wire(wire.to_wire(cds))
    assert np.array_equal(np.asarray(got.column_array("d")), d)

    return {
        "rows": rows,
        "payload_mb": round(nbytes / 1e6, 1),
        "encode_ms": round(enc_s * 1e3, 3),
        "decode_ms": round(dec_s * 1e3, 3),
        "roundtrip_ms": round(rt_s * 1e3, 2),
        "roundtrip_gbps": round(nbytes / rt_s / 1e9, 2),
        "rowform_rows": row_rows,
        "rowform_columnar_ms": round(col_s * 1e3, 2),
        "rowform_percell_ms": round(percell_s * 1e3, 2),
        "rowform_speedup": round(percell_s / col_s, 2) if col_s else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--repeat", type=int, default=5)
    args = ap.parse_args(argv)
    print(json.dumps(run(args.rows, args.repeat), indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
