"""overload-bench — goodput-vs-offered-load curve over a live
3-replica cluster (ISSUE 10; the overload mirror of chaos_bench.py).

The headline question of admission control: when offered load exceeds
capacity, does goodput COLLAPSE (every statement times out together)
or DEGRADE (admitted statements finish near peak rate, the excess is
shed fast with a structured `E_OVERLOAD` + retry-after, and control
statements still answer)?

Method: stand up a LocalCluster (1 metad / 3 storaged / 1 graphd),
calibrate 1× capacity with a closed-loop probe, then sweep offered
load at 1× / 2× / 4× via concurrency multiplication (each level runs
`calibration threads × level` closed-loop workers — the standard way
to push a blocking client past saturation).  Admission is armed for
the sweep (`max_running_queries`, `admission_queue_capacity`,
`rpc_server_inbox_capacity`); a control thread issues SHOW QUERIES
throughout and its latency is reported separately (the priority lane's
proof).  Per level:

  goodput_qps      statements that returned rows, per second
  shed             E_OVERLOAD results + admission/inbox shed counters
  admitted_p99_ms  latency of successful statements
  control_p99_ms   SHOW QUERIES latency DURING the level's saturation
  hints_ok         every observed E_OVERLOAD carried retry_after_ms

Usage:
    python -m nebula_tpu.tools.overload_bench
    python -m nebula_tpu.tools.overload_bench --persons 4000 --duration 5

Emits one JSON object on stdout; bench.py folds the curve into its
`overload` block (goodput_4x_vs_1x is the acceptance number: ≥ 0.7).
"""
from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional


def _percentile(sorted_xs: List[float], p: float) -> float:
    if not sorted_xs:
        return 0.0
    return sorted_xs[min(len(sorted_xs) - 1,
                         int(len(sorted_xs) * p / 100.0))]


def _stat_totals(prefixes) -> Dict[str, float]:
    from nebula_tpu.utils.stats import stats
    snap = stats().snapshot()
    out = {}
    for pfx in prefixes:
        out[pfx] = sum(v for k, v in snap.items()
                       if k.startswith(pfx) and not k.endswith("_us")
                       and ".sum" not in k and ".count" not in k
                       and ".bucket" not in k)
    return out


_SHED_COUNTERS = ("admission_shed", "overload_server_rejections")


class _LevelResult:
    def __init__(self):
        self.lats: List[float] = []
        self.ok = 0
        self.shed_results = 0
        self.errors: List[str] = []
        self.hints_missing = 0
        self.lock = threading.Lock()


def _worker(cluster, space: str, stmt_of, duration_s: float, wid: int,
            res: _LevelResult):
    from nebula_tpu.utils.admission import is_overload, parse_retry_after
    try:
        cl = cluster.client()
        cl.execute(f"USE {space}")
    except Exception as ex:  # noqa: BLE001 — saturation may refuse conns
        with res.lock:
            res.errors.append(f"connect: {ex!r}")
        return
    end = time.monotonic() + duration_s
    j = 0
    while time.monotonic() < end:
        t0 = time.perf_counter()
        try:
            r = cl.execute(stmt_of(wid, j))
        except Exception as ex:  # noqa: BLE001
            with res.lock:
                res.errors.append(repr(ex))
            break
        dt = time.perf_counter() - t0
        with res.lock:
            if r.error is None:
                res.ok += 1
                res.lats.append(dt)
            elif is_overload(r.error):
                res.shed_results += 1
                if parse_retry_after(r.error) is None:
                    res.hints_missing += 1
            else:
                res.errors.append(r.error)
        j += 1
    try:
        cl.close()
    except Exception:  # noqa: BLE001
        pass


def _control_probe(cluster, stop: threading.Event, out: Dict):
    """SHOW QUERIES every 50ms on its own session — the priority lane
    must answer while the data plane saturates."""
    lats: List[float] = []
    errs = 0
    try:
        cl = cluster.client()
    except Exception:  # noqa: BLE001
        out["control_errors"] = -1
        return
    while not stop.wait(0.05):
        t0 = time.perf_counter()
        try:
            r = cl.execute("SHOW LOCAL QUERIES")
            if r.error is not None:
                errs += 1
            else:
                lats.append(time.perf_counter() - t0)
        except Exception:  # noqa: BLE001
            errs += 1
    try:
        cl.close()
    except Exception:  # noqa: BLE001
        pass
    lats.sort()
    out["control_p50_ms"] = round(_percentile(lats, 50) * 1e3, 2)
    out["control_p99_ms"] = round(_percentile(lats, 99) * 1e3, 2)
    out["control_probes"] = len(lats)
    out["control_errors"] = errs


def run_sweep(persons: int = 1200, degree: int = 5,
              cal_threads: int = 6, duration_s: float = 3.0,
              levels=(1, 2, 4), slots: Optional[int] = None,
              queue_capacity: Optional[int] = None,
              inbox_capacity: int = 0,
              tpu_runtime=None, data_dir: Optional[str] = None) -> dict:
    import numpy as np

    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.admission import admission
    from nebula_tpu.utils.config import get_config

    space = "ovld"
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_overload_")
    cluster = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                           data_dir=tmp, tpu_runtime=tpu_runtime)
    cfg = get_config()
    dyn_keys = ("max_running_queries", "admission_queue_capacity",
                "rpc_server_inbox_capacity", "query_timeout_secs")
    try:
        cl = cluster.client()
        assert cl.execute(
            f"CREATE SPACE {space}(partition_num=8, replica_factor=3, "
            f"vid_type=INT64)").error is None
        cluster.reconcile_storage()
        for q in (f"USE {space}", "CREATE TAG Person(age int)",
                  "CREATE EDGE KNOWS(w int)"):
            assert cl.execute(q).error is None, q
        rng = np.random.default_rng(31)
        B = 400
        for lo in range(0, persons, B):
            vals = ", ".join(f"{v}:({v % 90})"
                             for v in range(lo, min(lo + B, persons)))
            r = cl.execute(f"INSERT VERTEX Person(age) VALUES {vals}")
            assert r.error is None, r.error
        src = rng.integers(0, persons, persons * degree)
        dst = rng.integers(0, persons, persons * degree)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        for lo in range(0, src.size, B):
            vals = ", ".join(
                f"{s}->{d}:({int(s + d) % 100})"
                for s, d in zip(src[lo:lo + B].tolist(),
                                dst[lo:lo + B].tolist()))
            r = cl.execute(f"INSERT EDGE KNOWS(w) VALUES {vals}")
            assert r.error is None, r.error

        def stmt_of(wid: int, j: int) -> str:
            seed = (wid * 131 + j * 17) % persons
            return f"GO FROM {seed} OVER KNOWS YIELD dst(edge) AS d"

        # warm the plan cache / device plane before calibrating
        warm = cluster.client()
        warm.execute(f"USE {space}")
        warm.execute(stmt_of(0, 0))
        warm.close()

        # ---- calibrate 1× capacity: closed loop, admission OFF ------
        cal = _LevelResult()
        ths = [threading.Thread(target=_worker,
                                args=(cluster, space, stmt_of,
                                      duration_s, i, cal))
               for i in range(cal_threads)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        cal_wall = time.perf_counter() - t0
        qps_1x = cal.ok / cal_wall if cal_wall > 0 else 0.0
        assert not cal.errors, cal.errors[:3]

        # ---- arm the overload plane for the sweep -------------------
        n_slots = slots if slots is not None else max(cal_threads, 2)
        n_cap = queue_capacity if queue_capacity is not None \
            else 2 * n_slots
        cfg.set_dynamic_many({
            "max_running_queries": n_slots,
            "admission_queue_capacity": n_cap,
            "rpc_server_inbox_capacity": inbox_capacity,
            # bounded budgets keep a saturated level from running away:
            # queued statements are deadline-evicted, client overload
            # retries stay inside this budget
            "query_timeout_secs": max(duration_s * 2, 5.0),
        })

        out_levels: Dict[str, dict] = {}
        for level in levels:
            res = _LevelResult()
            shed0 = _stat_totals(_SHED_COUNTERS)
            stop = threading.Event()
            ctl: Dict = {}
            ctl_t = threading.Thread(target=_control_probe,
                                     args=(cluster, stop, ctl))
            ctl_t.start()
            n_workers = cal_threads * level
            ths = [threading.Thread(target=_worker,
                                    args=(cluster, space, stmt_of,
                                          duration_s, i, res))
                   for i in range(n_workers)]
            t0 = time.perf_counter()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall = time.perf_counter() - t0
            stop.set()
            ctl_t.join()
            shed1 = _stat_totals(_SHED_COUNTERS)
            res.lats.sort()
            attempts = res.ok + res.shed_results + len(res.errors)
            row = {
                "workers": n_workers,
                "wall_s": round(wall, 2),
                "attempted_qps": round(attempts / wall, 1) if wall else 0,
                "goodput_qps": round(res.ok / wall, 1) if wall else 0,
                "ok": res.ok,
                "shed_results": res.shed_results,
                "shed_counters": {
                    k: int(shed1[k] - shed0[k]) for k in shed1},
                "other_errors": len(res.errors),
                "error_sample": res.errors[:3],
                "admitted_p50_ms": round(
                    _percentile(res.lats, 50) * 1e3, 2),
                "admitted_p99_ms": round(
                    _percentile(res.lats, 99) * 1e3, 2),
                # the E_OVERLOAD contract: every shed carries a hint
                "hints_ok": res.hints_missing == 0,
            }
            row.update(ctl)
            out_levels[f"{level}x"] = row

        g1 = out_levels[f"{levels[0]}x"]["goodput_qps"]
        g4 = out_levels[f"{levels[-1]}x"]["goodput_qps"]
        return {
            "persons": persons,
            "degree": degree,
            "replica_factor": 3,
            "statement": "1-hop GO (small-query admission shape)",
            "calibration": {"threads": cal_threads,
                            "qps": round(qps_1x, 1),
                            "p50_ms": round(
                                _percentile(sorted(cal.lats), 50) * 1e3,
                                2)},
            "slots": n_slots,
            "queue_capacity": n_cap,
            "inbox_capacity": inbox_capacity,
            "duration_per_level_s": duration_s,
            "levels": out_levels,
            # the acceptance number: offered 4×, goodput vs the 1× level
            "goodput_4x_vs_1x": round(g4 / g1, 3) if g1 else None,
        }
    finally:
        with cfg.lock:
            for k in dyn_keys:
                cfg.dynamic_layer.pop(k, None)
        admission().reset()
        cluster.stop()
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--persons", type=int, default=1200)
    ap.add_argument("--degree", type=int, default=5)
    ap.add_argument("--threads", type=int, default=6,
                    help="calibration (1×) closed-loop threads")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per load level")
    ap.add_argument("--slots", type=int, default=None,
                    help="max_running_queries for the sweep")
    ap.add_argument("--queue-capacity", type=int, default=None)
    ap.add_argument("--inbox-capacity", type=int, default=0)
    args = ap.parse_args(argv)
    print(json.dumps(run_sweep(
        persons=args.persons, degree=args.degree,
        cal_threads=args.threads, duration_s=args.duration,
        slots=args.slots, queue_capacity=args.queue_capacity,
        inbox_capacity=args.inbox_capacity), indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
